"""Scheduler configuration + cluster constants.

Role parity: reference ``scheduler/config/config.go`` + ``constants.go``
(candidate/filter limits :33-37, retry limits :63-71).
"""

from __future__ import annotations

from dataclasses import dataclass, field


# The candidate set doubles the reference's 4
# (scheduler/config/constants.go:33-37): piece-availability metadata flows
# ONLY along parent->child sync streams, so the candidate limit is the
# mesh's information fan-in. At 4 a cold fan-out's piece knowledge diffuses
# slower than the origin trickles and children starve into seed pulls; at 8
# a fresh piece is one peer-hop from most of a 16-child swarm. Transfers
# stay bounded separately (upload-server concurrency), so extra parents
# cost metadata streams, not bandwidth.
CANDIDATE_PARENT_LIMIT = 8
FILTER_PARENT_LIMIT = 15

# reference scheduler/config/constants.go:63-71
DEFAULT_BACK_SOURCE_CONCURRENT = 200
RETRY_LIMIT = 5                  # schedule retries before back-source verdict
RETRY_BACK_SOURCE_LIMIT = 4      # failed reports before NeedBackSource

PEER_TTL_S = 24 * 3600.0
TASK_TTL_S = 24 * 3600.0
HOST_TTL_S = 6 * 3600.0
PEER_GC_INTERVAL_S = 60.0


@dataclass
class SeedPeerAddr:
    """A seed daemon the scheduler may trigger (config- or manager-sourced)."""

    host_id: str = ""
    ip: str = "127.0.0.1"
    rpc_port: int = 0
    download_port: int = 0


@dataclass
class SchedulerConfig:
    listen_ip: str = "0.0.0.0"
    advertise_ip: str = "127.0.0.1"
    port: int = 0                          # 0 = ephemeral
    cluster_id: int = 1
    algorithm: str = "default"             # default | nt | ml
    seed_peers: list[SeedPeerAddr] = field(default_factory=list)
    candidate_parent_limit: int = CANDIDATE_PARENT_LIMIT
    filter_parent_limit: int = FILTER_PARENT_LIMIT
    # per-host concurrent-upload defaults applied when a daemon announces 0
    # ("auto"); slots ride DAG edges, so this is max direct children per
    # node of the distribution tree (see resource.Host)
    peer_upload_limit: int = 0             # 0 -> Host.DEFAULT_PEER_UPLOAD_LIMIT
    seed_upload_limit: int = 0             # 0 -> Host.DEFAULT_SEED_UPLOAD_LIMIT
    # relay-tree shaping (0 = off, the exact pre-relay scoring path —
    # dfbench's baseline schedule_digest stays byte-identical). When > 0,
    # a parent already feeding this many direct children in the task DAG
    # is demoted behind under-cap candidates, so a cold fan-out forms
    # ICI-near relay CHAINS of depth ~log_fanout(N) instead of a star on
    # the seed whose one uplink then sets the pod's cold-start makespan
    # (see Scheduling._relay_shape; cut-through serving makes the chain
    # hops overlap, daemon/relay.py).
    relay_fanout: int = 0
    # per-class relay fan-out slot caps (QoS, active only while
    # relay_fanout > 0): how many of a parent's relay-tree child slots a
    # child of each class may claim. Unlisted classes use relay_fanout
    # itself. The default caps ``bulk`` at half the fan-out (floor 1), so
    # a bulk herd's cold start builds a NARROWER, deeper tree and leaves
    # breadth slots — the low-latency positions near the seed — for
    # critical/standard children.
    class_fanout_caps: dict = field(default_factory=dict)
    # bulk-dispatch preemption (QoS): a waiting ``critical`` child with no
    # legal parent may evict one ``bulk`` child's edge from a slot-full
    # content holder (Scheduling.preempt_for; the ruling rides the
    # decision ledger). Off = the exact pre-QoS patience path.
    qos_preemption: bool = True
    # pod-wide peer quarantine (scheduler/quarantine.py): hard corrupt
    # evidence (typed PieceResult.fail_code verdicts, cross-task) walks a
    # host down healthy -> suspect -> quarantined -> probation. Disabled
    # = the exact pre-quarantine scoring/filter path (dfbench digest
    # gate). Thresholds are decayed-verdict mass, not raw counts.
    quarantine_enabled: bool = True
    quarantine_corrupt_threshold: float = 3.0
    quarantine_halflife_s: float = 600.0
    # quarantined -> probation after this long without fresh evidence;
    # probation exposes the host to at most quarantine_probe_children
    # concurrent children and quarantine_probe_successes clean pieces
    # climb it back to healthy without an operator
    quarantine_probation_delay_s: float = 30.0
    quarantine_probe_successes: int = 2
    quarantine_probe_children: int = 1
    # distinct reporting hosts required before corrupt evidence may
    # QUARANTINE (one forging child must not evict honest parents —
    # a single reporter tops out at suspect)
    quarantine_min_reporters: int = 2
    # cross-pod federation (scheduler/federation.py, ROADMAP item 2):
    # per-pod seed election + DCN routing policy — cross-pod parents are
    # legal only for a pod's elected seeds, so the distribution chain is
    # origin -> pod-seed (one DCN copy per pod) -> in-pod ICI relay.
    # Disabled (default) = the exact pre-federation filter path: the
    # single-pod schedule_digest stays byte-identical (dfbench gate).
    federation_enabled: bool = False
    # elected seeds per (task, pod): >1 spreads the pod's DCN ingest and
    # survives one seed death without a re-election stall
    federation_seeds_per_pod: int = 1
    # sharded-checkpoint shard affinity (scheduler/shard_affinity.py,
    # ROADMAP item 3): at register, a sharded task's requested shards
    # are split disjointly across the co-located replicas requesting
    # them (RegisterResult.assigned_shards, decision_kind=shard) so the
    # group fetches ONE tree copy and swaps the rest over ICI. Only
    # activates on requests that carry UrlMeta.shards; parent scoring is
    # untouched either way (dfbench digest gate). Disabled = no
    # assignment ever rides a register — every daemon tree-fetches its
    # whole requested set.
    shard_affinity_enabled: bool = True
    retry_limit: int = RETRY_LIMIT
    retry_back_source_limit: int = RETRY_BACK_SOURCE_LIMIT
    back_source_concurrent: int = DEFAULT_BACK_SOURCE_CONCURRENT
    # scheduler-wide cap on concurrent back-source peers across ALL tasks
    # (reference DefaultSchedulerBackToSourceCount = 200,
    # scheduler/config/constants.go:63): origin/WAN egress is a cluster
    # resource, not a per-task one. Counted per priority CLASS — lower-
    # priority holders don't block a higher-priority requester, which is
    # how a LEVEL0 application preempts LEVEL6 traffic's origin slots.
    back_source_total: int = 200
    peer_ttl_s: float = PEER_TTL_S
    task_ttl_s: float = TASK_TTL_S
    host_ttl_s: float = HOST_TTL_S
    gc_interval_s: float = PEER_GC_INTERVAL_S
    manager_addresses: list[str] = field(default_factory=list)
    trainer_address: str = ""
    keepalive_interval_s: float = 30.0
    records_dir: str = ""                  # download-record JSONL ("" = memory-only)
    tracing_jsonl: str = ""                # span export path ("" = disabled)
    tracing_otlp: str = ""                 # OTLP/HTTP collector endpoint
    plugin_dir: str = ""                   # df_plugin_*.py extensions
    # fleet mTLS toward security-enabled seed daemons: enroll via the
    # manager with this issuance token (daemon SecurityConfig parity)
    security_issue_token: str = ""
    security_ca_cert: str = ""             # pinned fleet CA for enrollment
    train_upload_interval_s: float = 60.0  # records -> trainer cadence
    model_refresh_interval_s: float = 60.0  # manager -> ml evaluator cadence
    workdir: str = ""
    # crash-survivable control-plane state (scheduler/statestore.py):
    # the quarantine ladder, shard-affinity memos, federation seed
    # elections, and tenant quotas journal to
    # <statestore_dir>/scheduler_state.json (tmp+fsync+rename) on this
    # cadence PLUS every covered transition (event-driven dirty mark);
    # on boot the snapshot restores before the first ruling and daemons
    # seeing the epoch change re-announce held content. "" = durability
    # off: the pre-PR amnesiac brain (and the exact pre-PR boot path).
    statestore_dir: str = ""
    statestore_interval_s: float = 30.0
    # failover handoff: on graceful stop/demotion, park the exported
    # quarantine/affinity summary with the manager (the config plane of
    # record) so the ring successor can import it — warmed to at most
    # `suspect` (the PR 12 anti-slander ceiling). Needs manager_addresses.
    statestore_handoff: bool = True
    # fleet pulse plane (scheduler/fleetpulse.py): ingest announce-borne
    # pulse digests, run the EWMA anomaly detector, keep per-daemon ring
    # time series + incident bundles at GET /debug/fleet. Strictly
    # observational — disabling it (False) changes no ruling.
    fleetpulse_enabled: bool = True
