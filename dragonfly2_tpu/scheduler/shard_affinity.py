"""Scheduler shard affinity: disjoint tree-fetch assignment per replica.

Role parity: none in the reference — Dragonfly2 schedules whole files.
The sharded-checkpoint rollout (ROADMAP item 3) puts many co-located
replicas behind one distribution tree, all requesting the SAME shard
subset of a multi-GB checkpoint. Letting each pull everything from the
tree costs ``replicas x shard_bytes`` over the thin feeder links while
4.8 TB/s of ICI sits idle. This module is the ``sharded=`` arm of
``Scheduling``: at register, each peer's requested shards are split
DISJOINTLY across the co-located replicas requesting them (rendezvous
hashing, ``common.sharding.split_affinity``), the peer fetches only its
assigned subset from the tree, and the rest arrives by ICI-near P2P swap
(the daemon's swap-hold machinery; tree fallback bounded by
``piece_dispatcher.SWAP_HOLD_S`` when a partner dies). Pod-wide cost then
approaches ``shard_bytes / bisection_bandwidth`` instead of
``shard_bytes x replicas / one_NIC``.

Co-location = same pod (``tpu.topology.pod_id``: one slice == one ICI
domain); pod-less hosts group under "" — a plain cluster still splits
the tree fetch, it just swaps over whatever links it has. Every ruling
is a ``decision_kind=shard`` ledger row, so who-fetches-what is
offline-replayable like every other scheduler decision.

Like ``PodFederation``, everything here is synchronous dict work at
register cadence — nothing rides the per-piece hot path.
"""

from __future__ import annotations

import logging

from ..common.metrics import REGISTRY
from ..common.sharding import split_affinity
from ..tpu.topology import pod_id

log = logging.getLogger("df.sched.shards")

_assignments = REGISTRY.counter(
    "df_shard_assignments_total",
    "shard-affinity rulings, by outcome (assigned = a disjoint subset "
    "ruled, solo = the peer is its group's only requester so it fetches "
    "everything)", ("result",))


class ShardAffinity:
    """Per-(task, group) shard-request membership + disjoint assignment.

    The split is a pure function of {who requests which shards} —
    rendezvous hashing needs no stored partition, so a replay (or a
    second scheduler behind the ring) rules identically. Membership only
    ever helps: a peer assigned a subset before its replicas registered
    simply fetches more from the tree than the steady state would; the
    next refresh of the late joiners sees the full membership and the
    split tightens. Re-rulings for a known peer are emitted only when
    its subset CHANGED, so the ledger sees churn, not cadence."""

    MAX_TASKS = 4096          # (task, group) memo bound, federation-style

    def __init__(self, *, sink=None):
        self.sink = sink      # decision-ledger hook: callable(row dict)
        # (task_id, group) -> {peer_id: requested shard names (ordered)}
        self._requests: dict[tuple[str, str], dict[str, list[str]]] = {}
        # (task_id, group, peer_id) -> last emitted assignment
        self._last: dict[tuple[str, str, str], list[str]] = {}
        self._seq = 0

    @staticmethod
    def group_of(topology) -> str:
        """The co-location group a peer swaps within: its pod (ICI
        bandwidth domain); "" for pod-less hosts (one flat group)."""
        return pod_id(topology)

    def assign(self, *, task_id: str, peer_id: str, host_id: str,
               topology, requested: list[str]) -> list[str]:
        """Rule this peer's tree-fetch subset of ``requested``. Owners
        are rendezvous-hashed per shard over the HOSTS currently
        requesting that shard in the peer's group — disjoint across the
        group by construction, minimal movement as membership churns."""
        group = self.group_of(topology)
        key = (task_id, group)
        reqs = self._requests.get(key)
        if reqs is None:
            if len(self._requests) >= self.MAX_TASKS:
                oldest = next(iter(self._requests))
                del self._requests[oldest]
                self._last = {k: v for k, v in self._last.items()
                              if (k[0], k[1]) != oldest}
            reqs = self._requests[key] = {}
        reqs[host_id] = list(requested)
        # group shards by their REQUESTER SET and balance within each:
        # co-located replicas requesting the same shards (the rollout
        # shape) each get an exact 1/n slice; shards requested by only
        # some members are balanced among exactly those
        by_sig: dict[tuple[str, ...], list[str]] = {}
        for name in requested:
            owners = tuple(sorted(hid for hid, names in reqs.items()
                                  if name in names))
            by_sig.setdefault(owners, []).append(name)
        mine: set[str] = set()
        for owners, group_names in by_sig.items():
            split = split_affinity(group_names, owners)
            mine.update(n for n, o in split.items() if o == host_id)
        assigned = [n for n in requested if n in mine]
        solo = len(reqs) == 1
        _assignments.labels("solo" if solo else "assigned").inc()
        memo_key = (task_id, group, host_id)
        if self._last.get(memo_key) != assigned:
            self._last[memo_key] = assigned
            self._emit(task_id=task_id, peer_id=peer_id, host_id=host_id,
                       group=group, requested=requested,
                       assigned=assigned, members=len(reqs))
        return assigned

    def _emit(self, *, task_id: str, peer_id: str, host_id: str,
              group: str, requested: list[str], assigned: list[str],
              members: int) -> None:
        log.info("shard affinity: %s gets %d/%d requested shards "
                 "(group %s, %d replicas)", host_id, len(assigned),
                 len(requested), group or "<flat>", members)
        if self.sink is None:
            return
        self._seq += 1
        self.sink({
            "kind": "decision",
            "decision_id": f"s{self._seq:08d}.{peer_id[-12:]}",
            "decision_kind": "shard",
            "task_id": task_id,
            "peer_id": peer_id,
            "host_id": host_id,
            "group": group,
            "group_members": members,
            "requested": list(requested),
            "assigned": list(assigned),
            "swap": [n for n in requested if n not in assigned],
            "candidates": [],
            "excluded": [],
            "chosen": list(assigned),
        })

    def drop_task(self, task_id: str) -> None:
        """Task GC (``Resource.on_task_evict``): request tables die with
        the task."""
        for key in [k for k in self._requests if k[0] == task_id]:
            del self._requests[key]
        self._last = {k: v for k, v in self._last.items()
                      if k[0] != task_id}

    def forget_host(self, host_id: str) -> None:
        """Host leave/GC: its shard requests stop anchoring ownership —
        the next register/refresh of a surviving replica re-rules the
        dead host's shards onto the living (rendezvous moves only
        those). The daemon-side swap hold covers the window in between:
        a shard whose owner died is tree-pulled after the bounded hold.
        Its assignment memos go too: a re-registration must emit a fresh
        ledger row even when it re-rules the identical subset (and dead
        hosts must not accumulate memo entries until task GC)."""
        for reqs in self._requests.values():
            reqs.pop(host_id, None)
        self._last = {k: v for k, v in self._last.items()
                      if k[2] != host_id}

    def state_bytes(self) -> int:
        """Bytes of shard-affinity state (request tables, assignment
        memos) for the /debug/ctrl bytes-per-peer accounting. Deep
        sizeof walk — snapshot cadence only, never on a ruling path."""
        from ..common.sizeof import deep_sizeof
        seen: set = set()
        return sum(deep_sizeof(o, seen)
                   for o in (self._requests, self._last))

    def describe(self) -> dict:
        return {
            "tasks": {f"{tid[:12]}/{group or '<flat>'}":
                      {hid: len(names) for hid, names in reqs.items()}
                      for (tid, group), reqs in
                      sorted(self._requests.items())},
        }

    # -- durable state (scheduler/statestore.py) -------------------------

    def export_state(self) -> dict:
        """Request membership + assignment memos, tuple keys flattened to
        JSON-safe lists. The split itself is a pure function of the
        request tables (rendezvous hashing stores no partition), so
        carrying membership across a crash is exactly what makes the
        restarted brain re-rule the SAME subsets — the ≥90 % stickiness
        the recovery bench gates."""
        return {
            "seq": self._seq,
            "requests": [[tid, group, reqs]
                         for (tid, group), reqs in self._requests.items()],
            "last": [[tid, group, hid, assigned]
                     for (tid, group, hid), assigned in self._last.items()],
        }

    def restore(self, state: dict) -> int:
        """Rebuild from :meth:`export_state` output. Insertion order is
        preserved (the MAX_TASKS eviction order), memos silently — a
        restored memo means the first post-restart register of an
        unchanged requester set emits NO fresh ledger row, which is the
        point: recovery observes, it does not re-rule."""
        restored = 0
        for tid, group, reqs in (state.get("requests") or ()):
            self._requests[(tid, group)] = {
                hid: list(names) for hid, names in reqs.items()}
            restored += 1
        for tid, group, hid, assigned in (state.get("last") or ()):
            self._last[(tid, group, hid)] = list(assigned)
        self._seq = max(self._seq, int(state.get("seq", 0)))
        return restored
