"""Scheduler announcer: ship records to the trainer, pull models back.

Role parity: reference ``scheduler/announcer/announcer.go:142-235`` — the
interval loop that gzips download + networktopology datasets and streams
them to the trainer's ``Train`` RPC. TPU-native addition (the half the
reference never built): a model-refresh loop that pulls the latest fitted
``bandwidth_mlp`` from the manager registry and hot-binds it into the
``ml`` evaluator, so scheduling decisions improve while the scheduler runs.
"""

from __future__ import annotations

import asyncio
import gzip
import json
import logging
import socket
import time

from ..common.metrics import REGISTRY
from ..idl.messages import GetModelRequest, TrainRequest
from ..rpc.client import Channel, ServiceClient
from ..trainer.features import MLP_MODEL_NAME

log = logging.getLogger("df.sched.announcer")

TRAINER_SERVICE = "df.trainer.Trainer"
UPLOAD_CHUNK_BYTES = 1 << 20
MAX_REFUSALS_REMEMBERED = 8         # rollout-provenance journal bound

_rollouts_total = REGISTRY.counter(
    "df_ml_model_rollouts_total",
    "model versions successfully bound into the live serving path",
    ("model",))
_refused_total = REGISTRY.counter(
    "df_ml_model_refused_total",
    "model blobs refused wholesale at bind time (garbage bytes, stale "
    "feature schema, non-finite weights)", ("model",))


class SchedulerAnnouncer:
    """Owned by ``Scheduler``; both loops are optional and independent:
    records upload needs ``trainer_address``, model refresh needs the
    manager link + an MLEvaluator to feed."""

    def __init__(self, scheduler, *, upload_interval_s: float = 60.0,
                 refresh_interval_s: float = 60.0):
        self.scheduler = scheduler
        self.upload_interval_s = upload_interval_s
        self.refresh_interval_s = refresh_interval_s
        self._tasks: list[asyncio.Task] = []
        self._trainer_channel: Channel | None = None
        self.model_version = ""        # newest MLP version seen (served OR
        self.gnn_version = ""          # refused) — the if_none_match cursor
        self.model_bound_at = 0.0      # wall clock of the last MLP bind
        self.model_metrics: dict = {}  # registry metrics of the served MLP
        self.refused: dict[str, str] = {}   # version -> bind refusal reason
        self._last_topo_key = 0        # hash of last uploaded topo snapshot

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self.scheduler.cfg.trainer_address and \
                self.scheduler.service.records is not None:
            self._tasks.append(loop.create_task(self._upload_loop()))
        # the refresh loop feeds BOTH the ml evaluator (MLP) and the
        # topology store's imputer (GNN) — nt schedulers without an
        # MLEvaluator still want the imputer
        if self.scheduler.manager is not None:
            self._tasks.append(loop.create_task(self._refresh_loop()))

    def _evaluator(self):
        from .evaluator_ml import MLEvaluator
        ev = self.scheduler.scheduling.evaluator
        return ev if isinstance(ev, MLEvaluator) else None

    # -- records upload ------------------------------------------------

    def _trainer_client(self) -> ServiceClient:
        if self._trainer_channel is None:
            self._trainer_channel = Channel(self.scheduler.cfg.trainer_address)
        return ServiceClient(self._trainer_channel, TRAINER_SERVICE)

    async def _upload_loop(self) -> None:
        while True:
            await asyncio.sleep(self.upload_interval_s)
            try:
                await self.upload_once()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - trainer may be away
                log.debug("records upload failed: %s", exc)

    async def upload_once(self) -> bool:
        """One gzip'd upload of everything buffered; False if nothing to send.
        Public so tests/benches can force a cycle without waiting."""
        records = self.scheduler.service.records
        rows = records.drain() if records is not None else []
        topo_rows = self.scheduler.topo.snapshot_rows()
        # the topology snapshot is state, not a stream: re-sending an
        # unchanged snapshot every interval would duplicate every edge in
        # the trainer's spool and skew the GNN fit
        topo_key = hash(json.dumps(topo_rows, sort_keys=True))
        if topo_key == self._last_topo_key:
            topo_rows = []
        if not rows and not topo_rows:
            return False
        hostname = socket.gethostname()
        ip = self.scheduler.cfg.advertise_ip
        cluster_id = self.scheduler.cfg.cluster_id

        def compress(payload):
            return gzip.compress(
                "\n".join(json.dumps(r) for r in payload).encode())

        # serialize+compress off the event loop — tens of MB of JSON inline
        # would stall every scheduling RPC for the duration
        blobs = {dataset: await asyncio.to_thread(compress, payload)
                 for dataset, payload in (("download", rows),
                                          ("networktopology", topo_rows))
                 if payload}

        async def chunks():
            for dataset, blob in blobs.items():
                for off in range(0, len(blob), UPLOAD_CHUNK_BYTES):
                    yield TrainRequest(
                        hostname=hostname, ip=ip, cluster_id=cluster_id,
                        dataset=dataset,
                        chunk=blob[off:off + UPLOAD_CHUNK_BYTES])
            yield TrainRequest(hostname=hostname, ip=ip,
                               cluster_id=cluster_id, dataset="download",
                               done=True)

        try:
            resp = await self._trainer_client().stream_unary(
                "Train", chunks(), timeout=300.0)
        except Exception:
            # trainer away: put the interval's rows back so the next cycle
            # retries instead of silently losing training data. Delivery is
            # at-least-once — a timeout AFTER the trainer consumed the
            # stream re-sends these rows, which the fit tolerates (dupes are
            # a mild reweighting, loss is a hole in the dataset).
            if records is not None:
                records.requeue(rows)
            raise
        if topo_rows:
            self._last_topo_key = topo_key
        log.info("records uploaded: %d download + %d topology rows -> %s",
                 len(rows), len(topo_rows),
                 resp.model_version or "(no new model)")
        return True

    # -- model refresh -------------------------------------------------

    async def _refresh_loop(self) -> None:
        while True:
            try:
                await self.refresh_model_once()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - registry may be away
                log.debug("model refresh failed: %s", exc)
            await asyncio.sleep(self.refresh_interval_s)

    async def refresh_model_once(self) -> bool:
        """Pull the latest models; True if a new MLP version was bound.
        The topology GNN rides the same refresh: bound into the
        TopologyStore as an RTT imputer for unprobed pairs so nt/ml
        scoring stops treating them as unknowable."""
        if self.scheduler.manager is None:
            return False
        try:
            # best-effort and independent: a bad GNN artifact must not
            # starve MLP refresh for every future cycle
            await self._refresh_gnn_once()
        except Exception as exc:  # noqa: BLE001
            log.warning("topology gnn refresh failed: %s", exc)
        evaluator = self._evaluator()
        if evaluator is None:
            return False
        resp = await self.scheduler.manager.get_model(GetModelRequest(
            name=MLP_MODEL_NAME,
            scheduler_cluster_id=self.scheduler.cfg.cluster_id,
            if_none_match=self.model_version))
        model = resp.model
        if model is None or model.version == self.model_version \
                or not model.data:
            return False
        from ..trainer.serving import make_mlp_infer
        try:
            # deserialize + hash the model blob off-loop: this is the
            # scheduler's serving loop, and a rollout must not stall rulings
            infer = await asyncio.to_thread(make_mlp_infer, model.data)
        except ValueError as exc:
            # bind-time refusal (garbage bytes / stale feature schema /
            # non-finite weights): the evaluator keeps whatever it is
            # serving — worst case the heuristic floor. Remember the
            # refused version so if_none_match skips the full-blob refetch
            # every cycle, and journal the reason for /debug/ctrl
            self.model_version = model.version
            self._remember_refusal(model.version, str(exc))
            _refused_total.labels(MLP_MODEL_NAME).inc()
            log.warning("bandwidth mlp %s refused: %s", model.version, exc)
            return False
        evaluator.infer = infer
        self.model_version = model.version
        self.model_bound_at = time.time()
        self.model_metrics = dict(model.metrics or {})
        _rollouts_total.labels(MLP_MODEL_NAME).inc()
        log.info("ml evaluator now serving %s@%s (final_loss=%s)",
                 model.name, model.version,
                 (model.metrics or {}).get("final_loss"))
        return True

    def _remember_refusal(self, version: str, reason: str) -> None:
        self.refused[version] = reason
        while len(self.refused) > MAX_REFUSALS_REMEMBERED:
            self.refused.pop(next(iter(self.refused)))

    def model_provenance(self) -> dict:
        """Rollout provenance for ``/debug/ctrl``: which brain version is
        ruling (from the evaluator itself, not the fetch cursor — a
        refused blob advances the cursor without being served), when it
        was bound, the registry metrics it shipped with, and every blob
        refused at bind time since startup (bounded journal)."""
        out = {
            "model": MLP_MODEL_NAME,
            "checked_version": self.model_version,
            "bound_at": self.model_bound_at,
            "metrics": {k: self.model_metrics[k]
                        for k in ("version", "rows", "final_loss",
                                  "schema_version")
                        if k in self.model_metrics},
            "refused": dict(self.refused),
            "gnn_version": self.gnn_version,
        }
        ev = self._evaluator()
        if ev is not None:
            out["evaluator"] = ev.health()
        return out

    async def _refresh_gnn_once(self) -> bool:
        topo = getattr(self.scheduler, "topo", None)
        if topo is None:
            return False
        from ..trainer.features import GNN_MODEL_NAME
        resp = await self.scheduler.manager.get_model(GetModelRequest(
            name=GNN_MODEL_NAME,
            scheduler_cluster_id=self.scheduler.cfg.cluster_id,
            if_none_match=self.gnn_version))
        model = resp.model
        if model is None or model.version == self.gnn_version \
                or not model.data:
            return False
        from ..trainer.serving import make_gnn_impute
        try:
            # blob deserialize + digest off-loop, same as the MLP path
            impute = await asyncio.to_thread(make_gnn_impute, model.data)
            topo.bind_imputer(impute)
        except ValueError as exc:
            # schema-gate refusal (stale NODE_FEATURES layout): remember
            # the refused version so if_none_match skips the full-blob
            # refetch every cycle — the trainer's next refit changes the
            # version and gets fetched normally
            self.gnn_version = model.version
            self._remember_refusal(model.version, str(exc))
            _refused_total.labels(GNN_MODEL_NAME).inc()
            log.warning("topology gnn %s refused: %s", model.version, exc)
            return False
        self.gnn_version = model.version
        _rollouts_total.labels(GNN_MODEL_NAME).inc()
        log.info("topology store now imputing with %s@%s",
                 model.name, model.version)
        return True

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._trainer_channel is not None:
            await self._trainer_channel.close()
