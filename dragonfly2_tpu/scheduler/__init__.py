"""Scheduler: the per-cluster brain that builds piece-flow trees.

Role parity: reference ``scheduler/`` (SURVEY §2.4) — resource FSMs over an
in-memory cluster state, candidate filtering + evaluator scoring, seed-peer
triggering, and the register/report gRPC surface. TPU-native: parent scoring
uses real fabric link classes (LOCAL/ICI/DCN/WAN) instead of IDC strings.
"""

from .server import Scheduler, SchedulerConfig  # noqa: F401
