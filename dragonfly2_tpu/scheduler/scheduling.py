"""Scheduling core: pick parents for a peer, or rule back-source.

Role parity: reference ``scheduler/scheduling/scheduling.go`` —
``ScheduleParentAndCandidateParents`` retry loop, ``FindCandidateParents``
(:385) and ``filterCandidateParents`` (:500-570: blocklist, same-peer,
DAG-cycle, bad-node, free-upload-slot checks), with the
``RetryBackToSourceLimit`` arbitration.
"""

from __future__ import annotations

import logging
import random
import time

from ..common import phasetimer
from ..common.metrics import REGISTRY
from ..idl.messages import PeerAddr, PeerPacket
from ..tpu.topology import link_type
from .config import SchedulerConfig
from .evaluator import Evaluator
from .resource import Peer

log = logging.getLogger("df.sched.core")

_filter_excluded = REGISTRY.counter(
    "df_sched_filter_excluded_total",
    "candidate parents excluded by the scheduling filter", ("reason",))
_preemptions = REGISTRY.counter(
    "df_sched_preempt_total",
    "bulk-class parent edges evicted so a waiting critical child could "
    "be scheduled (QoS preemption; each ruling rides the decision "
    "ledger)", ("cls",))

# The filter's exclusion-reason vocabulary. Every reason ``_trace`` fires
# must be registered here and documented in docs/OBSERVABILITY.md — a pod
# herding onto ``no-slots`` or ``bad-node`` shows up in the counter above
# and in decision-row ``excluded`` entries, and an undocumented reason is
# a surface operators cannot read (dflint DF006 decision-vocabulary).
EXCLUSION_REASONS = ("stream-gone", "blocklist", "no-slots", "bad-node",
                     "cycle", "quarantined", "cross-pod")


class Scheduling:
    def __init__(self, cfg: SchedulerConfig, evaluator: Evaluator,
                 quarantine=None, federation=None, sharded=None):
        self.cfg = cfg
        self.evaluator = evaluator
        # quarantine registry (scheduler/quarantine.py). None (default)
        # skips every lookup — the exact pre-quarantine filter path, which
        # is how dfbench's baseline schedule_digest stays byte-identical
        # with the immune system in the tree.
        self.quarantine = quarantine
        # cross-pod federation view (scheduler/federation.py). None
        # (default) skips every lookup — the exact pre-federation filter
        # path, which is how the single-pod schedule_digest stays
        # byte-identical with the federation plane in the tree.
        self.federation = federation
        # shard-affinity arm (scheduler/shard_affinity.py). None
        # (default) = no shard rulings at all: register never attaches
        # an assignment, every daemon fetches its whole requested set
        # from the tree — the exact pre-sharding path (parent scoring is
        # untouched either way, so the schedule digest cannot move; the
        # dfbench gate proves it).
        self.sharded = sharded
        # decision ledger hook: callable(row dict) receiving one
        # ``kind=decision`` row per find/refresh ruling. None (default)
        # skips ALL ledger work — scoring then runs the exact pre-ledger
        # path, which is how dfbench's baseline schedule_digest stays
        # byte-identical with the ledger code in the tree.
        self.decision_sink = None
        self._decision_seq = 0

    def shard_assignment(self, child: Peer,
                         requested: list[str]) -> list[str] | None:
        """Sharded-task register hook: the disjoint tree-fetch subset of
        ``requested`` ruled for this peer (``decision_kind=shard`` rides
        the affinity's own ledger sink). None while the arm is disabled
        — the daemon then treats every requested shard as tree-class."""
        if self.sharded is None or not requested:
            return None
        with phasetimer.ruling("shard"):
            return self.sharded.assign(
                task_id=child.task.id, peer_id=child.id,
                host_id=child.host.id,
                topology=child.host.msg.topology, requested=requested)

    # ------------------------------------------------------------------

    def filter_candidates(self, child: Peer,
                          excluded: list | None = None) -> list[Peer]:
        """All legal parents for ``child``, pre-scoring (the filter half).

        The pool is sampled in random order (reference ``LoadRandomPeers``,
        ``scheduling.go:511``): a deterministic iteration order would hand
        every child the same first-N candidates and herd the fan-out onto
        them."""
        task = child.task
        pool = list(task.peers.values())
        random.shuffle(pool)
        # ONE reachability sweep per ruling: every cycle probe below asks
        # "can child reach parent" over the same frozen DAG (offers only
        # mutate edges via set_parents AFTER the ruling), so walking the
        # child's descendant set once and testing membership replaces
        # O(candidates x DAG) repeated can_reach walks — the filter's
        # former hot spot at 1k+-peer pools (dfbench --pr13 fakepods)
        with phasetimer.phase("dag-walk"):
            cycle_blocked = task.dag.descendants(child.id)
        # hoisted ARMED (the phasetimer overhead contract): the
        # per-candidate quarantine/federation lookups below accumulate a
        # local perf_counter delta and record ONE `exclusion` sample per
        # ruling — a context manager per candidate would put profiler
        # cost inside the pool loop even disarmed
        armed = phasetimer.ARMED
        excl_s = 0.0
        out: list[Peer] = []
        for parent in pool:
            full = len(out) >= self.cfg.filter_parent_limit
            if full and any(p.has_content() for p in out):
                break
            if full and not parent.has_content():
                # truncated but holderless so far: keep scanning for a
                # content-holder only — a fan-out wider than the filter
                # limit could otherwise sample nothing but pieceless
                # siblings and the offer would never name the seed
                continue
            if parent.id == child.id:
                continue
            if parent.stream_gone and not parent.is_done():
                # mid-download peer whose report stream died: almost
                # certainly a dead process — offering it strands children
                # on a parent that will never answer (chaos e2e)
                self._trace(child, parent, "stream-gone", excluded)
                continue
            if child.is_blocked(parent.id):
                self._trace(child, parent, "blocklist", excluded)
                continue
            if not parent.has_content() and parent.is_done():
                # finished-but-empty (failed) peers serve nothing. RUNNING
                # pieceless siblings stay IN: the engine dispatches only to
                # announcers, so they cost one sync stream — and that stream
                # is how a child hears a sibling's first piece the moment it
                # lands. Requiring content here meant every child's initial
                # packet named only the seed, sibling meshing waited on
                # first-piece top-ups, and a congested seed kept the mesh
                # from ever forming (the r04 bimodal collapse: 18s waves
                # with try=51 against the seed while siblings held pieces).
                continue
            # a parent this child is ALREADY assigned to holds its edge (and
            # slot) — re-checking free slots would evict current parents of
            # any loaded host exactly when stickiness matters, and the
            # engine's packet prune would then tear down their sync streams
            if (parent.host.free_upload_slots() <= 0
                    and parent.id not in child.last_offer_ids):
                self._trace(child, parent, "no-slots", excluded)
                continue
            if self.evaluator.is_bad_node(parent):
                self._trace(child, parent, "bad-node", excluded)
                continue
            if self.quarantine is not None:
                t0 = time.perf_counter() if armed else 0.0
                offerable = self.quarantine.offerable(parent.host.id,
                                                      child.id)
                if armed:
                    excl_s += time.perf_counter() - t0
                if not offerable:
                    # pod-wide quarantine (hard corrupt evidence /
                    # self-flag): excluded from offers — and therefore
                    # from relay-tree shaping and every downstream choice
                    # — until the ladder walks the host back through
                    # probation. Probation hosts pass here only within
                    # the bounded probe budget.
                    self._trace(child, parent, "quarantined", excluded)
                    continue
            if self.federation is not None:
                t0 = time.perf_counter() if armed else 0.0
                allowed = self.federation.allows(child, parent)
                if armed:
                    excl_s += time.perf_counter() - t0
                if not allowed:
                    # cross-pod federation: a parent in ANOTHER pod is
                    # legal only for this pod's elected seeds — everyone
                    # else gets the bytes off the pod seed's ICI tree
                    # instead of opening one more DCN stream per child
                    # (the two-level origin -> pod-seed -> ICI relay
                    # chain, ROADMAP item 2)
                    self._trace(child, parent, "cross-pod", excluded)
                    continue
            if parent.id in cycle_blocked:
                # would_cycle(parent, child): the parent is downstream of
                # the child, so the edge would close a loop
                self._trace(child, parent, "cycle", excluded)
                continue
            out.append(parent)
        if armed and (self.quarantine is not None
                      or self.federation is not None):
            phasetimer.record("exclusion", excl_s)
        return out

    @staticmethod
    def _trace(child: Peer, parent: Peer, reason: str,
               excluded: list | None = None) -> None:
        """One exclusion: counted always, collected for the decision row
        when the ledger is armed, logged only at DEBUG."""
        _filter_excluded.labels(reason).inc()
        if excluded is not None:
            excluded.append((parent, reason))
        if log.isEnabledFor(logging.DEBUG):
            log.debug("filter %s: parent %s excluded (%s)",
                      child.id[-12:], parent.id[-12:], reason)

    @staticmethod
    def _ensure_holder(scored: list[Peer], top: list[Peer]) -> list[Peer]:
        """Keep ≥1 content-holder in the offer when one exists: an offer of
        only pieceless siblings (local links can outscore the remote seed)
        would leave the child subscribed to peers that may never announce."""
        if any(p.has_content() for p in top):
            return top
        holder = next((p for p in scored if p.has_content()), None)
        if holder is None:
            return top
        return [*top[:-1], holder] if top else [holder]

    def _relay_shape(self, child: Peer,
                     scored: list[Peer]) -> tuple[list[Peer], dict | None]:
        """Relay-chain shaping (``cfg.relay_fanout`` > 0): demote parents
        already feeding ``relay_fanout`` direct children behind under-cap
        candidates. Score order — which already prefers ICI-near hosts
        via the evaluator's locality term (tpu/topology.py distance) —
        is preserved WITHIN each partition, so the choice among legal
        relays stays the evaluator's; only the fan-out cap is imposed on
        top. Parents this child already holds keep their edge (the same
        stickiness rationale as the no-slots filter): the cap shapes NEW
        edges, it never tears down working ones. Returns the reshaped
        order plus the ledger annotation (None when nothing was capped)
        so every relay ruling stays explainable in the decision row."""
        fanout = self.cfg.relay_fanout
        # per-class slot cap (QoS): bulk children claim fewer of a
        # parent's relay slots, leaving breadth near the seed for
        # foreground classes; default caps bulk at half the fan-out
        cls = getattr(child, "qos_class", "standard")
        if self.cfg.class_fanout_caps:
            fanout = int(self.cfg.class_fanout_caps.get(cls, fanout))
        elif cls == "bulk":
            fanout = max(1, fanout // 2)
        dag = child.task.dag
        mine = child.last_offer_ids
        under: list[Peer] = []
        over: list[Peer] = []
        counts: dict[str, int] = {}
        for p in scored:
            n = len(dag.children(p.id)) if p.id in dag else 0
            counts[p.id] = n
            if n >= fanout and p.id not in mine:
                over.append(p)
            else:
                under.append(p)
        if not over:
            return scored, None
        note = {"fanout": fanout,
                "capped": [p.id for p in over],
                "child_counts": {p.id: counts[p.id] for p in over}}
        return under + over, note

    def preempt_for(self, child: Peer) -> Peer | None:
        """Bulk-dispatch preemption: a waiting ``critical`` child found no
        legal parent because every content holder's upload slots are
        taken — evict ONE ``bulk`` child's edge from the best such holder
        so the next find_parents sees a free slot. The evicted bulk child
        keeps its remaining parents (and its pieces; nothing downloaded is
        lost) and the scheduler's next refresh re-offers it whatever is
        legal then — degradation, not starvation. The ruling is emitted as
        a ``kind=decision`` row (decision_kind="preempt") carrying both
        peers and the freed parent, so fairness stays offline-replayable
        via dfsched. Returns the evicted bulk peer (the caller pushes it
        a fresh packet so its engine actually drops the edge — a
        preemption the daemon never hears about would free nothing) or
        None when no preemptable edge exists."""
        if not self.cfg.qos_preemption \
                or getattr(child, "qos_class", "standard") != "critical":
            return None
        with phasetimer.ruling("preempt"):
            return self._preempt_scan(child)

    def _preempt_scan(self, child: Peer) -> Peer | None:
        task = child.task
        dag = task.dag
        # holders whose slots are exhausted (the no-slots exclusion the
        # filter just fired), best victim edge = a bulk child that joined
        # the parent most recently (it has sunk the least into this edge)
        for parent in task.peers.values():
            if (parent.id == child.id or not parent.has_content()
                    or parent.host.free_upload_slots() > 0
                    or parent.id not in dag):
                continue
            victims = [
                task.peers[cid] for cid in dag.children(parent.id)
                if cid in task.peers
                and getattr(task.peers[cid], "qos_class",
                            "standard") == "bulk"
                and not task.peers[cid].is_done()]
            if not victims:
                continue
            victim = max(victims, key=lambda p: p.created_at)
            keep = [pid for pid in dag.parents(victim.id)
                    if pid != parent.id]
            task.set_parents(victim.id, keep)
            victim.last_offer_ids = set(keep)
            _preemptions.labels("bulk").inc()
            log.info("preempt: bulk child %s lost parent %s so critical "
                     "%s can schedule", victim.id[-12:], parent.id[-12:],
                     child.id[-12:])
            if self.decision_sink is not None:
                self._decision_seq += 1
                self.decision_sink({
                    "kind": "decision",
                    "decision_id": (f"d{self._decision_seq:08d}."
                                    f"{child.id[-12:]}"),
                    "decision_kind": "preempt",
                    "task_id": task.id,
                    "peer_id": child.id,
                    "host_id": child.host.id,
                    "qos_class": getattr(child, "qos_class", "standard"),
                    "tenant": getattr(child, "tenant", ""),
                    "candidates": [],
                    "excluded": [],
                    "chosen": [],
                    "preempted": {
                        "victim_peer_id": victim.id,
                        "victim_class": "bulk",
                        "victim_tenant": getattr(victim, "tenant", ""),
                        "parent_id": parent.id,
                        "victim_parents_kept": keep,
                    },
                })
            return victim
        return None

    def find_parents(self, child: Peer) -> list[Peer]:
        return self._decide(child, "find")

    def refresh_parents(self, child: Peer) -> list[Peer]:
        """Sticky variant of ``find_parents`` for mid-download re-offers:
        current parents that are still legal stay, best newcomers fill the
        remaining candidate slots."""
        return self._decide(child, "refresh")

    def _decide(self, child: Peer, decision_kind: str) -> list[Peer]:
        """Filter, score, choose — and, when the decision ledger is armed,
        emit one ``kind=decision`` row carrying the full candidate set with
        per-term score decomposition, every exclusion with its reason, and
        the chosen offer. PURE OBSERVATION: with the sink armed the ranking
        key is ``explain()["total"]``, which is bit-identical to
        ``evaluate()`` (same term computations, same summation order), and
        ``sorted(..., reverse=True)`` is stable either way — the offer, and
        therefore the schedule digest, cannot move (gated by
        tests/test_dfbench.py on the PR-3 baseline)."""
        # the ruling profiler (common/phasetimer.py) wraps the whole
        # ruling and decomposes it into the pinned PHASES — same purity
        # contract as the ledger: timing never touches the rng or the
        # ordering, so the armed digest gate holds too
        with phasetimer.ruling(decision_kind):
            sink = self.decision_sink
            excluded: list | None = [] if sink is not None else None
            with phasetimer.phase("filter"):
                candidates = self.filter_candidates(child, excluded)
            total = child.task.total_piece_count
            explained: list[tuple[Peer, dict]] = []
            relay_note: dict | None = None
            prev_offer = set(child.last_offer_ids)
            if not candidates:
                offer: list[Peer] = []
            else:
                with phasetimer.phase("score"):
                    if sink is None:
                        scored = sorted(
                            candidates,
                            key=lambda p: self.evaluator.evaluate(
                                child, p, total_piece_count=total),
                            reverse=True)
                    else:
                        explained = [(p, self.evaluator.explain(
                            child, p, total_piece_count=total))
                            for p in candidates]
                        explained.sort(key=lambda pe: pe[1]["total"],
                                       reverse=True)
                        scored = [p for p, _ in explained]
                if self.cfg.relay_fanout > 0:
                    with phasetimer.phase("relay"):
                        scored, relay_note = self._relay_shape(child, scored)
                if decision_kind == "refresh":
                    kept = [p for p in scored if p.id in prev_offer]
                    fresh = [p for p in scored if p.id not in prev_offer]
                    offer = self._ensure_holder(
                        scored,
                        (kept + fresh)[:self.cfg.candidate_parent_limit])
                else:
                    offer = self._ensure_holder(
                        scored, scored[:self.cfg.candidate_parent_limit])
            if sink is not None:
                with phasetimer.phase("emit"):
                    self._emit_decision(child, decision_kind, explained,
                                        excluded or [], offer, prev_offer,
                                        total, relay_note=relay_note)
            return offer

    def _emit_decision(self, child: Peer, decision_kind: str,
                       explained: list, excluded: list, offer: list[Peer],
                       prev_offer: set, total: int,
                       relay_note: dict | None = None) -> None:
        self._decision_seq += 1
        decision_id = f"d{self._decision_seq:08d}.{child.id[-12:]}"
        candidates = []
        for rank, (p, ex) in enumerate(explained, 1):
            terms = ex["terms"]
            # the exact scoring-time feature row (trainer layout:
            # evaluator_ml.parent_feature_row), rebuilt from the terms
            # explain() already computed instead of re-scoring every
            # candidate — same staticmethod outputs, half the hot-path
            # cost. features[4] must stay the STATIC locality (the
            # train/serve contract): when the nt evaluator substituted
            # measured RTT into the locality term, recompute the base
            # score for the row
            locality = terms["locality"]
            if "locality" in (ex.get("substituted") or {}):
                locality = Evaluator._locality_score(child, p)
            cand = {
                "peer_id": p.id,
                "host_id": p.host.id,
                "rank": rank,
                "total": ex["total"],
                "terms": terms,
                "features": [terms["piece"], terms["upload_success"],
                             terms["free_upload"], terms["host_type"],
                             locality, float(len(p.finished_pieces)),
                             float(p.host.concurrent_upload_count)],
            }
            for key in ("substituted", "rtt_us", "base_total",
                        "link_tier", "cross_pod"):
                if key in ex:
                    cand[key] = ex[key]
            candidates.append(cand)
        row = {
            "kind": "decision",
            "decision_id": decision_id,
            "decision_kind": decision_kind,
            "task_id": child.task.id,
            "peer_id": child.id,
            "host_id": child.host.id,
            # QoS attribution on every ruling: replaying the ledger can
            # audit class fairness (who got which slots, what the
            # fan-out caps demoted, which preemptions fired) offline
            "qos_class": getattr(child, "qos_class", "standard"),
            "tenant": getattr(child, "tenant", ""),
            "total_piece_count": total,
            "evaluator": type(self.evaluator).__name__,
            "candidates": candidates,
            "excluded": [{"peer_id": p.id, "host_id": p.host.id,
                          "reason": reason} for p, reason in excluded],
            "chosen": [p.id for p in offer],
        }
        if relay_note is not None:
            # relay-tree shaping ruling: which candidates the fan-out cap
            # demoted and their DAG child counts — the relay analog of
            # the excluded[] reasons, so "why isn't the seed my parent"
            # is answerable from the row alone
            row["relay"] = relay_note
        if self.federation is not None:
            # federation ruling context: the child's pod, its elected
            # seed set, and whether this child may cross the DCN — with
            # the per-candidate ``link_tier`` term this makes federation
            # fairness replayable from the row stream alone
            fed_note = self.federation.note(child)
            if fed_note is not None:
                row["federation"] = fed_note
        if decision_kind == "refresh":
            # sticky attribution of the final offer: which slots the
            # stickiness held vs which the newcomers won
            row["kept"] = [p.id for p in offer if p.id in prev_offer]
            row["fresh"] = [p.id for p in offer if p.id not in prev_offer]
        if offer:
            # join key for outcome rows: records.on_piece stamps each piece
            # row with the child's newest ruling (see records.py)
            child.last_decision_id = decision_id
        self.decision_sink(row)

    # ------------------------------------------------------------------

    def build_packet(self, child: Peer, parents: list[Peer]) -> PeerPacket:
        from ..idl.messages import HostType

        def addr(p: Peer) -> PeerAddr:
            same_host = p.host.id == child.host.id
            return PeerAddr(
                peer_id=p.id, ip=p.host.msg.ip,
                rpc_port=p.host.msg.port,
                download_port=p.host.msg.download_port,
                link=link_type(child.host.msg.topology, p.host.msg.topology,
                               same_host=same_host),
                is_seed=p.host.msg.type != HostType.NORMAL)
        main = addr(parents[0]) if parents else None
        return PeerPacket(
            task_id=child.task.id, src_peer_id=child.id,
            parallel_count=4, main_peer=main,
            candidate_peers=[addr(p) for p in parents[1:]])

