"""Scheduling core: pick parents for a peer, or rule back-source.

Role parity: reference ``scheduler/scheduling/scheduling.go`` —
``ScheduleParentAndCandidateParents`` retry loop, ``FindCandidateParents``
(:385) and ``filterCandidateParents`` (:500-570: blocklist, same-peer,
DAG-cycle, bad-node, free-upload-slot checks), with the
``RetryBackToSourceLimit`` arbitration.
"""

from __future__ import annotations

import logging

from ..idl.messages import PeerAddr, PeerPacket
from ..tpu.topology import link_type
from .config import SchedulerConfig
from .evaluator import Evaluator
from .resource import Peer

log = logging.getLogger("df.sched.core")


class Scheduling:
    def __init__(self, cfg: SchedulerConfig, evaluator: Evaluator):
        self.cfg = cfg
        self.evaluator = evaluator

    # ------------------------------------------------------------------

    def filter_candidates(self, child: Peer) -> list[Peer]:
        """All legal parents for ``child``, pre-scoring (the filter half)."""
        task = child.task
        out: list[Peer] = []
        for parent in task.peers.values():
            if len(out) >= self.cfg.filter_parent_limit:
                break
            if parent.id == child.id:
                continue
            if parent.id in child.blocked_parents:
                continue
            if not parent.has_content():
                continue
            if parent.host.free_upload_slots() <= 0:
                continue
            if self.evaluator.is_bad_node(parent):
                continue
            if task.would_cycle(parent.id, child.id):
                continue
            out.append(parent)
        return out

    def find_parents(self, child: Peer) -> list[Peer]:
        candidates = self.filter_candidates(child)
        if not candidates:
            return []
        total = child.task.total_piece_count
        scored = sorted(
            candidates,
            key=lambda p: self.evaluator.evaluate(child, p,
                                                  total_piece_count=total),
            reverse=True)
        return scored[:self.cfg.candidate_parent_limit]

    # ------------------------------------------------------------------

    def build_packet(self, child: Peer, parents: list[Peer]) -> PeerPacket:
        def addr(p: Peer) -> PeerAddr:
            same_host = p.host.id == child.host.id
            return PeerAddr(
                peer_id=p.id, ip=p.host.msg.ip,
                rpc_port=p.host.msg.port,
                download_port=p.host.msg.download_port,
                link=link_type(child.host.msg.topology, p.host.msg.topology,
                               same_host=same_host))
        main = addr(parents[0]) if parents else None
        return PeerPacket(
            task_id=child.task.id, src_peer_id=child.id,
            parallel_count=4, main_peer=main,
            candidate_peers=[addr(p) for p in parents[1:]])

