"""Scheduling core: pick parents for a peer, or rule back-source.

Role parity: reference ``scheduler/scheduling/scheduling.go`` —
``ScheduleParentAndCandidateParents`` retry loop, ``FindCandidateParents``
(:385) and ``filterCandidateParents`` (:500-570: blocklist, same-peer,
DAG-cycle, bad-node, free-upload-slot checks), with the
``RetryBackToSourceLimit`` arbitration.
"""

from __future__ import annotations

import logging
import random

from ..idl.messages import PeerAddr, PeerPacket
from ..tpu.topology import link_type
from .config import SchedulerConfig
from .evaluator import Evaluator
from .resource import Peer

log = logging.getLogger("df.sched.core")


class Scheduling:
    def __init__(self, cfg: SchedulerConfig, evaluator: Evaluator):
        self.cfg = cfg
        self.evaluator = evaluator

    # ------------------------------------------------------------------

    def filter_candidates(self, child: Peer) -> list[Peer]:
        """All legal parents for ``child``, pre-scoring (the filter half).

        The pool is sampled in random order (reference ``LoadRandomPeers``,
        ``scheduling.go:511``): a deterministic iteration order would hand
        every child the same first-N candidates and herd the fan-out onto
        them."""
        task = child.task
        pool = list(task.peers.values())
        random.shuffle(pool)
        out: list[Peer] = []
        for parent in pool:
            full = len(out) >= self.cfg.filter_parent_limit
            if full and any(p.has_content() for p in out):
                break
            if full and not parent.has_content():
                # truncated but holderless so far: keep scanning for a
                # content-holder only — a fan-out wider than the filter
                # limit could otherwise sample nothing but pieceless
                # siblings and the offer would never name the seed
                continue
            if parent.id == child.id:
                continue
            if parent.stream_gone and not parent.is_done():
                # mid-download peer whose report stream died: almost
                # certainly a dead process — offering it strands children
                # on a parent that will never answer (chaos e2e)
                self._trace(child, parent, "stream-gone")
                continue
            if child.is_blocked(parent.id):
                self._trace(child, parent, "blocklist")
                continue
            if not parent.has_content() and parent.is_done():
                # finished-but-empty (failed) peers serve nothing. RUNNING
                # pieceless siblings stay IN: the engine dispatches only to
                # announcers, so they cost one sync stream — and that stream
                # is how a child hears a sibling's first piece the moment it
                # lands. Requiring content here meant every child's initial
                # packet named only the seed, sibling meshing waited on
                # first-piece top-ups, and a congested seed kept the mesh
                # from ever forming (the r04 bimodal collapse: 18s waves
                # with try=51 against the seed while siblings held pieces).
                continue
            # a parent this child is ALREADY assigned to holds its edge (and
            # slot) — re-checking free slots would evict current parents of
            # any loaded host exactly when stickiness matters, and the
            # engine's packet prune would then tear down their sync streams
            if (parent.host.free_upload_slots() <= 0
                    and parent.id not in child.last_offer_ids):
                self._trace(child, parent, "no-slots")
                continue
            if self.evaluator.is_bad_node(parent):
                self._trace(child, parent, "bad-node")
                continue
            if task.would_cycle(parent.id, child.id):
                self._trace(child, parent, "cycle")
                continue
            out.append(parent)
        return out

    @staticmethod
    def _trace(child: Peer, parent: Peer, reason: str) -> None:
        if log.isEnabledFor(logging.DEBUG):
            log.debug("filter %s: parent %s excluded (%s)",
                      child.id[-12:], parent.id[-12:], reason)

    @staticmethod
    def _ensure_holder(scored: list[Peer], top: list[Peer]) -> list[Peer]:
        """Keep ≥1 content-holder in the offer when one exists: an offer of
        only pieceless siblings (local links can outscore the remote seed)
        would leave the child subscribed to peers that may never announce."""
        if any(p.has_content() for p in top):
            return top
        holder = next((p for p in scored if p.has_content()), None)
        if holder is None:
            return top
        return [*top[:-1], holder] if top else [holder]

    def find_parents(self, child: Peer) -> list[Peer]:
        candidates = self.filter_candidates(child)
        if not candidates:
            return []
        total = child.task.total_piece_count
        scored = sorted(
            candidates,
            key=lambda p: self.evaluator.evaluate(child, p,
                                                  total_piece_count=total),
            reverse=True)
        return self._ensure_holder(scored,
                                   scored[:self.cfg.candidate_parent_limit])

    def refresh_parents(self, child: Peer) -> list[Peer]:
        """Sticky variant of ``find_parents`` for mid-download re-offers:
        current parents that are still legal stay, best newcomers fill the
        remaining candidate slots."""
        candidates = self.filter_candidates(child)
        if not candidates:
            return []
        total = child.task.total_piece_count
        scored = sorted(
            candidates,
            key=lambda p: self.evaluator.evaluate(child, p,
                                                  total_piece_count=total),
            reverse=True)
        kept = [p for p in scored if p.id in child.last_offer_ids]
        fresh = [p for p in scored if p.id not in child.last_offer_ids]
        return self._ensure_holder(
            scored, (kept + fresh)[:self.cfg.candidate_parent_limit])

    # ------------------------------------------------------------------

    def build_packet(self, child: Peer, parents: list[Peer]) -> PeerPacket:
        from ..idl.messages import HostType

        def addr(p: Peer) -> PeerAddr:
            same_host = p.host.id == child.host.id
            return PeerAddr(
                peer_id=p.id, ip=p.host.msg.ip,
                rpc_port=p.host.msg.port,
                download_port=p.host.msg.download_port,
                link=link_type(child.host.msg.topology, p.host.msg.topology,
                               same_host=same_host),
                is_seed=p.host.msg.type != HostType.NORMAL)
        main = addr(parents[0]) if parents else None
        return PeerPacket(
            task_id=child.task.id, src_peer_id=child.id,
            parallel_count=4, main_peer=main,
            candidate_peers=[addr(p) for p in parents[1:]])

