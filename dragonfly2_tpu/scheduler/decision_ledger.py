"""Scheduler decision ledger: every ruling, explained, joinable, replayable.

Role parity: none in the reference — ``scheduling.go`` computes every
candidate's score inside a sort and throws it away, and filter exclusions
survive only as debug log lines. Here ``Scheduling._decide`` emits one
``kind=decision`` row per ``find_parents``/``refresh_parents`` call: the
full candidate set with the per-term score decomposition the ruling was
based on (``Evaluator.explain``), every filtered-out parent with its
exclusion reason, the chosen offer, and sticky-refresh kept/fresh
attribution. This module is everything downstream of that emission:

* ``DecisionLedger`` — bounded in-memory ring for live inspection
  (``GET /debug/decisions`` on the scheduler's ``--debug-port``) that
  also forwards rows into ``records.py``'s JSONL batching path, where
  they interleave with the ``kind=piece``/``kind=edge`` outcome rows
  they join against;
* ``stitch_outcomes`` — the join: piece rows carry the child's newest
  ``decision_id`` (stamped at scoring time), edge rows join by
  (task, child, parent) keys — "why did child X get parent Y, what did
  the runner-up score, and how did the choice pay off";
* the **counterfactual replay** (``dfbench --pr8``): re-score logged
  candidate sets under a different evaluator (default vs ``nt`` vs
  ``ml``) entirely offline — rank-agreement / choice-flip rates and a
  deterministic ``decision_digest``. This is the offline A/B harness a
  learned evaluator (ROADMAP item 1) must win before it serves traffic.

Everything below ``DecisionLedger`` is pure (no clock, no IO) so the
replay is deterministic and unit-testable.
"""

from __future__ import annotations

import hashlib
import json
import math
import time
from collections import Counter, deque

from .evaluator import SCORE_TERMS, rtt_locality_score, weighted_total

DEFAULT_RING_ROWS = 512

#: evaluators the offline replay can re-score a logged candidate set under
REPLAY_EVALUATORS = ("default", "nt", "ml")


class DecisionLedger:
    """Bounded ring of recent decision rows + forwarding into records.

    Attached as ``Scheduling.decision_sink`` by the scheduler bootstrap;
    ``records`` may be None (memory-only scheduler) — the live debug
    surface works either way.
    """

    def __init__(self, records=None, max_rows: int = DEFAULT_RING_ROWS):
        self.records = records
        self._ring: deque = deque(maxlen=max_rows)
        self.decisions_total = 0
        self.by_kind: Counter = Counter()
        self.excluded_by_reason: Counter = Counter()

    def on_decision(self, row: dict) -> None:
        row = dict(row)
        row.setdefault("created_at", time.time())
        self._ring.append(row)
        self.decisions_total += 1
        self.by_kind[row.get("decision_kind", "")] += 1
        for ex in row.get("excluded") or []:
            self.excluded_by_reason[ex.get("reason", "")] += 1
        if self.records is not None:
            self.records.on_decision(row)

    def stats(self) -> dict:
        """Compact counters for /debug/cluster: is the pod herding onto
        an exclusion reason, and how many rulings has it taken."""
        return {
            "total": self.decisions_total,
            "by_kind": dict(self.by_kind),
            "excluded_by_reason": dict(self.excluded_by_reason),
            "ring": len(self._ring),
        }

    def state_bytes(self) -> int:
        """Bytes of ledger state (the bounded ring + counters) for the
        /debug/ctrl bytes-per-peer accounting. Deep sizeof walk —
        snapshot cadence only, never on a ruling path."""
        from ..common.sizeof import deep_sizeof
        seen: set = set()
        return sum(deep_sizeof(o, seen) for o in (
            self._ring, self.by_kind, self.excluded_by_reason))

    def snapshot(self, task_id: str = "", peer_id: str = "",
                 limit: int = 64) -> dict:
        """Newest-last slice of the ring for ``GET /debug/decisions``
        (``?task=`` prefix, ``?peer=`` suffix, ``?limit=``)."""
        rows = [r for r in self._ring
                if (not task_id or r.get("task_id", "").startswith(task_id))
                and (not peer_id or r.get("peer_id", "").endswith(peer_id))]
        return {"stats": self.stats(),
                "decisions": rows[-max(limit, 1):]}


def add_decision_routes(router, ledger: DecisionLedger) -> None:
    """``GET /debug/decisions`` — mounted on the scheduler launcher's
    --debug-port server next to /debug/cluster."""
    from aiohttp import web

    async def decisions(req: web.Request) -> web.Response:
        try:
            limit = int(req.query.get("limit", "64"))
        except ValueError:
            return web.Response(status=400, text="limit must be an integer")
        return web.json_response(ledger.snapshot(
            task_id=req.query.get("task", ""),
            peer_id=req.query.get("peer", ""), limit=limit))

    router.add_get("/debug/decisions", decisions)


# ------------------------------------------------------------- outcome join

def stitch_outcomes(rows: list[dict]) -> dict:
    """Join ``kind=piece`` / ``kind=edge`` outcome rows to the decision
    that caused them.

    Primary key: the ``decision_id`` stamped on each piece row at scoring
    time. Fallback (rows from a scheduler restarted mid-task, or edge rows
    which aggregate a whole flight): the child's newest decision whose
    ``chosen`` set names the serving parent. Returns the decision rows
    (in input order) annotated with ``outcomes``/``edges`` per parent,
    plus the join-coverage numbers the e2e acceptance gates on (≥95% of
    piece rows must stitch)."""
    decisions: dict[str, dict] = {}
    order: list[dict] = []
    by_child: dict[tuple, list[dict]] = {}
    for r in rows:
        if r.get("kind") != "decision":
            continue
        d = dict(r)
        d["outcomes"] = {}
        d["edges"] = {}
        decisions[d.get("decision_id", "")] = d
        order.append(d)
        by_child.setdefault((d.get("task_id"), d.get("peer_id")),
                            []).append(d)

    def newest_naming(task_id, child_id, parent_id):
        for d in reversed(by_child.get((task_id, child_id), [])):
            if parent_id in (d.get("chosen") or []):
                return d
        return None

    piece_rows = joined = 0
    for r in rows:
        kind = r.get("kind")
        if kind == "piece":
            piece_rows += 1
            parent_id = r.get("parent_peer_id", "")
            d = decisions.get(r.get("decision_id", ""))
            if d is None:
                d = newest_naming(r.get("task_id"), r.get("peer_id"),
                                  parent_id)
            if d is None:
                continue
            joined += 1
            o = d["outcomes"].setdefault(
                parent_id, {"pieces": 0, "bytes": 0, "cost_ms": 0.0})
            o["pieces"] += 1
            o["bytes"] += r.get("piece_length", 0) or 0
            o["cost_ms"] += float(r.get("cost_ms", 0) or 0)
        elif kind == "edge":
            d = newest_naming(r.get("task_id"), r.get("dst_peer_id"),
                              r.get("src_peer_id", ""))
            if d is not None:
                d["edges"][r.get("src_peer_id", "")] = {
                    "bytes": r.get("bytes", 0),
                    "pieces": r.get("pieces", 0),
                    "bandwidth_bps": r.get("bandwidth_bps", 0),
                }
    return {
        "decisions": order,
        "coverage": {
            "piece_rows": piece_rows,
            "joined": joined,
            "ratio": round(joined / piece_rows, 4) if piece_rows else 1.0,
        },
    }


# ------------------------------------------------------ counterfactual replay

def synthetic_rtt_us(child_host_id: str, parent_host_id: str) -> float:
    """Deterministic stand-in RTT for replaying ``nt`` over decision rows
    that carry no measured ``rtt_us`` (the probe store had no data, or the
    rows come from the fakepod sim): log-uniform over 50us (ICI
    neighborhood) .. 10ms (congested WAN), a pure hash of the directed
    host pair — the same pair always replays the same link."""
    h = hashlib.sha256(
        f"{child_host_id}->{parent_host_id}".encode()).digest()
    frac = int.from_bytes(h[:8], "big") / 2.0 ** 64
    return 50.0 * (10_000.0 / 50.0) ** frac


# Deterministic stand-in for a served parent-quality model (logistic over
# trainer/features.PARENT_FEATURES). Weighted toward piece coverage and
# locality, penalizing concurrent upload load — a plausible learned shape
# that genuinely disagrees with the heuristic on loaded parents, so the
# replay's rank-agreement columns measure something until ROADMAP item 1's
# trained model is passed in instead (``infer=`` hooks it in verbatim).
_STANDIN_W = (1.2, 0.8, 0.5, 0.4, 1.6, 0.02, -0.08)
_STANDIN_B = -1.0


def standin_ml_infer(rows: list[list[float]]) -> list[float]:
    out = []
    for row in rows:
        z = _STANDIN_B + sum(w * x for w, x in zip(_STANDIN_W, row))
        out.append(1.0 / (1.0 + math.exp(-z)))
    return out


def rescore_candidate(cand: dict, evaluator_name: str,
                      child_host_id: str, infer=None) -> float:
    """One candidate's score under ``evaluator_name``, from the logged
    decomposition alone — no live Peer state needed."""
    terms = cand.get("terms") or {}
    if evaluator_name == "default":
        # rows logged by the nt evaluator carry the RTT-substituted score
        # in terms["locality"] — replaying "default" over them must
        # restore the static locality (features[4] in the trainer layout)
        # or the "default vs nt" comparison degenerates to nt-vs-itself
        if "locality" in (cand.get("substituted") or {}):
            feats = cand.get("features")
            if feats and len(feats) >= 5:
                terms = dict(terms, locality=feats[4])
        return weighted_total(terms)
    if evaluator_name == "nt":
        rtt_us = cand.get("rtt_us")
        if rtt_us is None:
            rtt_us = synthetic_rtt_us(child_host_id,
                                      cand.get("host_id", ""))
        subbed = dict(terms)
        subbed["locality"] = rtt_locality_score(float(rtt_us))
        return weighted_total(subbed)
    if evaluator_name == "ml":
        feats = cand.get("features")
        if feats:
            return float((infer or standin_ml_infer)([feats])[0])
        return weighted_total(terms)
    raise ValueError(f"unknown replay evaluator {evaluator_name!r} "
                     f"(known: {REPLAY_EVALUATORS})")


def rescore_decision(row: dict, evaluator_name: str,
                     infer=None) -> list[str]:
    """Candidate peer ids ranked best-first under ``evaluator_name``.
    Ties break on peer id so the ranking — and the digest over it — is a
    pure function of the row."""
    scored = [(rescore_candidate(c, evaluator_name,
                                 row.get("host_id", ""), infer),
               c.get("peer_id", ""))
              for c in row.get("candidates") or []]
    scored.sort(key=lambda t: (-t[0], t[1]))
    return [pid for _, pid in scored]


def rank_agreement(a: list[str], b: list[str]) -> float:
    """Pairwise concordance over the common candidates of two rankings
    (1.0 = identical order, 0.0 = fully reversed)."""
    in_b = {pid: i for i, pid in enumerate(b)}
    common = [pid for pid in a if pid in in_b]
    n = len(common)
    if n < 2:
        return 1.0
    concordant = pairs = 0
    for i in range(n):
        for j in range(i + 1, n):
            pairs += 1
            if in_b[common[i]] < in_b[common[j]]:
                concordant += 1
    return concordant / pairs


def replay_decisions(rows: list[dict],
                     evaluators: tuple = REPLAY_EVALUATORS,
                     infer=None) -> dict:
    """Re-score every logged candidate set under each evaluator and
    compare the rankings — the ``dfbench --pr8`` core. Returns per-pair
    mean rank agreement + top-choice flip rate, each evaluator's agreement
    with the logged chosen parent, and a deterministic
    ``decision_digest`` over the full ranking table (same rows + same
    evaluators ⇒ byte-identical digest)."""
    decisions = [r for r in rows
                 if r.get("kind") == "decision" and r.get("candidates")]
    rankings: dict[str, dict[str, list[str]]] = {
        name: {d.get("decision_id", ""): rescore_decision(d, name, infer)
               for d in decisions}
        for name in evaluators}
    pairs = {}
    for i, a in enumerate(evaluators):
        for b in evaluators[i + 1:]:
            agree = []
            flips = 0
            for d in decisions:
                did = d.get("decision_id", "")
                ra, rb = rankings[a][did], rankings[b][did]
                agree.append(rank_agreement(ra, rb))
                if ra and rb and ra[0] != rb[0]:
                    flips += 1
            n = len(decisions)
            pairs[f"{a}_vs_{b}"] = {
                "rank_agreement": round(sum(agree) / n, 4) if n else 1.0,
                "choice_flip_rate": round(flips / n, 4) if n else 0.0,
            }
    logged_choice = {}
    for name in evaluators:
        hits = with_choice = 0
        for d in decisions:
            chosen = d.get("chosen") or []
            ranked = rankings[name][d.get("decision_id", "")]
            if not chosen or not ranked:
                continue
            with_choice += 1
            if ranked[0] == chosen[0]:
                hits += 1
        logged_choice[name] = (round(hits / with_choice, 4)
                               if with_choice else 1.0)
    digest = hashlib.sha256(json.dumps(
        rankings, sort_keys=True).encode()).hexdigest()
    return {
        "decisions_scored": len(decisions),
        "evaluators": list(evaluators),
        "pairs": pairs,
        "logged_choice_agreement": logged_choice,
        "decision_digest": digest,
    }


def replay_regret(rows: list[dict],
                  evaluators: tuple = ("default", "ml"),
                  infer=None) -> dict:
    """Observed-bandwidth regret of each evaluator's counterfactual top
    pick, judged by what the logged outcomes actually measured.

    For every decision whose ``kind=piece`` outcome rows cover at least
    two candidates, each evaluator's ranking (``rescore_decision`` — the
    same pure replay math as ``replay_decisions``) nominates its best
    candidate *among those with measured outcomes*; the regret of that
    pick is its shortfall against the best observed bandwidth for the
    ruling, relative: ``(best_bps - picked_bps) / best_bps``. Restricting
    the pick to measured candidates keeps the judgment honest — an
    unmeasured candidate has no observed bandwidth to be judged by.

    Returns per-evaluator mean regret, mean chosen bandwidth, and the
    fraction of rulings where the evaluator picked the observed-best
    parent outright. ``decisions_judged`` counts rulings with a usable
    counterfactual (≥2 measured candidates); single-outcome rulings
    carry no signal and are skipped, not silently averaged in.
    """
    decisions = {r.get("decision_id", ""): r for r in rows
                 if r.get("kind") == "decision" and r.get("candidates")}
    # (decision_id, parent) -> [bytes, seconds] accumulated over pieces
    flow: dict[tuple, list] = {}
    for r in rows:
        if r.get("kind") != "piece" or not r.get("decision_id"):
            continue
        if r["decision_id"] not in decisions:
            continue
        key = (r["decision_id"], r.get("parent_peer_id", ""))
        agg = flow.setdefault(key, [0, 0.0])
        agg[0] += int(r.get("piece_length", 0) or 0)
        agg[1] += float(r.get("cost_ms", 0) or 0) / 1e3
    per = {name: {"regret": [], "bps": [], "best_picks": 0}
           for name in evaluators}
    judged = 0
    for did, d in decisions.items():
        observed = {}
        for c in d.get("candidates") or []:
            pid = c.get("peer_id", "")
            agg = flow.get((did, pid))
            if agg and agg[1] > 0:
                observed[pid] = agg[0] / agg[1]
        if len(observed) < 2:
            continue
        judged += 1
        best = max(observed.values())
        for name in evaluators:
            ranked = rescore_decision(d, name, infer)
            pick = next((pid for pid in ranked if pid in observed), None)
            if pick is None:    # unreachable: observed ⊆ candidates
                continue
            bps = observed[pick]
            per[name]["bps"].append(bps)
            per[name]["regret"].append((best - bps) / best if best else 0.0)
            if bps == best:
                per[name]["best_picks"] += 1
    out = {"decisions_judged": judged, "evaluators": {}}
    for name in evaluators:
        r = per[name]["regret"]
        b = per[name]["bps"]
        out["evaluators"][name] = {
            "mean_regret": round(sum(r) / len(r), 4) if r else 0.0,
            "mean_chosen_bandwidth_bps": round(sum(b) / len(b), 1)
            if b else 0.0,
            "best_pick_rate": round(per[name]["best_picks"] / judged, 4)
            if judged else 0.0,
        }
    return out


# drift guard: the replay rebuilds totals from SCORE_TERMS — a new term in
# the evaluator that never lands here would silently mis-replay
if tuple(n for n, _ in SCORE_TERMS) != (
        "piece", "upload_success", "free_upload", "host_type", "locality"):
    raise RuntimeError("decision replay expects the 5-term evaluator "
                       "decomposition; update rescore_candidate with "
                       "evaluator.SCORE_TERMS together")
