"""Parent evaluator: scores candidate parents for a downloading peer.

Role parity: reference ``scheduler/scheduling/evaluator/`` — the base
weighted-sum scorer (``evaluator_base.go:28-46``: piece 0.2, upload-success
0.2, free-upload 0.15, host-type 0.15, IDC 0.15, location 0.15), the
``nt`` network-topology variant (RTT weight 0.3), the ``ml`` slot, and the
``IsBadNode`` Z-score outlier ejection (``evaluator.go:93``).

TPU-native change: the IDC + location string-affinity weights (0.30 combined)
become a single fabric-locality score computed from real pod coordinates
(LOCAL > ICI > DCN > WAN, ``tpu/topology.py``) — same weight mass, but
driven by where the bytes would actually flow (ICI stays on the slice's
wired mesh; DCN rides the NIC).
"""

from __future__ import annotations

import logging
import statistics

from ..idl.messages import HostType, LinkType
from ..tpu.topology import (LINK_BANDWIDTH_SCORE, LINK_TIER_NAMES, classify,
                            ici_hops, link_type)
from .resource import Peer

log = logging.getLogger("df.sched.eval")

# weight structure per evaluator_base.go:28-46, with IDC+location mass
# reassigned to fabric locality
W_PIECE = 0.20
W_UPLOAD_SUCCESS = 0.20
W_FREE_UPLOAD = 0.15
W_HOST_TYPE = 0.15
W_LOCALITY = 0.30

# (term name, weight) in evaluate()'s exact summation order — the decision
# ledger's explain() and the dfbench --pr8 offline replay both rebuild the
# total from these, and floats only stay bit-identical to evaluate() when
# the summation order matches
SCORE_TERMS = (
    ("piece", W_PIECE),
    ("upload_success", W_UPLOAD_SUCCESS),
    ("free_upload", W_FREE_UPLOAD),
    ("host_type", W_HOST_TYPE),
    ("locality", W_LOCALITY),
)

BAD_NODE_Z = 3.0                 # reference uses 3-sigma piece-cost outliers


def weighted_total(terms: dict) -> float:
    """Weighted sum over SCORE_TERMS in declaration order (== the order
    ``evaluate`` adds them, so a rebuilt total is bit-identical)."""
    total = 0.0
    for name, weight in SCORE_TERMS:
        total += weight * terms[name]
    return total


def rtt_locality_score(rtt_us: float) -> float:
    """Measured-RTT locality mapping shared by the live ``nt`` evaluator
    and the offline decision replay: <=50us (ICI neighborhood) ~1.0,
    10ms ~0.1 (reference ``evaluator_network_topology.go:30-57``)."""
    return max(0.05, min(1.0, 50.0 / max(rtt_us, 50.0) + 0.05))


class Evaluator:
    """``default`` algorithm: rule-based weighted sum."""

    def evaluate(self, child: Peer, parent: Peer, *,
                 total_piece_count: int) -> float:
        return weighted_total(self._term_scores(
            child, parent, total_piece_count=total_piece_count))

    def _term_scores(self, child: Peer, parent: Peer, *,
                     total_piece_count: int) -> dict:
        return {
            "piece": self._piece_score(parent, total_piece_count),
            "upload_success": parent.host.upload_success_ratio(),
            "free_upload": self._free_upload_score(parent),
            "host_type": self._host_type_score(parent),
            "locality": self._locality_score(child, parent),
        }

    def explain(self, child: Peer, parent: Peer, *,
                total_piece_count: int) -> dict:
        """Per-term score decomposition for the decision ledger:
        ``{"terms": {name: raw score}, "total": float}`` where ``total``
        is bit-identical to ``evaluate()`` on the same state. Variants
        annotate what they substituted (``nt``: the locality term from
        measured RTT; ``ml``: the whole total from the served model).
        ``link_tier`` is the pinned tier name (tpu.topology
        LINK_TIER_NAMES) the locality score was computed from, and
        ``cross_pod`` flags a pod-boundary crossing (tpu.topology
        ``classify``; a multi-slice DF_POD_ID grouping can make these
        disagree with the raw link class) — the federation plane's
        per-candidate ledger terms, so which tier a ruling chose (and
        what cross-pod traffic it authorized) replays from the row
        alone. Annotation only: the weighted total, and therefore the
        schedule digest, never moves."""
        terms = self._term_scores(child, parent,
                                  total_piece_count=total_piece_count)
        lc = classify(child.host.msg.topology, parent.host.msg.topology,
                      same_host=child.host.id == parent.host.id)
        return {"terms": terms, "total": weighted_total(terms),
                "link_tier": LINK_TIER_NAMES[lc.link],
                "cross_pod": lc.dcn_hops > 0}

    # -- individual scores --------------------------------------------

    @staticmethod
    def _piece_score(parent: Peer, total_piece_count: int) -> float:
        if total_piece_count > 0:
            return len(parent.finished_pieces) / total_piece_count
        return 1.0 if parent.finished_pieces else 0.0

    @staticmethod
    def _free_upload_score(parent: Peer) -> float:
        limit = parent.host.upload_limit
        return parent.host.free_upload_slots() / limit if limit else 0.0

    @staticmethod
    def _host_type_score(parent: Peer) -> float:
        # seed classes beat normal peers (they hold full content and serve
        # nothing else); reference orders super > strong > weak > normal
        return {HostType.SUPER_SEED: 1.0, HostType.STRONG_SEED: 0.9,
                HostType.WEAK_SEED: 0.8, HostType.NORMAL: 0.5}.get(
                    parent.host.msg.type, 0.5)

    @staticmethod
    def _locality_score(child: Peer, parent: Peer) -> float:
        same_host = child.host.id == parent.host.id
        lt = link_type(child.host.msg.topology, parent.host.msg.topology,
                       same_host=same_host)
        score = LINK_BANDWIDTH_SCORE[lt]
        if lt == LinkType.ICI:
            # tie-break same-slice parents by torus distance: every hop is
            # wired bandwidth, but fewer hops = less contention
            a, b = child.host.msg.topology, parent.host.msg.topology
            hops = ici_hops(a, b)
            if hops < (1 << 16):
                score -= min(0.05, 0.005 * hops)
        return score

    # -- bad node ------------------------------------------------------

    @staticmethod
    def is_bad_node(peer: Peer) -> bool:
        """Z-score ejection on recent piece costs (evaluator.go:93+)."""
        costs = peer.piece_costs_ms
        if len(costs) < 4:
            return False
        mean = statistics.fmean(costs)
        stdev = statistics.pstdev(costs)
        if stdev == 0:
            return False
        return (costs[-1] - mean) / stdev > BAD_NODE_Z


class RTTEvaluator(Evaluator):
    """``nt`` algorithm: replaces the static locality score with measured
    RTT when the probe store has data for the pair
    (reference ``evaluator_network_topology.go:30-57``)."""

    def __init__(self, topo_store):
        self.topo = topo_store

    def _locality_score(self, child: Peer, parent: Peer) -> float:  # type: ignore[override]
        rtt_us = self.topo.avg_rtt_us(child.host.id, parent.host.id)
        if rtt_us is None:
            return Evaluator._locality_score(child, parent)
        return rtt_locality_score(rtt_us)

    def explain(self, child: Peer, parent: Peer, *,
                total_piece_count: int) -> dict:
        out = super().explain(child, parent,
                              total_piece_count=total_piece_count)
        rtt_us = self.topo.avg_rtt_us(child.host.id, parent.host.id)
        if rtt_us is not None:
            # the locality term above already carries the RTT-derived
            # score; record that it was measured, and the measurement, so
            # the offline replay can re-map it instead of synthesizing one
            out["substituted"] = {"locality": "rtt"}
            out["rtt_us"] = rtt_us
        return out


def make_evaluator(algorithm: str, *, topo_store=None, infer=None,
                   plugin_dir: str = "") -> Evaluator:
    if algorithm == "nt" and topo_store is not None:
        return RTTEvaluator(topo_store)
    if algorithm == "ml":
        # infer may be None at boot; the model-refresh loop binds it when a
        # trained version lands (base-score fallback covers the cold start)
        from .evaluator_ml import MLEvaluator
        return MLEvaluator(infer)
    if algorithm.startswith("plugin:"):
        # operator-supplied scorer (reference evaluator 'plugin' algorithm
        # + internal/dfplugin); the plugin object must expose
        # evaluate(child, parent, total_piece_count) -> float
        from ..common import plugins
        impl, _meta = plugins.load(plugin_dir, "evaluator",
                                   algorithm.split(":", 1)[1])
        return _PluginEvaluator(impl)
    return Evaluator()


class _PluginEvaluator(Evaluator):
    def __init__(self, impl):
        self.impl = impl

    def evaluate(self, child, parent, *, total_piece_count: int) -> float:
        return float(self.impl.evaluate(
            child, parent, total_piece_count=total_piece_count))

    def explain(self, child, parent, *, total_piece_count: int) -> dict:
        # base terms stay as context; the ruling total is the plugin's
        out = super().explain(child, parent,
                              total_piece_count=total_piece_count)
        out["base_total"] = out["total"]
        out["total"] = self.evaluate(child, parent,
                                     total_piece_count=total_piece_count)
        out["substituted"] = {"total": "plugin"}
        return out
