"""In-memory cluster state: Task / Peer / Host with explicit state machines.

Role parity: reference ``scheduler/resource/`` — Task piece-holder DAG over
peers (``task.go:58-220``), Peer FSM (``peer.go:53-80``), Host
upload-slot accounting (``host.go``), managers with TTL GC
(``peer_manager.go:250`` etc.). The FSMs here are explicit enum + allowed-
transition tables — the state × stream × retry matrix is the bug farm
(SURVEY §7 hard parts), so transitions are validated, never implied.
"""

from __future__ import annotations

import enum
import logging
import time

from ..common.dag import DAG, DAGError
from ..common.errors import Code, DFError
from ..idl.messages import Host as HostMsg
from ..idl.messages import HostType, PieceInfo, SizeScope, TaskType

log = logging.getLogger("df.sched.resource")


# ---------------------------------------------------------------- FSMs

class PeerState(str, enum.Enum):
    PENDING = "pending"
    RUNNING = "running"            # registered, downloading via P2P
    BACK_SOURCE = "back_source"    # told to fetch from origin
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    LEAVING = "leaving"


_PEER_TRANSITIONS: dict[PeerState, set[PeerState]] = {
    PeerState.PENDING: {PeerState.RUNNING, PeerState.BACK_SOURCE,
                        PeerState.FAILED, PeerState.LEAVING},
    PeerState.RUNNING: {PeerState.BACK_SOURCE, PeerState.SUCCEEDED,
                        PeerState.FAILED, PeerState.LEAVING},
    PeerState.BACK_SOURCE: {PeerState.SUCCEEDED, PeerState.FAILED,
                            PeerState.LEAVING},
    PeerState.SUCCEEDED: {PeerState.LEAVING},
    PeerState.FAILED: {PeerState.RUNNING, PeerState.LEAVING},
    PeerState.LEAVING: set(),
}


class TaskState(str, enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"        # at least one peer finished the content
    FAILED = "failed"


_TASK_TRANSITIONS: dict[TaskState, set[TaskState]] = {
    TaskState.PENDING: {TaskState.RUNNING, TaskState.FAILED},
    TaskState.RUNNING: {TaskState.SUCCEEDED, TaskState.FAILED},
    TaskState.SUCCEEDED: {TaskState.RUNNING},   # re-validated after GC/expiry
    TaskState.FAILED: {TaskState.RUNNING},
}


# ---------------------------------------------------------------- entities

class Host:
    # Defaults when the daemon announces 0 ("auto"). Slots ride DAG edges
    # (one slot per parent->child assignment for the child's whole download),
    # so the limit is the node's max direct children in the distribution
    # DAG — a loose safety valve against unbounded fan-in, NOT the transfer
    # throttle. Reference parity: 200 peer / 500 seed
    # (scheduler/config/constants.go:27-31). Round 3 set these to 8/16 and
    # used them as the primary backpressure; combined with announcement
    # rationing that starved the swarm (BENCH_r03 halved). The per-TRANSFER
    # limits live where the bytes move: the upload server's concurrency
    # gate + NIC token bucket, and the dispatcher's busy-backoff/load-aware
    # scoring on the demand side. Overridable per host (daemon upload
    # config) and per cluster (SchedulerConfig.{peer,seed}_upload_limit).
    DEFAULT_PEER_UPLOAD_LIMIT = 200
    DEFAULT_SEED_UPLOAD_LIMIT = 500

    def __init__(self, msg: HostMsg, *, peer_upload_limit: int = 0,
                 seed_upload_limit: int = 0):
        self.id = msg.id
        self.msg = msg
        self.peer_upload_limit = peer_upload_limit or self.DEFAULT_PEER_UPLOAD_LIMIT
        self.seed_upload_limit = seed_upload_limit or self.DEFAULT_SEED_UPLOAD_LIMIT
        self.concurrent_upload_count = 0
        self.upload_success = 0
        self.upload_fail = 0
        self.created_at = time.time()
        self.updated_at = self.created_at

    @property
    def upload_limit(self) -> int:
        if self.msg.concurrent_upload_limit > 0:
            return self.msg.concurrent_upload_limit
        if self.msg.type != HostType.NORMAL:
            return self.seed_upload_limit
        return self.peer_upload_limit

    def free_upload_slots(self) -> int:
        return max(0, self.upload_limit - self.concurrent_upload_count)

    def acquire_upload_slot(self) -> None:
        self.concurrent_upload_count += 1

    def release_upload_slot(self) -> None:
        self.concurrent_upload_count = max(0, self.concurrent_upload_count - 1)

    def touch(self, msg: HostMsg | None = None) -> None:
        if msg is not None:
            self.msg = msg
        self.updated_at = time.time()

    def observe_upload(self, ok: bool) -> None:
        if ok:
            self.upload_success += 1
        else:
            self.upload_fail += 1

    def upload_success_ratio(self) -> float:
        total = self.upload_success + self.upload_fail
        return self.upload_success / total if total else 1.0


class Peer:
    def __init__(self, peer_id: str, task: "Task", host: Host):
        self.id = peer_id
        self.task = task
        self.host = host
        self.state = PeerState.PENDING
        self.finished_pieces: set[int] = set()
        self.piece_costs_ms: list[int] = []       # recent piece costs (bad-node)
        self.schedule_count = 0                   # packets sent to this peer
        self.report_fail_count = 0                # failed piece reports
        self.blocked_parents: dict[str, float] = {}   # parent id -> expiry
        self.last_offer_ids: set[str] = set()     # parents last pushed to peer
        # newest decision-ledger ruling that named parents for this child;
        # stamped by Scheduling._emit_decision, carried onto every
        # kind=piece record row as the outcome->decision join key
        self.last_decision_id = ""
        self.packet_sink = None                   # set by the report stream
        # resolved download priority (idl.Priority numeric: 0 = highest).
        # Set at register: explicit request value, else the manager-fed
        # application table, else LEVEL0 (reference Peer.CalculatePriority)
        self.priority = 0
        # multi-tenant QoS (set at register from UrlMeta): the service
        # class rides every scheduling ruling (decision-ledger rows, the
        # per-class relay fan-out cap, bulk-dispatch preemption) and the
        # tenant is the quota/attribution key
        self.qos_class = "standard"
        self.tenant = ""
        # report stream broke while the peer was mid-download: very likely
        # a dead process. Not a removal — completion can land via a late
        # unary report, and a live peer re-opens a stream (both clear it) —
        # but offers and coverage must stop counting the peer meanwhile.
        self.stream_gone = False
        self.created_at = time.time()
        self.updated_at = self.created_at

    def transit(self, to: PeerState) -> None:
        if to == self.state:
            return
        if to not in _PEER_TRANSITIONS[self.state]:
            raise DFError(Code.SCHED_TASK_STATUS_ERROR,
                          f"peer {self.id[-12:]}: illegal {self.state.value}"
                          f" -> {to.value}")
        log.debug("peer %s: %s -> %s", self.id[-12:], self.state.value, to.value)
        self.state = to
        self.updated_at = time.time()

    def touch(self) -> None:
        self.updated_at = time.time()

    def block_parent(self, parent_id: str, ttl_s: float = 10.0) -> None:
        """Exclude a parent after a failed fetch. Time-bounded: a transient
        wobble (restart, brief overload) must not sever the pair for the
        rest of the task — permanent ejection is the Z-score bad-node
        check's job, not the blocklist's."""
        self.blocked_parents[parent_id] = time.time() + ttl_s

    def is_blocked(self, parent_id: str) -> bool:
        expiry = self.blocked_parents.get(parent_id)
        if expiry is None:
            return False
        if time.time() >= expiry:
            del self.blocked_parents[parent_id]
            return False
        return True

    def observe_piece_cost(self, cost_ms: int) -> None:
        self.piece_costs_ms.append(cost_ms)
        if len(self.piece_costs_ms) > 20:
            self.piece_costs_ms = self.piece_costs_ms[-20:]

    def is_done(self) -> bool:
        return self.state in (PeerState.SUCCEEDED, PeerState.FAILED,
                              PeerState.LEAVING)

    def has_content(self) -> bool:
        """Usable as a parent: finished, running with pieces to share, or
        back-sourcing (its origin pull will announce pieces over the sync
        stream moments from now — children attach early so the pipeline
        preforms instead of polling for the seed's first piece; reference
        ``scheduling.go:538-541`` similarly admits back-source parents)."""
        if self.state in (PeerState.SUCCEEDED, PeerState.BACK_SOURCE):
            return True
        return self.state == PeerState.RUNNING and bool(self.finished_pieces)


class Task:
    def __init__(self, task_id: str, url: str, *,
                 task_type: TaskType = TaskType.STANDARD):
        self.id = task_id
        self.url = url
        self.task_type = task_type
        self.state = TaskState.PENDING
        self.content_length = -1
        self.piece_size = 0
        self.total_piece_count = -1
        self.direct_content = b""                # TINY tasks: inline bytes
        self.pieces: dict[int, PieceInfo] = {}   # canonical piece metadata
        self.peers: dict[str, Peer] = {}
        self.dag: DAG[str] = DAG()               # edges parent -> child
        self.back_source_peers: set[str] = set()  # peers holding an origin slot
        self.seed_triggered = False
        self.seed_job = None                     # asyncio.Task of the trigger
        self.seed_retries = 0                    # re-triggers after failure
        self.seed_next_retry_at = 0.0            # monotonic backoff gate
        self.url_meta = None                     # first register's UrlMeta:
        # kept so a seed RE-trigger (seed daemon died mid-injection) can
        # replay the original request headers/tag against the origin
        self.created_at = time.time()
        self.updated_at = self.created_at

    def transit(self, to: TaskState) -> None:
        if to == self.state:
            return
        if to not in _TASK_TRANSITIONS[self.state]:
            raise DFError(Code.SCHED_TASK_STATUS_ERROR,
                          f"task {self.id[:12]}: illegal {self.state.value}"
                          f" -> {to.value}")
        self.state = to
        self.updated_at = time.time()

    # -- geometry ------------------------------------------------------

    def set_content_info(self, content_length: int, piece_size: int,
                         total_piece_count: int) -> None:
        if content_length >= 0:
            self.content_length = content_length
        if piece_size > 0:
            self.piece_size = piece_size
        if total_piece_count >= 0:
            self.total_piece_count = total_piece_count
        self.updated_at = time.time()

    def size_scope(self) -> SizeScope:
        if self.content_length < 0:
            return SizeScope.NORMAL
        if self.content_length == 0:
            return SizeScope.EMPTY
        if self.content_length <= 128 * 1024 and self.direct_content:
            return SizeScope.TINY
        if self.total_piece_count == 1:
            return SizeScope.SMALL
        return SizeScope.NORMAL

    def record_piece(self, info: PieceInfo) -> None:
        known = self.pieces.get(info.piece_num)
        if known is None or (not known.digest and info.digest):
            self.pieces[info.piece_num] = info

    # -- peer/DAG management ------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        self.peers[peer.id] = peer
        self.dag.add_vertex(peer.id, peer.id)
        self.touch()

    def remove_peer(self, peer_id: str) -> None:
        peer = self.peers.pop(peer_id, None)
        if peer_id in self.dag:
            # release upload slots: this peer's parents each lose one child
            # (their slot), and this peer's host frees one slot per child
            for pid in self.dag.parents(peer_id):
                parent = self.peers.get(pid)
                if parent is not None:
                    parent.host.release_upload_slot()
            if peer is not None:
                for _ in self.dag.children(peer_id):
                    peer.host.release_upload_slot()
            try:
                self.dag.delete_vertex(peer_id)
            except DAGError:
                pass
        self.back_source_peers.discard(peer_id)
        self.touch()

    def set_parents(self, child_id: str, parent_ids: list[str]) -> None:
        """Re-point the child's in-edges at the new parent set (re-parenting
        on reschedule must drop stale edges or the DAG fills with cycles).
        Upload-slot accounting rides the edge changes: one in-flight upload
        per parent→child edge (reference ``resource/host.go`` accounting)."""
        old = self.dag.parents(child_id)
        self.dag.delete_in_edges(child_id)
        new: set[str] = set()
        for pid in parent_ids:
            if pid == child_id or pid not in self.dag:
                continue
            try:
                self.dag.add_edge(pid, child_id)
                new.add(pid)
            except DAGError:
                log.debug("edge %s->%s would cycle; skipped", pid[-12:],
                          child_id[-12:])
        for pid in old - new:
            parent = self.peers.get(pid)
            if parent is not None:
                parent.host.release_upload_slot()
        for pid in new - old:
            parent = self.peers.get(pid)
            if parent is not None:
                parent.host.acquire_upload_slot()

    def would_cycle(self, parent_id: str, child_id: str) -> bool:
        return self.dag.can_reach(child_id, parent_id)

    def has_available_peer(self) -> bool:
        return any(p.has_content() for p in self.peers.values())

    def has_live_available_peer(self) -> bool:
        """has_available_peer minus peers whose report stream died
        mid-download (their content is unreachable until they return)."""
        return any(p.has_content()
                   and not (p.stream_gone and not p.is_done())
                   for p in self.peers.values())

    def swarm_can_complete(self) -> bool:
        """Whether the union of live peers' finished pieces covers every
        piece of the task. False means some content exists NOWHERE in the
        swarm (e.g. the seed died mid-injection and took the tail pieces
        with it) — no amount of peer-to-peer scheduling can finish, and
        the scheduler must re-source (seed re-trigger / back-source).
        Unknown totals count as coverable: there is nothing to prove yet.
        """
        if self.total_piece_count <= 0:
            return True
        covered: set[int] = set()
        for p in self.peers.values():
            if p.state in (PeerState.FAILED, PeerState.LEAVING) \
                    or (p.stream_gone and not p.is_done()):
                continue
            covered |= p.finished_pieces
            if len(covered) >= self.total_piece_count:
                return True
        return False

    def touch(self) -> None:
        self.updated_at = time.time()


# ---------------------------------------------------------------- managers

class Resource:
    """The cluster state of record for one scheduler."""

    def __init__(self, *, peer_ttl_s: float = 24 * 3600.0,
                 task_ttl_s: float = 24 * 3600.0,
                 host_ttl_s: float = 6 * 3600.0,
                 peer_upload_limit: int = 0,
                 seed_upload_limit: int = 0):
        self.tasks: dict[str, Task] = {}
        self.hosts: dict[str, Host] = {}
        self.peer_ttl_s = peer_ttl_s
        self.task_ttl_s = task_ttl_s
        self.host_ttl_s = host_ttl_s
        self.peer_upload_limit = peer_upload_limit
        self.seed_upload_limit = seed_upload_limit
        # optional eviction observers (the server sets these when the
        # federation plane is armed): a host or task leaving the resource
        # model must also leave the federation view, or per-pod seed
        # elections keep naming hosts that no longer exist
        self.on_host_evict = None      # callable(host_id)
        self.on_task_evict = None      # callable(task_id)

    # -- lookups -------------------------------------------------------

    def get_or_create_task(self, task_id: str, url: str, *,
                           task_type: TaskType = TaskType.STANDARD) -> Task:
        task = self.tasks.get(task_id)
        if task is None:
            task = Task(task_id, url, task_type=task_type)
            self.tasks[task_id] = task
        return task

    def store_host(self, msg: HostMsg) -> Host:
        host = self.hosts.get(msg.id)
        if host is None:
            host = Host(msg, peer_upload_limit=self.peer_upload_limit,
                        seed_upload_limit=self.seed_upload_limit)
            self.hosts[msg.id] = host
        else:
            host.touch(msg)
        return host

    def get_or_create_peer(self, peer_id: str, task: Task, host: Host) -> Peer:
        peer = task.peers.get(peer_id)
        if peer is None:
            peer = Peer(peer_id, task, host)
            task.add_peer(peer)
        return peer

    def find_peer(self, task_id: str, peer_id: str) -> Peer | None:
        task = self.tasks.get(task_id)
        return task.peers.get(peer_id) if task else None

    # -- departures ----------------------------------------------------

    def leave_peer(self, task_id: str, peer_id: str) -> None:
        task = self.tasks.get(task_id)
        if task is None:
            return
        peer = task.peers.get(peer_id)
        if peer is not None and peer.state != PeerState.LEAVING:
            try:
                peer.transit(PeerState.LEAVING)
            except DFError:
                pass
        task.remove_peer(peer_id)

    def leave_host(self, host_id: str) -> list[Peer]:
        """Remove the host and every peer on it; returns orphaned children's
        peers so the service can reschedule them."""
        self.hosts.pop(host_id, None)
        if self.on_host_evict is not None:
            self.on_host_evict(host_id)
        orphaned: list[Peer] = []
        for task in self.tasks.values():
            gone = [p for p in task.peers.values() if p.host.id == host_id]
            for peer in gone:
                children = task.dag.children(peer.id)
                task.remove_peer(peer.id)
                for cid in children:
                    child = task.peers.get(cid)
                    if child is not None and not child.is_done():
                        orphaned.append(child)
        return orphaned

    # -- GC ------------------------------------------------------------

    def state_bytes(self) -> int:
        """Bytes of cluster state of record (tasks, peers, hosts, DAGs)
        for the /debug/ctrl bytes-per-peer accounting — the number that
        decides whether a 10k-daemon fleet fits one asyncio brain. Deep
        sizeof walk over the full object graph (O(peers); the visited
        set keeps the Peer<->Task<->Host cross-references from double
        counting) — snapshot cadence only, never on a ruling path."""
        from ..common.sizeof import deep_sizeof
        seen: set = set()
        return sum(deep_sizeof(o, seen)
                   for o in (self.tasks, self.hosts))

    def gc(self) -> int:
        """Evict idle peers, empty/expired tasks, and silent hosts."""
        now = time.time()
        evicted = 0
        for task in list(self.tasks.values()):
            for peer in list(task.peers.values()):
                idle = now - peer.updated_at
                if (peer.is_done() and idle > 300.0) or idle > self.peer_ttl_s:
                    task.remove_peer(peer.id)
                    evicted += 1
            if not task.peers and now - task.updated_at > self.task_ttl_s:
                del self.tasks[task.id]
                if self.on_task_evict is not None:
                    self.on_task_evict(task.id)
                evicted += 1
        for host in list(self.hosts.values()):
            if now - host.updated_at > self.host_ttl_s:
                del self.hosts[host.id]
                if self.on_host_evict is not None:
                    self.on_host_evict(host.id)
                evicted += 1
        return evicted
