"""Seed-peer control: trigger the root of the piece tree to back-source.

Role parity: reference ``scheduler/resource/seed_peer.go`` ``TriggerTask``
(:101) — the scheduler opens ``ObtainSeeds`` on a seed daemon and folds the
resulting piece announcements into its resource state, so the seed becomes a
schedulable parent while it is still downloading.
"""

from __future__ import annotations

import asyncio
import logging

from ..idl.messages import Host as HostMsg
from ..idl.messages import HostType, ObtainSeedsRequest, UrlMeta
from ..rpc.balancer import HashRing
from ..rpc.client import ChannelPool, ServiceClient
from .config import SeedPeerAddr
from .resource import Peer, PeerState, Resource, Task

log = logging.getLogger("df.sched.seed")

SEEDER_SERVICE = "df.daemon.Seeder"


class SeedPeerClient:
    def __init__(self, resource: Resource, seed_peers: list[SeedPeerAddr],
                 *, tls: tuple[str, str, str] | None = None,
                 quarantine=None):
        """``tls``: (cert, key, ca) fleet material — security-enabled seed
        daemons serve their rpc port over mTLS, and a plaintext trigger
        would silently fail every seed fleet-wide. ``quarantine``:
        registry consulted at seed ELECTION — injecting content through a
        quarantined (possibly bit-rotted) seed would poison the root of
        the whole distribution tree."""
        self.resource = resource
        self.quarantine = quarantine
        self.seed_peers = {self._host_id(s): s for s in seed_peers}
        self._ring = HashRing(list(self.seed_peers))
        if tls is not None:
            cert, key, ca = tls
            self._channels = ChannelPool(limit=32, tls_ca=ca,
                                         tls_cert=cert, tls_key=key)
        else:
            self._channels = ChannelPool(limit=32)

    @staticmethod
    def _host_id(s: SeedPeerAddr) -> str:
        return s.host_id or f"seed-{s.ip}:{s.rpc_port}"

    def available(self) -> bool:
        return bool(self.seed_peers)

    def _elect(self, task_id: str) -> str | None:
        """Seed election: the hashed member, walking clockwise past any
        QUARANTINED seed (a poisoned root poisons the whole tree). With
        every member quarantined the hashed one still serves — a wholly
        quarantined seed fleet beats no injection path at all, and each
        corrupt verdict it earns keeps it excluded everywhere else.
        The walk itself is ``federation.walk_ring`` — the SAME election
        the cross-pod plane runs per (task, pod), so both tiers of the
        distribution tree skip poisoned roots identically."""
        if self.quarantine is None:
            return self._ring.pick(task_id)
        from .federation import walk_ring
        picked = walk_ring(self._ring, task_id, len(self.seed_peers),
                           self.quarantine)
        return picked[0] if picked else None

    # ------------------------------------------------------------------

    async def trigger(self, task: Task, url_meta: UrlMeta | None) -> None:
        """Run one seed download to completion, folding piece announcements
        into the task as they arrive. Exceptions are contained: a failed
        seed leaves the task unseeded and peers fall back to origin."""
        hid = self._elect(task.id)
        if hid is None:
            return
        seed = self.seed_peers[hid]
        host = self.resource.store_host(HostMsg(
            id=hid, ip=seed.ip, hostname=hid, port=seed.rpc_port,
            download_port=seed.download_port, type=HostType.SUPER_SEED,
            concurrent_upload_limit=0))  # 0 = auto -> seed_upload_limit
        client = ServiceClient(self._channels.get(f"{seed.ip}:{seed.rpc_port}"),
                               SEEDER_SERVICE)
        seed_peer: Peer | None = None
        try:
            stream = client.unary_stream("ObtainSeeds", ObtainSeedsRequest(
                url=task.url, url_meta=url_meta, task_id=task.id))
            async for piece_seed in stream:
                if seed_peer is None:
                    peer_id = piece_seed.peer_id or f"{hid}-seedpeer"
                    seed_peer = self.resource.get_or_create_peer(
                        peer_id, task, host)
                    if seed_peer.state == PeerState.PENDING:
                        seed_peer.transit(PeerState.RUNNING)
                task.set_content_info(piece_seed.content_length, 0,
                                      piece_seed.total_piece_count)
                if piece_seed.piece_info is not None:
                    task.record_piece(piece_seed.piece_info)
                    seed_peer.finished_pieces.add(
                        piece_seed.piece_info.piece_num)
                    seed_peer.touch()
                if piece_seed.done:
                    seed_peer.transit(PeerState.SUCCEEDED)
                    log.info("seed %s complete for task %s (%d pieces)",
                             hid, task.id[:12], len(seed_peer.finished_pieces))
                    return
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - seed failure is survivable
            log.warning("seed trigger for task %s failed: %s", task.id[:12], exc)
            if seed_peer is not None and not seed_peer.is_done():
                try:
                    seed_peer.transit(PeerState.FAILED)
                except Exception:  # noqa: BLE001
                    pass

    async def close(self) -> None:
        await self._channels.close()
