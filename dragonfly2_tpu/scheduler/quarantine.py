"""Pod-wide peer quarantine registry: the scheduler half of the swarm
immune system.

Role parity: none in the reference — Dragonfly2's scheduler sees a failed
piece as a generic ``ok=False``, blocklists the pair for ten seconds, and
keeps offering the same host to everyone else; its only long-term ejector
(``IsBadNode``) is per-task statistical *slowness*, which a bit-rotted or
byzantine daemon serving corrupt bytes at full speed never trips. This
registry promotes HARD evidence — typed ``corrupt`` verdicts
(``PieceResult.fail_code``), aggregated per HOST across every task and
reporter, plus a daemon's own self-quarantine flag — into an explicit
per-host ladder:

    healthy ──corrupt verdict──▶ suspect ──≥ threshold──▶ quarantined
       ▲                                                      │
       │◀──probe successes── probation ◀──probation delay─────┘

* **healthy** — offerable everywhere (the default; unknown hosts never
  allocate registry state).
* **suspect** — some decayed corrupt evidence, below the threshold:
  still offerable (the evaluator/blocklist handle it), but counted.
* **quarantined** — evidence reached ``corrupt_threshold`` (or the host
  self-quarantined): excluded from offers (``EXCLUSION_REASONS``
  ``quarantined``), relay-tree shaping, and seed election, pod-wide.
* **probation** — ``probation_delay_s`` after the last evidence, the
  host earns bounded reprieve probes: it may be offered to at most
  ``probe_children`` concurrent children (one low-stakes piece each).
  ``probe_successes`` clean verdicts climb it back to healthy without an
  operator; one more corrupt verdict sends it straight back to
  quarantined with the timer reset.

Every transition is emitted as a ``kind=decision`` row
(``decision_kind="quarantine"``) through the same sink the scheduling
ledger uses, so rulings are replayable offline (dfsched / the records
JSONL) and visible live at ``/debug/decisions``.

Evidence decays (half-life) on an injectable clock, so the registry is a
pure deterministic function of (verdict stream, clock) — dfbench drives
it on a virtual clock and the committed BENCH_pr12 numbers replay
byte-identically.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

from ..common.metrics import REGISTRY

log = logging.getLogger("df.sched.quarantine")

HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"
STATES = (HEALTHY, SUSPECT, QUARANTINED, PROBATION)

_transitions = REGISTRY.counter(
    "df_quarantine_transitions_total",
    "quarantine-ladder state transitions, by the state entered", ("to",))
_hosts_gauge = REGISTRY.gauge(
    "df_quarantine_hosts",
    "hosts currently in each non-healthy quarantine-ladder state",
    ("state",))
_evidence = REGISTRY.counter(
    "df_quarantine_verdicts_total",
    "corrupt piece verdicts recorded as quarantine evidence")
_probes = REGISTRY.counter(
    "df_quarantine_probes_total",
    "probation reprieve-probe outcomes", ("result",))


class _HostLadder:
    __slots__ = ("state", "corrupt", "relayed", "at", "reporters", "tasks",
                 "last_evidence", "entered_at", "probe_children",
                 "probe_ok", "self_flagged", "reason")

    def __init__(self, now: float) -> None:
        self.state = HEALTHY
        self.corrupt = 0.0            # decayed DIRECT corrupt-verdict mass
        self.relayed = 0.0            # decayed cut-through corrupt mass:
        # circumstantial (the bytes originated upstream of this host) —
        # reaches `suspect`, NEVER `quarantined` on its own
        self.at = now                 # decay anchor
        self.reporters: set[str] = set()
        self.tasks: set[str] = set()
        self.last_evidence = now
        self.entered_at = now         # when the current state was entered
        # children currently granted a probe slot -> grant time: a
        # grant EXPIRES if the child never actually fetches from the
        # host (its dispatcher may simply prefer other parents), or a
        # stuck grant would hold the bounded probe budget forever and
        # the host could never be reprieved (found by the live drive)
        self.probe_children: dict[str, float] = {}
        self.probe_ok = 0
        self.self_flagged = False
        self.reason = ""

    def decay(self, now: float, halflife_s: float) -> None:
        if halflife_s > 0:
            factor = 0.5 ** (max(now - self.at, 0.0) / halflife_s)
            self.corrupt *= factor
            self.relayed *= factor
            if self.corrupt < 0.01:
                self.corrupt = 0.0
            if self.relayed < 0.01:
                self.relayed = 0.0
        self.at = now


class QuarantineRegistry:
    """Per-host quarantine ladder with decision-ledger emission.

    ``sink`` receives one ``kind=decision`` row per transition (the
    scheduler wires the DecisionLedger's ``on_decision``); ``clock`` is
    injectable so dfbench replays the ladder on its virtual clock.
    """

    def __init__(self, *, corrupt_threshold: float = 3.0,
                 halflife_s: float = 600.0,
                 probation_delay_s: float = 30.0,
                 probe_successes: int = 2,
                 probe_children: int = 1,
                 min_reporters: int = 2,
                 sink: Callable[[dict], None] | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.corrupt_threshold = corrupt_threshold
        self.halflife_s = halflife_s
        self.probation_delay_s = probation_delay_s
        self.probe_successes = probe_successes
        self.probe_children = probe_children
        # the report-plane anti-slander rule: the QUARANTINED transition
        # needs corrupt evidence from at least this many DISTINCT
        # reporting hosts — one faulty (bad RAM on its receive side) or
        # byzantine CHILD forging corrupt reports must not be able to
        # serially evict the pod's honest parents; a single reporter
        # tops out at `suspect`. Reporterless verdicts (offline tools,
        # sims) count as one anonymous reporter. Probation regression is
        # exempt (the host carries a prior multi-reporter conviction).
        self.min_reporters = max(1, min_reporters)
        self.sink = sink
        self.clock = clock
        self._hosts: dict[str, _HostLadder] = {}
        self._seq = 0

    # -- transitions ---------------------------------------------------

    def _get(self, host_id: str) -> _HostLadder:
        h = self._hosts.get(host_id)
        if h is None:
            h = self._hosts[host_id] = _HostLadder(self.clock())
        return h

    def _transit(self, host_id: str, h: _HostLadder, to: str,
                 why: str) -> None:
        frm = h.state
        if frm == to:
            return
        h.state = to
        h.entered_at = self.clock()
        h.probe_children.clear()
        h.probe_ok = 0
        _transitions.labels(to).inc()
        self._export()
        log.warning("quarantine: host %s %s -> %s (%s)", host_id[-28:],
                    frm, to, why)
        if self.sink is not None:
            self._seq += 1
            self.sink({
                "kind": "decision",
                "decision_kind": "quarantine",
                "decision_id": f"q{self._seq:08d}.{host_id[-12:]}",
                "host_id": host_id,
                "from_state": frm,
                "to_state": to,
                "why": why,
                "corrupt_evidence": round(h.corrupt, 3),
                "reporters": sorted(h.reporters),
                "tasks": len(h.tasks),
                "self_flagged": h.self_flagged,
                # the scheduling rows' fields, empty, so every ledger
                # consumer (stitch, dfsched, /debug/decisions filters)
                # reads quarantine rulings without special cases
                "task_id": "",
                "peer_id": "",
                "candidates": [],
                "excluded": [],
                "chosen": [],
            })

    def _export(self) -> None:
        counts = {s: 0 for s in STATES if s != HEALTHY}
        for h in self._hosts.values():
            if h.state != HEALTHY:
                counts[h.state] += 1
        for state, n in counts.items():
            _hosts_gauge.labels(state).set(n)

    # -- evidence (called from the piece-report path) -------------------

    def record_corrupt(self, host_id: str, *, task_id: str = "",
                       reporter: str = "", relayed: bool = False) -> None:
        """One verified ``corrupt`` piece verdict against ``host_id``
        (cross-task, cross-reporter — the evidence the ladder promotes).

        ``relayed`` (PieceResult.relayed — the transfer rode the host's
        cut-through path): CIRCUMSTANTIAL, kept in its own counter that
        can reach `suspect` but NEVER `quarantined` — the bytes
        originated upstream of the relay, and promoting relayed mass
        would let one poisoner get every honest relay below it evicted
        (a sophisticated host that poisons ONLY its cut-through windows
        evades eviction but stays suspect/deprioritized, and the moment
        it serves corrupt bytes from disk it earns direct evidence)."""
        if not host_id:
            return
        _evidence.inc()
        now = self.clock()
        h = self._get(host_id)
        h.decay(now, self.halflife_s)
        if task_id:
            h.tasks.add(task_id)
        if reporter:
            h.reporters.add(reporter)
        if relayed:
            h.relayed += 1.0
            if h.state == HEALTHY:
                self._transit(host_id, h, SUSPECT,
                              "relayed-corruption evidence (suspect "
                              "ceiling: circumstantial)")
            return
        h.corrupt += 1.0
        h.last_evidence = now
        if h.state == PROBATION:
            # a probed host that serves corruption again goes straight
            # back — with the timer reset, not a fresh evidence budget
            _probes.labels("corrupt").inc()
            self._transit(host_id, h, QUARANTINED,
                          "corrupt verdict during probation")
        elif h.corrupt >= self.corrupt_threshold \
                and max(len(h.reporters), 1) >= self.min_reporters:
            if h.state != QUARANTINED:
                self._transit(host_id, h, QUARANTINED,
                              f"{h.corrupt:.1f} decayed corrupt verdicts "
                              f"from {len(h.reporters)} reporter(s) over "
                              f"{len(h.tasks)} task(s)")
        elif h.state == HEALTHY:
            self._transit(host_id, h, SUSPECT,
                          "first corrupt verdict (below threshold)")

    def record_ok(self, host_id: str) -> None:
        """A successful piece served by ``host_id``: in probation this is
        a reprieve-probe pass; elsewhere it is just decay time passing."""
        h = self._hosts.get(host_id)
        if h is None:
            return
        if h.state == PROBATION:
            h.probe_ok += 1
            _probes.labels("ok").inc()
            if h.probe_ok >= self.probe_successes:
                h.corrupt = 0.0
                h.reporters.clear()
                h.tasks.clear()
                self._transit(host_id, h, HEALTHY,
                              f"{h.probe_ok} clean probe piece(s)")
        elif h.state == SUSPECT:
            h.decay(self.clock(), self.halflife_s)
            if h.corrupt <= 0.0 and h.relayed <= 0.0:
                self._transit(host_id, h, HEALTHY, "evidence decayed")

    def record_self(self, host_id: str, flagged: bool,
                    *, reason: str = "") -> None:
        """The host's own register/announce carried (or cleared) the
        ``Host.quarantined`` self-flag — first-hand evidence from the
        daemon itself (boot re-verify / placement re-hash failed)."""
        if not host_id:
            return
        if flagged:
            h = self._get(host_id)
            h.self_flagged = True
            h.reason = reason or "self-quarantine flag on announce"
            h.last_evidence = self.clock()
            if h.state != QUARANTINED:
                self._transit(host_id, h, QUARANTINED, h.reason)
            return
        h = self._hosts.get(host_id)
        if h is not None and h.self_flagged:
            # the flag cleared (daemon restarted and re-verified clean):
            # the host still walks back through probation like everyone
            # else — a clean boot says nothing about the bytes it serves
            h.self_flagged = False
            if h.state == QUARANTINED:
                self._transit(host_id, h, PROBATION,
                              "self-quarantine flag cleared")

    # -- queries (the scheduling filter / seed election) ----------------

    def state(self, host_id: str) -> str:
        """Current ladder state, with the lazy quarantine→probation
        promotion applied (time-based: no ticker to wire or leak)."""
        h = self._hosts.get(host_id)
        if h is None:
            return HEALTHY
        if (h.state == QUARANTINED and not h.self_flagged
                and self.clock() - h.last_evidence
                >= self.probation_delay_s):
            self._transit(host_id, h, PROBATION,
                          f"{self.probation_delay_s:.0f}s without fresh "
                          f"evidence")
        return h.state

    def offerable(self, host_id: str, child_id: str = "") -> bool:
        """May ``host_id`` be offered as a parent to ``child_id``?

        healthy/suspect: yes. quarantined: no. probation: only within
        the bounded probe budget — at most ``probe_children`` concurrent
        children get it (one low-stakes exposure each); everyone else
        keeps being steered around it until the probes settle it."""
        st = self.state(host_id)
        if st in (HEALTHY, SUSPECT):
            return True
        if st == QUARANTINED:
            return False
        h = self._hosts[host_id]
        now = self.clock()
        for cid in [c for c, at in h.probe_children.items()
                    if now - at > self.probation_delay_s]:
            del h.probe_children[cid]      # expired grant frees the slot
        if child_id and child_id in h.probe_children:
            return True
        if len(h.probe_children) < self.probe_children:
            if child_id:
                h.probe_children[child_id] = now
            return True
        return False

    def quarantined_hosts(self) -> list[str]:
        return sorted(hid for hid in self._hosts
                      if self.state(hid) == QUARANTINED)

    # -- debug surface ---------------------------------------------------

    def state_bytes(self) -> int:
        """Bytes of quarantine state (per-host ladders, evidence sets)
        for the /debug/ctrl bytes-per-peer accounting. Deep sizeof walk
        — snapshot cadence only, never on a ruling path."""
        from ..common.sizeof import deep_sizeof
        return deep_sizeof(self._hosts)

    def snapshot(self) -> dict:
        now = self.clock()
        hosts = {}
        for hid, h in self._hosts.items():
            st = self.state(hid)
            if st == HEALTHY and h.corrupt <= 0.0 and h.relayed <= 0.0:
                continue              # fully recovered: no row to read
            h.decay(now, self.halflife_s)
            hosts[hid] = {
                "state": st,
                "corrupt_evidence": round(h.corrupt, 3),
                "relayed_evidence": round(h.relayed, 3),
                "reporters": len(h.reporters),
                "tasks": len(h.tasks),
                "self_flagged": h.self_flagged,
                "probe_ok": h.probe_ok,
                "probing_children": len(h.probe_children),
                "since_s": round(max(now - h.entered_at, 0.0), 1),
            }
        return {
            "corrupt_threshold": self.corrupt_threshold,
            "probation_delay_s": self.probation_delay_s,
            "probe_successes": self.probe_successes,
            "hosts": hosts,
        }

    # -- durable state (scheduler/statestore.py) -------------------------

    def export_state(self) -> dict:
        """The crash-survivable half of the ladder, decayed to now and
        anchored in AGES (seconds before the export), never in absolute
        monotonic stamps — a restarted process has a different monotonic
        origin, and the statestore adds the wall-clock downtime gap on
        restore so decay keeps running while the scheduler is down."""
        now = self.clock()
        hosts = {}
        for hid, h in self._hosts.items():
            h.decay(now, self.halflife_s)
            if h.state == HEALTHY and h.corrupt <= 0.0 and h.relayed <= 0.0:
                continue              # fully recovered: nothing to carry
            hosts[hid] = {
                "state": h.state,
                "corrupt": round(h.corrupt, 6),
                "relayed": round(h.relayed, 6),
                "reporters": sorted(h.reporters),
                "tasks": sorted(h.tasks),
                "last_evidence_age_s": round(max(now - h.last_evidence,
                                                 0.0), 3),
                "entered_age_s": round(max(now - h.entered_at, 0.0), 3),
                "probe_ok": h.probe_ok,
                "self_flagged": h.self_flagged,
                "reason": h.reason,
            }
        return {"seq": self._seq, "hosts": hosts}

    def restore(self, state: dict, *, gap_s: float = 0.0) -> int:
        """Rebuild the ladder from :meth:`export_state` output. ``gap_s``
        is the wall-clock downtime between export and now: evidence ages
        by ``age + gap`` so the lazy decay arithmetic lands exactly where
        an uninterrupted registry would (a suspect whose evidence crosses
        the decay horizon during the outage comes back HEALTHY — its
        entry is simply dropped, unknown hosts being healthy by default).

        QUARANTINED hosts are the one deliberate exception: their
        probation timer restarts at recovery (``last_evidence = now``)
        instead of aging through the gap — no probe could possibly have
        run while the brain was down, and a poisoner must never walk
        itself into offerable probation on the strength of the
        scheduler's own outage. Restores are silent (no ledger rows, no
        transition counters): nothing here is a fresh ruling."""
        now = self.clock()
        gap = max(float(gap_s), 0.0)
        restored = 0
        for hid, row in (state.get("hosts") or {}).items():
            h = _HostLadder(now)
            h.state = row.get("state", SUSPECT)
            if h.state not in STATES:
                continue
            h.corrupt = float(row.get("corrupt", 0.0))
            h.relayed = float(row.get("relayed", 0.0))
            # the export decayed evidence to export time; anchoring the
            # decay clock `gap` in the past makes the next decay() charge
            # the downtime too
            h.at = now - gap
            h.decay(now, self.halflife_s)
            h.reporters = set(row.get("reporters") or ())
            h.tasks = set(row.get("tasks") or ())
            h.probe_ok = int(row.get("probe_ok", 0))
            h.self_flagged = bool(row.get("self_flagged", False))
            h.reason = row.get("reason", "")
            if h.state == QUARANTINED:
                h.last_evidence = now
                h.entered_at = now
            else:
                h.last_evidence = now - (
                    float(row.get("last_evidence_age_s", 0.0)) + gap)
                h.entered_at = now - (
                    float(row.get("entered_age_s", 0.0)) + gap)
                if h.state == SUSPECT and h.corrupt <= 0.0 \
                        and h.relayed <= 0.0:
                    continue          # decayed across the outage: healthy
            self._hosts[hid] = h
            restored += 1
        self._seq = max(self._seq, int(state.get("seq", 0)))
        self._export()
        return restored

    def import_summary(self, state: dict, *, source: str = "") -> int:
        """Failover handoff import — the PR 12 anti-slander rule applied
        to second-hand state: a demoted scheduler's exported summary
        warms the successor's ladder to at most SUSPECT. Imported mass
        lands in the RELAYED (circumstantial) counter, which by
        construction can never cross into QUARANTINED — only fresh
        first-hand corrupt reports arriving at THIS scheduler can evict.
        Reporter identities are deliberately not imported (carrying them
        over would let a forged blob pre-stage ``min_reporters``)."""
        imported = 0
        now = self.clock()
        for hid, row in (state.get("hosts") or {}).items():
            mass = float(row.get("corrupt", 0.0)) \
                + float(row.get("relayed", 0.0))
            if mass <= 0.0 and row.get("state") == HEALTHY:
                continue
            h = self._get(hid)
            h.decay(now, self.halflife_s)
            h.relayed += min(mass, self.corrupt_threshold) or 1.0
            if h.state == HEALTHY:
                self._transit(hid, h, SUSPECT,
                              f"imported verdict from {source or 'peer'} "
                              "(anti-slander: suspect ceiling)")
            imported += 1
        return imported
