"""Scheduler gRPC service: register / report / announce / probes / leave.

Role parity: reference ``scheduler/service/service_v1.go`` — RegisterPeerTask
with size-scope dispatch (:1005-1110), the ReportPieceResult bidi stream
driving reschedules (:187), piece success/failure handlers (:1159, :1210),
AnnounceHost (:478), SyncProbes (:688), StatTask, LeaveHost/LeavePeer.

Back-source arbitration (SURVEY §7 hard part): a child with no viable
parents is NOT immediately sent to origin — if a seed trigger is in flight
the scheduler retries on a short interval and only rules NeedBackSource when
patience runs out or no seed exists. Encoded in ``_schedule_with_patience``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import AsyncIterator

from ..common import phasetimer
from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import (AnnounceContentRequest, AnnounceContentResponse,
                            AnnounceHostRequest, AnnounceHostResponse,
                            Empty, HostType,
                            LeaveHostRequest,
                            LeavePeerRequest, PeerPacket, PeerResult,
                            PieceResult, Priority, RegisterPeerTaskRequest,
                            RegisterResult, SinglePiece, SizeScope,
                            StatTaskRequest, SyncProbesResponse, TaskStat,
                            ProbeTarget)
from ..rpc.server import ServiceDef, span_parent
from .cluster_view import ClusterView
from .config import SchedulerConfig
from .resource import Peer, PeerState, Resource, TaskState
from .scheduling import Scheduling
from .seed_client import SeedPeerClient
from .topology_store import TopologyStore

log = logging.getLogger("df.sched.service")

SCHEDULER_SERVICE = "df.scheduler.Scheduler"

_registers = REGISTRY.counter("df_sched_register_total",
                              "peer task registrations", ("scope",))
_schedules = REGISTRY.counter("df_sched_schedule_total",
                              "scheduling decisions", ("kind",))
_piece_reports = REGISTRY.counter("df_sched_piece_report_total",
                                  "piece results received", ("result",))
_quota_sheds = REGISTRY.counter(
    "df_qos_quota_shed_total",
    "registers rejected by a tenant's max_running quota "
    "(RESOURCE_EXHAUSTED + retry-after; HTTP surfaces answer 429)",
    ("tenant",))
_recovery_announces = REGISTRY.counter(
    "df_sched_recovery_announces_total",
    "daemon content re-announces after a scheduler epoch change, by "
    "outcome (adopted = holdings merged into the resource view, "
    "rejected = torn/unsealed digest refused wholesale)", ("result",))

SCHEDULE_RETRY_INTERVAL_S = 0.25
SCHEDULE_PATIENCE_S = 10.0
# re-fires of a broken seed trigger per task (seed daemon death/restart);
# each retry is one ObtainSeeds RPC, so the cap bounds origin pressure from
# a permanently-down seed fleet while letting a restarted seed resume.
# Exponential backoff between fires (1,2,4,...s capped) makes the budget
# span a realistic daemon restart (~tens of seconds: process re-exec +
# imports + topology probe) instead of burning out in 2.5s of refresh ticks
SEED_RETRIGGER_LIMIT = 6
SEED_RETRIGGER_BACKOFF_CAP_S = 30.0


class SchedulerService:
    def __init__(self, cfg: SchedulerConfig, resource: Resource,
                 scheduling: Scheduling, seed_client: SeedPeerClient,
                 topo: TopologyStore, *, records=None, ledger=None,
                 quarantine=None, federation=None, fleetpulse=None):
        self.cfg = cfg
        self.resource = resource
        self.scheduling = scheduling
        self.seed_client = seed_client
        self.topo = topo
        self.records = records          # download-record sink (trainer dataset)
        self.ledger = ledger            # decision ledger (GET /debug/decisions)
        # quarantine registry (scheduler/quarantine.py): fed corrupt
        # verdicts + self-flags here, consulted by the scheduling filter
        # and seed election; None = the pre-quarantine fabric
        self.quarantine = quarantine
        # cross-pod federation view (scheduler/federation.py): fed host
        # pods from register/announce, forgets on leave; None = the
        # pre-federation single-pod fabric
        self.federation = federation
        # fleet pulse plane (scheduler/fleetpulse.py): announce-borne
        # telemetry digests land here; None = pulse plane disabled
        self.fleetpulse = fleetpulse
        self.cluster = ClusterView(ledger=ledger,
                                   quarantine=quarantine)  # GET /debug/cluster
        self._seed_tasks: set[asyncio.Task] = set()
        # application name -> Priority numeric, fed from the manager's
        # applications table (reference dynconfig.GetApplications); consulted
        # when a request carries no explicit priority
        self.applications: dict[str, int] = {}
        # tenant name -> quota row ({"qos_class", "max_running",
        # "shed_retry_after_ms"}), fed from the manager's tenants table
        # over the same dynconfig cadence; enforced at register
        self.tenants: dict[str, dict] = {}
        # boot epoch, echoed on register/announce so daemons detect a
        # restart and re-announce held content (AnnounceContent). The
        # wall-clock default changes on every restart even without a
        # statestore; a restore overrides it with snapshot-epoch + 1 so
        # it is strictly increasing across durable restarts.
        self.epoch = int(time.time())
        self._recovery_seq = 0

    # ------------------------------------------------------------------
    # RegisterPeerTask
    # ------------------------------------------------------------------

    async def register_peer_task(self, req: RegisterPeerTaskRequest,
                                 context) -> RegisterResult:
        from ..common import tracing
        # the daemon's traceparent rides the RPC metadata: the scheduling
        # decision joins the task trace that also covers the piece fetches
        # and the HBM landing
        with tracing.span("sched.register", parent=span_parent(context),
                          task_id=req.task_id[:16],
                          peer_id=req.peer_id[-16:]):
            return await self._register_peer_task(req, context)

    async def _register_peer_task(self, req: RegisterPeerTaskRequest,
                                  context) -> RegisterResult:
        if not req.task_id or not req.peer_id or req.peer_host is None:
            raise DFError(Code.INVALID_ARGUMENT,
                          "task_id, peer_id, peer_host required")
        task = self.resource.get_or_create_task(req.task_id, req.url)
        if task.state in (TaskState.SUCCEEDED, TaskState.FAILED):
            task.transit(TaskState.RUNNING)
        elif task.state == TaskState.PENDING:
            task.transit(TaskState.RUNNING)
        qos_class, tenant = self._resolve_class(req.url_meta)
        resolved_priority = self._resolve_priority(req.url_meta,
                                                   qos_class=qos_class)
        if resolved_priority == int(Priority.LEVEL1):
            # reference service_v2.go: LEVEL1 = download forbidden. Checked
            # BEFORE peer creation: a forbidden client retrying in a loop
            # must not grow a PENDING peer per attempt until the 24h TTL
            raise DFError(Code.SCHED_FORBIDDEN,
                          "download forbidden by priority (LEVEL1)")
        # manager-enforced per-tenant quota, checked BEFORE peer creation
        # for the same reason as LEVEL1: a quota-storming tenant must not
        # grow a PENDING peer per shed. Raises RESOURCE_EXHAUSTED with a
        # retry-after hint — the common/retry.py ladder honors it and the
        # proxy/gateway surface it as HTTP 429 + Retry-After. Seed hosts
        # are EXEMPT: the seed's ObtainSeeds register replays the
        # client's UrlMeta (tenant included), and infrastructure
        # injection billed to the tenant would shed the very pull that
        # lets the admitted download complete P2P.
        if req.peer_host.type == HostType.NORMAL:
            self._enforce_tenant_quota(tenant)
        if self.quarantine is not None:
            # the self-quarantine flag rides every register too: a daemon
            # that found its own bit-rot is excluded as a parent from its
            # FIRST contact, not from its next announce interval
            self.quarantine.record_self(
                req.peer_host.id, req.peer_host.quarantined,
                reason="self-quarantine flag on register")
        if self.federation is not None:
            # the federation view learns the host's pod from its FIRST
            # contact too — per-pod seed elections need the membership
            # before the first cross-pod ruling, not an announce later
            self.federation.observe_host(req.peer_host.id,
                                         req.peer_host.topology)
        host = self.resource.store_host(req.peer_host)
        peer = self.resource.get_or_create_peer(req.peer_id, task, host)
        peer.priority = resolved_priority
        peer.qos_class = qos_class
        peer.tenant = tenant
        if peer.state == PeerState.PENDING:
            peer.transit(PeerState.RUNNING)

        # first peer of an unseeded task: fire the seed trigger. LEVEL2
        # peers are about to be ruled straight to origin — triggering the
        # seed too would pull the content from origin TWICE
        if task.url_meta is None:
            task.url_meta = req.url_meta
        if (not task.seed_triggered and self.seed_client.available()
                and resolved_priority != int(Priority.LEVEL2)
                and not task.has_available_peer()):
            self._fire_seed_trigger(task, req.url_meta)

        scope = task.size_scope()
        result = RegisterResult(task_id=task.id, size_scope=SizeScope.NORMAL,
                                content_length=task.content_length,
                                piece_size=task.piece_size,
                                resolved_priority=Priority(resolved_priority),
                                scheduler_epoch=self.epoch)
        if scope == SizeScope.EMPTY:
            result.size_scope = SizeScope.EMPTY
        elif scope == SizeScope.TINY:
            result.size_scope = SizeScope.TINY
            result.direct_content = task.direct_content
        elif scope == SizeScope.SMALL:
            single = self._single_piece_parent(peer)
            if single is not None:
                result.size_scope = SizeScope.SMALL
                result.single_piece = single
        if req.url_meta is not None and req.url_meta.shards:
            # sharded task: rule this peer's disjoint tree-fetch subset
            # of its requested shards (decision_kind=shard rides the
            # ledger); the rest arrive by ICI-near swap from co-located
            # replicas. None (arm disabled) leaves the field off the
            # wire and the daemon tree-fetches everything it requested.
            from ..common.sharding import parse_shard_names
            names = parse_shard_names(req.url_meta.shards)
            result.assigned_shards = self.scheduling.shard_assignment(
                peer, names)
        _registers.labels(result.size_scope.name).inc()
        return result

    def _single_piece_parent(self, child: Peer) -> SinglePiece | None:
        info = child.task.pieces.get(0)
        if info is None:
            return None
        parents = self.scheduling.find_parents(child)
        if not parents:
            return None
        p = parents[0]
        return SinglePiece(
            dst_peer_id=p.id,
            dst_addr=f"{p.host.msg.ip}:{p.host.msg.download_port}",
            piece_info=info)

    # ------------------------------------------------------------------
    # ReportPieceResult (bidi stream)
    # ------------------------------------------------------------------

    async def report_piece_result(self, request_iter,
                                  context) -> AsyncIterator[PeerPacket]:
        first: PieceResult | None = None
        async for msg in request_iter:
            first = msg
            break
        if first is None:
            return
        peer = self.resource.find_peer(first.task_id, first.src_peer_id)
        if peer is None:
            raise DFError(Code.SCHED_REREGISTER,
                          f"unknown peer {first.src_peer_id[-12:]}")
        sink: asyncio.Queue[PeerPacket | None] = asyncio.Queue()
        peer.packet_sink = sink
        peer.stream_gone = False      # live again: a fresh report stream

        async def consume() -> None:
            try:
                async for result in request_iter:
                    await self._handle_piece_result(peer, result)
                log.debug("report stream from %s: clean EOF", peer.id[-12:])
            except Exception as exc:  # noqa: BLE001 - client went away
                log.debug("report stream from %s ended: %s",
                          peer.id[-12:], exc)
            finally:
                sink.put_nowait(None)

        consumer = asyncio.get_running_loop().create_task(consume())
        scheduler_task = asyncio.get_running_loop().create_task(
            self._schedule_with_patience(peer, sink))
        refresher = asyncio.get_running_loop().create_task(
            self._refresh_loop(peer))
        # the daemon opened this stream inside its peertask span: mark the
        # first offer (parents or back-source verdict) in that trace
        from ..common import tracing
        offer_parent = span_parent(context)
        first_offer = True
        try:
            while True:
                packet = await sink.get()
                if packet is None:
                    break
                if first_offer:
                    first_offer = False
                    with tracing.span("sched.offer", parent=offer_parent,
                                      task_id=peer.task.id[:16],
                                      code=packet.code):
                        pass
                yield packet
                if packet.code == int(Code.SCHED_NEED_BACK_SOURCE):
                    # verdict delivered; the stream stays open for reports
                    continue
        finally:
            scheduler_task.cancel()
            consumer.cancel()
            refresher.cancel()
            await asyncio.gather(consumer, scheduler_task, refresher,
                                 return_exceptions=True)
            if peer.packet_sink is sink:
                peer.packet_sink = None
                if not peer.is_done():
                    # the report stream died with the peer mid-download
                    # (process kill, node loss): a dead peer must stop
                    # being offered as a parent NOW — the chaos e2e showed
                    # survivors stuck with killed victims in their sticky
                    # offer, leaning on the seed for everything the
                    # victims "held". Not a removal: the daemon's final
                    # unary report (or a live peer's fresh stream) still
                    # finds the peer and clears the mark.
                    peer.stream_gone = True
                    log.info("peer %s report stream gone mid-task",
                             peer.id[-12:])
                    if self.federation is not None:
                        # a likely-dead host must stop winning pod-seed
                        # elections NOW (the mid-pull seed-kill failover)
                        # — its next announce re-admits it to the
                        # electorate via observe_host, so a transient
                        # stream wobble costs one announce interval of
                        # electability, while a dead seed's pod re-elects
                        # on its very next ruling
                        self.federation.forget_host(peer.host.id)

    REFRESH_INTERVAL_S = 0.5

    async def _refresh_loop(self, peer: Peer) -> None:
        """Periodic sticky re-offer while the report stream is open: piece
        distribution shifts continuously during a fan-out, and tying
        re-offers to the child's own report cadence (round 3: every 4th
        piece) leaves a slow child stuck with a stale parent set exactly
        when it most needs fresh sources. No-ops (no push) whenever the
        best sticky set is unchanged."""
        while True:
            await asyncio.sleep(self.REFRESH_INTERVAL_S)
            if peer.is_done() or peer.state == PeerState.BACK_SOURCE:
                return
            self._maybe_retrigger_seed(peer.task)
            await self._refresh_parents(peer)
            if (peer.qos_class == "critical" and peer.last_offer_ids
                    and not any(
                        p is not None and p.has_content()
                        for p in (peer.task.peers.get(pid)
                                  for pid in peer.last_offer_ids))):
                # mid-download starvation (every offered parent is a
                # pieceless sibling while content holders sit slot-full
                # behind bulk edges): same preemption rule as the
                # patience loop, on the refresh cadence
                victim = self.scheduling.preempt_for(peer)
                if victim is not None:
                    await self._push_victim_packet(victim)
                    await self._refresh_parents(peer)

    def _resolve_priority(self, url_meta, *,
                          qos_class: str = "standard") -> int:
        """Reference ``Peer.CalculatePriority``: an explicit request value
        wins; LEVEL0 (the unset default) falls through to the manager's
        application table, then to the QoS class's default (``bulk``
        sinks to LEVEL6 so priority-ordered surfaces — storage GC, the
        per-class back-source budget — order it behind foreground without
        new plumbing); unknown applications resolve the class default
        (LEVEL0 for standard, like the reference's LEVEL6/LEVEL0 arm)."""
        from ..idl.messages import CLASS_DEFAULT_PRIORITY
        if url_meta is not None and int(url_meta.priority) != int(Priority.LEVEL0):
            return int(url_meta.priority)
        if url_meta is not None and url_meta.application:
            prio = self.applications.get(url_meta.application)
            if prio is not None:
                return int(prio)
        return CLASS_DEFAULT_PRIORITY.get(qos_class, int(Priority.LEVEL0))

    def _resolve_class(self, url_meta) -> tuple[str, str]:
        """(qos_class, tenant) for a register: the request's explicit
        class wins; a classless request from a known tenant inherits the
        tenant's default class; everything else is ``standard``."""
        from ..idl.messages import PRIORITY_CLASSES, resolve_class
        tenant = url_meta.tenant if url_meta is not None else ""
        raw = url_meta.qos_class if url_meta is not None else ""
        if raw in PRIORITY_CLASSES:
            return raw, tenant
        row = self.tenants.get(tenant) if tenant else None
        if row and row.get("qos_class") in PRIORITY_CLASSES:
            return row["qos_class"], tenant
        return resolve_class(raw), tenant

    TENANT_SHED_RETRY_MS = 2000

    def _enforce_tenant_quota(self, tenant: str) -> None:
        """max_running quota: live (non-terminal, non-stale) peers this
        tenant already has across every task. Computed on demand — a
        register is not hot-path, and a counter maintained across peer
        GC/stream-death edges would drift exactly when it matters."""
        row = self.tenants.get(tenant) if tenant else None
        if not row:
            return
        limit = int(row.get("max_running") or 0)
        if limit <= 0:
            return
        import time as _time
        stale_after = _time.time() - 300.0
        running = 0
        for task in self.resource.tasks.values():
            for p in task.peers.values():
                if p.tenant != tenant or p.is_done() \
                        or p.host.msg.type != HostType.NORMAL:
                    continue
                # a crashed peer's stream is gone and its clock stops;
                # it must not occupy quota until the 24h TTL
                if p.stream_gone or p.updated_at < stale_after:
                    continue
                running += 1
                if running >= limit:
                    _quota_sheds.labels(tenant).inc()
                    exc = DFError(
                        Code.RESOURCE_EXHAUSTED,
                        f"tenant {tenant!r} at max_running={limit}; "
                        f"retry later")
                    exc.retry_after_ms = int(
                        row.get("shed_retry_after_ms") or 0) \
                        or self.TENANT_SHED_RETRY_MS
                    raise exc

    async def _schedule_with_patience(self, peer: Peer,
                                      sink: asyncio.Queue) -> None:
        """Initial scheduling loop: try now, retry while a seed is coming,
        rule back-source when patience ends. LEVEL2 peers skip the P2P
        wait entirely (reference: 'Peer is first to download
        back-to-source')."""
        if peer.priority == int(Priority.LEVEL2):
            packet = self._rule_back_source(peer)
            if packet is not None:
                sink.put_nowait(packet)
            return
        t0 = asyncio.get_running_loop().time()
        deadline = t0 + SCHEDULE_PATIENCE_S
        while True:
            if peer.is_done() or peer.state == PeerState.BACK_SOURCE:
                return
            parents = self.scheduling.find_parents(peer)
            if parents and not any(p.has_content() for p in parents):
                # holderless offer (pieceless siblings only — the filter
                # keeps them for their sync streams): a critical child
                # starving because every content holder is slot-full may
                # evict one bulk edge and re-rule NOW, instead of
                # subscribing to siblings who have nothing to announce
                victim = self.scheduling.preempt_for(peer)
                if victim is not None:
                    await self._push_victim_packet(victim)
                    continue
            if parents:
                if phasetimer.ARMED:
                    # queue-wait: register arrival -> offer landing, minus
                    # nothing — the ruling compute inside is µs against the
                    # 250ms retry ticks that dominate a queued child
                    phasetimer.note_queue_wait(
                        asyncio.get_running_loop().time() - t0)
                peer.schedule_count += 1
                peer.last_offer_ids = {p.id for p in parents}
                peer.task.set_parents(peer.id, [p.id for p in parents])
                _schedules.labels("parents").inc()
                log.debug("offer %s -> parents %s", peer.id[-12:],
                          [p.id[-12:] for p in parents])
                sink.put_nowait(self.scheduling.build_packet(peer, parents))
                return
            # QoS preemption, empty-offer form: no legal parent at all
            victim = self.scheduling.preempt_for(peer)
            if victim is not None:
                await self._push_victim_packet(victim)
                continue
            now = asyncio.get_running_loop().time()
            self._maybe_retrigger_seed(peer.task)
            seed_pending = (peer.task.seed_job is not None
                            and not peer.task.seed_job.done())
            # feeders = content is coming even though no parent is legal
            # RIGHT NOW (seed still origin-pulling, or peers hold pieces but
            # their upload slots are full). Keep retrying: with binding slot
            # limits a cold 16-child fan-out legitimately queues most
            # children for a few hundred ms while the tree's first tier
            # forms — sending them to origin instead would erase the egress
            # savings the mesh exists for.
            feeders = seed_pending or peer.task.has_available_peer()
            if now >= deadline or not feeders:
                packet = self._rule_back_source(peer)
                if packet is not None:
                    sink.put_nowait(packet)
                return
            await asyncio.sleep(SCHEDULE_RETRY_INTERVAL_S)

    def _fire_seed_trigger(self, task, url_meta) -> None:
        """Start (or restart) the seed ObtainSeeds job for a task and track
        it; shared by first-register, preheat, and the mid-task re-trigger."""
        task.seed_triggered = True
        t = asyncio.get_running_loop().create_task(
            self.seed_client.trigger(task, url_meta))
        task.seed_job = t
        self._seed_tasks.add(t)
        t.add_done_callback(self._seed_tasks.discard)

    def _maybe_retrigger_seed(self, task) -> None:
        """The seed daemon can die MID-INJECTION (process kill, node loss):
        its trigger stream breaks and the pieces it never announced exist
        nowhere, so every waiting peer starves no matter how the remaining
        swarm is scheduled — and a disable_back_source fleet has forbidden
        the origin fallback. When the swarm provably cannot complete and no
        trigger is in flight, re-fire it (bounded): a restarted seed
        reloads its piece store and resumes serving within one RPC.
        Checked from each peer's refresh loop and the patience loop."""
        seed_pending = task.seed_job is not None and not task.seed_job.done()
        now = asyncio.get_running_loop().time()
        if (seed_pending or not task.seed_triggered
                or not self.seed_client.available()
                or task.seed_retries >= SEED_RETRIGGER_LIMIT
                or now < task.seed_next_retry_at):
            return
        # cheap gate first: a coverage gap can only open when a peer died
        # or failed, or nobody (live) holds anything — skip the
        # O(peers x pieces) union on healthy 0.5s refresh ticks
        suspect = any(p.stream_gone or p.state in (PeerState.FAILED,
                                                   PeerState.LEAVING)
                      for p in task.peers.values())
        if not suspect and task.has_live_available_peer():
            return
        if task.total_piece_count > 0:
            gap = not task.swarm_can_complete()
        else:
            # seed died before announcing content info: nothing provable
            # about coverage — re-seed only if no LIVE peer holds anything
            gap = not task.has_live_available_peer()
        if not gap:
            return
        task.seed_retries += 1
        task.seed_next_retry_at = now + min(2.0 ** task.seed_retries,
                                            SEED_RETRIGGER_BACKOFF_CAP_S)
        log.warning("task %s has an uncoverable piece gap and no live seed "
                    "job; re-trigger %d/%d", task.id[:12], task.seed_retries,
                    SEED_RETRIGGER_LIMIT)
        self._fire_seed_trigger(task, task.url_meta)

    def _back_source_class_load(self, priority: int) -> int:
        """Active back-source peers that COUNT against a requester of this
        priority: equal-or-higher-priority holders only. Lower-priority
        (numerically greater) holders are invisible, so a LEVEL0 request
        is admitted even when LEVEL6 traffic has filled the budget — the
        admission-side form of slot preemption (origin pulls cannot be
        revoked mid-flight). Computed on demand: rulings are per-peer
        events, not hot-path."""
        import time as _time
        n = 0
        stale_after = _time.time() - 300.0
        for task in self.resource.tasks.values():
            for pid in task.back_source_peers:
                p = task.peers.get(pid)
                if p is None or p.state != PeerState.BACK_SOURCE \
                        or p.priority > priority:
                    continue
                # crashed holders must not wedge the cluster budget for
                # the 24h peer TTL: a dead process is stream_gone within
                # one RPC, and a live back-source peer touches on every
                # piece report — silent for 5 min means gone
                if p.stream_gone or p.updated_at < stale_after:
                    continue
                n += 1
        return n

    def _rule_back_source(self, peer: Peer) -> PeerPacket | None:
        task = peer.task
        if len(task.back_source_peers) >= self.cfg.back_source_concurrent:
            _schedules.labels("busy").inc()
            return PeerPacket(task_id=task.id, src_peer_id=peer.id,
                              code=int(Code.SCHED_TASK_STATUS_ERROR))
        if self._back_source_class_load(peer.priority) >= \
                self.cfg.back_source_total:
            _schedules.labels("busy_global").inc()
            log.info("back-source budget full for priority %d (peer %s)",
                     peer.priority, peer.id[-12:])
            return PeerPacket(task_id=task.id, src_peer_id=peer.id,
                              code=int(Code.SCHED_TASK_STATUS_ERROR))
        try:
            peer.transit(PeerState.BACK_SOURCE)
        except DFError:
            return None
        # slot held only while the peer is actively back-sourcing; released
        # on its terminal peer result or departure so a failed origin fetch
        # cannot permanently exhaust back_source_concurrent
        task.back_source_peers.add(peer.id)
        # no longer fetching from parents: free their upload slots
        task.set_parents(peer.id, [])
        peer.last_offer_ids = set()
        _schedules.labels("back_source").inc()
        return PeerPacket(task_id=task.id, src_peer_id=peer.id,
                          code=int(Code.SCHED_NEED_BACK_SOURCE))

    async def _handle_piece_result(self, peer: Peer,
                                   result: PieceResult) -> None:
        peer.touch()
        task = peer.task
        # endgame duplicate racers both report success for the same piece;
        # the cluster view must count delivered bytes once
        duplicate = (result.success and result.piece_info is not None
                     and result.piece_info.piece_num in peer.finished_pieces)
        if not duplicate:
            self.cluster.on_piece(peer, result)
        if result.success:
            _piece_reports.labels("ok").inc()
            if result.piece_info is not None:
                task.record_piece(result.piece_info)
                peer.finished_pieces.add(result.piece_info.piece_num)
                peer.observe_piece_cost(result.piece_info.download_cost_ms)
            if result.dst_peer_id:
                parent = task.peers.get(result.dst_peer_id)
                if parent is not None:
                    parent.host.observe_upload(True)
                    if self.quarantine is not None:
                        # probation reprieve: a clean piece off this host
                        # counts toward its climb back to healthy
                        self.quarantine.record_ok(parent.host.id)
            if self.records is not None and result.piece_info is not None:
                self.records.on_piece(peer, result)
            # the time-based _refresh_loop handles steady-state re-offers;
            # the one event worth reacting to immediately:
            if len(peer.finished_pieces) == 1:
                # this peer just became a usable parent: top up every child
                # still short on parents NOW — waiting for their own next
                # %4 report would leave the whole early fan-out herded on
                # the seed (the only content-holder at register time)
                for sibling in list(peer.task.peers.values()):
                    if (sibling.id != peer.id and not sibling.is_done()
                            and len(sibling.last_offer_ids)
                            < self.cfg.candidate_parent_limit):
                        await self._refresh_parents(sibling)
            return
        _piece_reports.labels("fail").inc()
        peer.report_fail_count += 1
        if result.dst_peer_id:
            parent = task.peers.get(result.dst_peer_id)
            if parent is not None:
                parent.host.observe_upload(False)
                if (self.quarantine is not None
                        and result.fail_code == "corrupt"):
                    # hard evidence: the child verified the bytes and
                    # they were wrong — promoted cross-task into the
                    # pod-wide ladder (stall/timeout/refused stay
                    # congestion-shaped: blocklist + bad-node only)
                    self.quarantine.record_corrupt(
                        parent.host.id, task_id=task.id,
                        reporter=peer.host.id,
                        relayed=result.relayed)
            peer.block_parent(result.dst_peer_id)
        if self.records is not None:
            # failed pieces get rows too (success=False, typed fail_code):
            # the ledger joins can now learn from failure KIND, which a
            # bare ok=False collapsed
            self.records.on_piece_fail(peer, result)
        # losing a parent: offer a fresh assignment (or the origin)
        await self._reschedule(peer)

    async def _refresh_parents(self, peer: Peer) -> None:
        if (peer.packet_sink is None or peer.is_done()
                or peer.state == PeerState.BACK_SOURCE):
            return
        # STICKY top-up: keep every still-legal current parent and only fill
        # free candidate slots with the best newcomers. A fresh top-4 pick
        # every refresh looks harmless but churns the whole mesh — scores sit
        # within noise of each other, so sets rotate, the daemon tears down
        # the dropped parents' sync streams, and accumulated piece-holder
        # knowledge is thrown away mid-download.
        parents = self.scheduling.refresh_parents(peer)
        if not parents:
            return
        new_ids = {p.id for p in parents}
        # compare against what was last OFFERED, not the DAG (set_parents may
        # have skipped a cycle-forming edge, which would re-push forever)
        if new_ids == peer.last_offer_ids:
            return
        peer.schedule_count += 1
        peer.last_offer_ids = new_ids
        peer.task.set_parents(peer.id, [p.id for p in parents])
        _schedules.labels("refresh").inc()
        log.debug("refresh %s -> parents %s", peer.id[-12:],
                  [p.id[-12:] for p in parents])
        peer.packet_sink.put_nowait(self.scheduling.build_packet(peer, parents))

    async def _push_victim_packet(self, victim: Peer) -> None:
        """Deliver a preempted bulk child its SHRUNK parent set so its
        engine actually tears down the evicted edge (and the in-flight
        pieces on it requeue against the remaining parents — preemption
        re-dispatches work, it never orphans it)."""
        if victim.packet_sink is None:
            return
        parents = [victim.task.peers[pid]
                   for pid in victim.last_offer_ids
                   if pid in victim.task.peers]
        victim.packet_sink.put_nowait(
            self.scheduling.build_packet(victim, parents))

    async def _reschedule(self, peer: Peer) -> None:
        if peer.packet_sink is None or peer.is_done():
            return
        if peer.state == PeerState.BACK_SOURCE:
            return
        parents = self.scheduling.find_parents(peer)
        if not parents:
            victim = self.scheduling.preempt_for(peer)
            if victim is not None:
                await self._push_victim_packet(victim)
                parents = self.scheduling.find_parents(peer)
        if parents:
            peer.schedule_count += 1
            peer.last_offer_ids = {p.id for p in parents}
            peer.task.set_parents(peer.id, [p.id for p in parents])
            _schedules.labels("parents").inc()
            peer.packet_sink.put_nowait(
                self.scheduling.build_packet(peer, parents))
            return
        if peer.report_fail_count >= self.cfg.retry_back_source_limit:
            packet = self._rule_back_source(peer)
            if packet is not None:
                peer.packet_sink.put_nowait(packet)

    # ------------------------------------------------------------------
    # ReportPeerResult — final verdict for one peer's run
    # ------------------------------------------------------------------

    async def report_peer_result(self, result: PeerResult, context) -> Empty:
        peer = self.resource.find_peer(result.task_id, result.peer_id)
        if peer is None:
            return Empty()
        task = peer.task
        task.back_source_peers.discard(peer.id)
        if result.success:
            task.set_content_info(result.content_length, 0,
                                  result.total_piece_count)
            if not peer.is_done():
                peer.transit(PeerState.SUCCEEDED)
            if task.state == TaskState.RUNNING:
                task.transit(TaskState.SUCCEEDED)
        else:
            if not peer.is_done():
                peer.transit(PeerState.FAILED)
        # download over: drop the child's in-edges so its parents' upload
        # slots free up for other children (the DAG keeps the peer as a
        # piece-holder vertex — only the active-transfer edges go)
        task.set_parents(peer.id, [])
        peer.last_offer_ids = set()
        if result.flight_summary:
            self.cluster.on_flight(peer, result.flight_summary)
        if self.records is not None:
            self.records.on_peer(peer, result)
            if result.flight_summary:
                self.records.on_flight(peer, result.flight_summary)
        return Empty()

    # ------------------------------------------------------------------
    # host lifecycle + stat + probes
    # ------------------------------------------------------------------

    async def announce_host(self, req: AnnounceHostRequest,
                            context) -> AnnounceHostResponse:
        if req.host is not None:
            self.resource.store_host(req.host)
            if self.quarantine is not None:
                # flag set -> quarantined (reason self); flag CLEARED on a
                # later announce (restart re-verified clean) -> probation
                self.quarantine.record_self(
                    req.host.id, req.host.quarantined,
                    reason="self-quarantine flag on announce")
            if self.federation is not None:
                # pod id is a pure function of the announced coordinates,
                # so re-announce is a no-op — elections stay sticky
                self.federation.observe_host(req.host.id,
                                             req.host.topology)
            if self.fleetpulse is not None and req.pulse is not None:
                # piggybacked telemetry: ingest is total (never raises)
                # and strictly observational — no ruling path reads it
                self.fleetpulse.ingest(
                    req.host.id, req.pulse,
                    interval_s=float(req.interval_s or 0.0) or 30.0)
        # the heartbeat answer carries the boot epoch: the announce plane
        # doubles as restart detection, so a daemon that never registers
        # still re-announces held content within one announce interval
        return AnnounceHostResponse(scheduler_epoch=self.epoch)

    async def announce_content(self, req: AnnounceContentRequest,
                               context) -> AnnounceContentResponse:
        """Recovery re-announce: a daemon saw the scheduler epoch change
        (restart) or a register failover, and replays what it holds so
        the new brain rebuilds its resource view from the swarm instead
        of ruling the herd back to origin. The sealed digest (the
        daemon's PEX envelope codec) is the authoritative payload —
        torn, unparseable, or version-skewed blobs are refused WHOLESALE
        (the statestore load rule, applied to the announce plane)."""
        from ..daemon.pex import unseal
        body = unseal(req.digest) if req.digest else None
        if req.host is None or body is None:
            _recovery_announces.labels("rejected").inc()
            return AnnounceContentResponse(scheduler_epoch=self.epoch)
        if self.quarantine is not None:
            self.quarantine.record_self(
                req.host.id, req.host.quarantined,
                reason="self-quarantine flag on content re-announce")
        if self.federation is not None:
            self.federation.observe_host(req.host.id, req.host.topology)
        if self.fleetpulse is not None and req.pulse is not None:
            self.fleetpulse.ingest(req.host.id, req.pulse)
        host = self.resource.store_host(req.host)
        adopted = 0
        pieces_learned = 0
        for e in body.get("tasks") or ():
            task_id = e.get("task_id") or ""
            if not task_id:
                continue
            task = self.resource.get_or_create_task(task_id,
                                                    e.get("url") or "")
            task.set_content_info(int(e.get("content_length", -1)),
                                  int(e.get("piece_size", 0)),
                                  int(e.get("total", -1)))
            if task.state == TaskState.PENDING:
                task.transit(TaskState.RUNNING)
            # a synthetic holder peer per (host, task): the recovered
            # brain can offer this daemon as a parent immediately — the
            # piece metadata itself still travels peer-to-peer over the
            # sync streams, exactly as it does for any live parent
            peer_id = f"{host.id}-recov-{task_id[:16]}"
            peer = self.resource.get_or_create_peer(peer_id, task, host)
            if peer.state == PeerState.PENDING:
                peer.transit(PeerState.RUNNING)
            if e.get("done"):
                if peer.state == PeerState.RUNNING:
                    peer.transit(PeerState.SUCCEEDED)
                if task.state == TaskState.RUNNING:
                    task.transit(TaskState.SUCCEEDED)
            else:
                fresh = set(int(p) for p in (e.get("pieces") or ()))
                pieces_learned += len(fresh - peer.finished_pieces)
                peer.finished_pieces |= fresh
            adopted += 1
        _recovery_announces.labels("adopted").inc()
        if self.ledger is not None and adopted:
            # provenance: this slice of the resource view was REBUILT
            # from the swarm, not recovered from the snapshot — the
            # recovery ledger row makes the distinction replayable
            self._recovery_seq += 1
            self.ledger.on_decision({
                "kind": "decision",
                "decision_kind": "recovery",
                "decision_id": f"r{self._recovery_seq:08d}."
                               f"{host.id[-12:]}",
                "host_id": host.id,
                "source": "reannounce",
                "tasks_adopted": adopted,
                "pieces_learned": pieces_learned,
                "scheduler_epoch": self.epoch,
                "task_id": "",
                "peer_id": "",
                "candidates": [],
                "excluded": [],
                "chosen": [],
            })
        return AnnounceContentResponse(scheduler_epoch=self.epoch,
                                       tasks_adopted=adopted)

    async def leave_host(self, req: LeaveHostRequest, context) -> Empty:
        # federation view notified via Resource.on_host_evict inside
        # leave_host: a departed host stops being electable NOW and its
        # pod re-elects on the next ruling (docs/RESILIENCE.md)
        orphans = self.resource.leave_host(req.host_id)
        for child in orphans:
            await self._reschedule(child)
        return Empty()

    async def leave_peer(self, req: LeavePeerRequest, context) -> Empty:
        self.resource.leave_peer(req.task_id, req.peer_id)
        return Empty()

    async def stat_task(self, req: StatTaskRequest, context) -> TaskStat:
        task = self.resource.tasks.get(req.task_id)
        if task is None:
            raise DFError(Code.NOT_FOUND, f"task {req.task_id[:12]} unknown")
        return TaskStat(id=task.id, type=task.task_type,
                        content_length=task.content_length,
                        total_piece_count=task.total_piece_count,
                        state=task.state.value, peer_count=len(task.peers),
                        has_available_peer=task.has_available_peer())

    async def preheat(self, req, context):
        """Warm a URL into the seed layer (reference ``scheduler/job/job.go:152``
        consumes the same verb from the manager's queue)."""
        from ..common import ids
        from ..idl.messages import PreheatResponse, UrlMeta

        meta = req.url_meta or UrlMeta()
        task_id = ids.task_id(
            req.url, tag=meta.tag, application=meta.application,
            digest=meta.digest, piece_range=meta.range,
            filtered_query_params=list(meta.filtered_query_params or []))
        if not self.seed_client.available():
            raise DFError(Code.SCHED_FORBIDDEN, "no seed peers to preheat into")
        task = self.resource.get_or_create_task(task_id, req.url)
        if task.url_meta is None:
            task.url_meta = meta      # a seed RE-trigger replays these
        if task.state == TaskState.PENDING:
            task.transit(TaskState.RUNNING)
        seed_done = task.seed_job is not None and task.seed_job.done()
        # re-trigger on retry after a failed seed (transient origin outage
        # must not poison the task until GC)
        if not task.seed_triggered or (seed_done
                                       and not task.has_available_peer()):
            self._fire_seed_trigger(task, meta)
        if req.wait and task.seed_job is not None:
            await asyncio.shield(task.seed_job)
        if task.has_available_peer():
            state = "succeeded"
        elif task.seed_job is not None and not task.seed_job.done():
            state = "running"
        else:
            state = "failed"
        return PreheatResponse(task_id=task_id, state=state,
                               content_length=task.content_length,
                               total_piece_count=task.total_piece_count)

    async def sync_peers(self, req, context):
        """Dump live hosts for the manager's sync_peers job (reference
        scheduler/job/job.go:224)."""
        from ..idl.messages import SyncPeersResponse
        return SyncPeersResponse(hosts=[h.msg
                                        for h in self.resource.hosts.values()])

    async def sync_probes(self, request_iter,
                          context) -> AsyncIterator[SyncProbesResponse]:
        async for req in request_iter:
            src = req.host.id if req.host is not None else ""
            for probe in req.probes or []:
                self.topo.record(src, probe.target_host_id, probe.rtt_us)
            for failed in req.failed_host_ids or []:
                self.topo.fail(src, failed)
            targets = []
            for hid in self.topo.pick_targets(
                    src, list(self.resource.hosts)):
                host = self.resource.hosts.get(hid)
                if host is not None:
                    targets.append(ProbeTarget(host_id=hid, ip=host.msg.ip,
                                               port=host.msg.port))
            yield SyncProbesResponse(targets=targets)


def build_service(svc: SchedulerService) -> ServiceDef:
    d = ServiceDef(SCHEDULER_SERVICE)
    d.unary_unary("RegisterPeerTask", svc.register_peer_task)
    d.stream_stream("ReportPieceResult", svc.report_piece_result)
    d.unary_unary("ReportPeerResult", svc.report_peer_result)
    d.unary_unary("AnnounceHost", svc.announce_host)
    d.unary_unary("AnnounceContent", svc.announce_content)
    d.unary_unary("LeaveHost", svc.leave_host)
    d.unary_unary("LeavePeer", svc.leave_peer)
    d.unary_unary("StatTask", svc.stat_task)
    d.unary_unary("Preheat", svc.preheat)
    d.unary_unary("SyncPeers", svc.sync_peers)
    d.stream_stream("SyncProbes", svc.sync_probes)
    return d
