"""Cluster-wide download health view, fed by piece reports + flight
summaries.

Role parity: none in the reference — scheduler-side half of the flight
recorder (daemon/flight_recorder.py). Every daemon already streams piece
results up and attaches a compact flight summary to its terminal
``PeerResult``; this module folds both into per-host aggregates the
operator reads at ``GET /debug/cluster`` (served on the scheduler
launcher's ``--debug-port``) and the trainer consumes via the records
stream:

  * per-peer/host throughput (bytes, pieces, mean piece cost),
  * cluster back-to-source ratio (the egress the mesh failed to save),
  * straggler parents — hosts whose mean served-piece cost sits far above
    the cluster median (the "one slow host drags the fan-out" signal).

All updates are O(1) per report; the snapshot walks the host table only
when asked.
"""

from __future__ import annotations

import time

from ..common.metrics import REGISTRY

_cluster_bytes = REGISTRY.counter(
    "df_cluster_bytes_total",
    "bytes reported downloaded cluster-wide", ("source",))
_flights = REGISTRY.counter(
    "df_cluster_flight_reports_total",
    "flight summaries received from daemons")

STRAGGLER_FACTOR = 3.0      # mean cost beyond this x median -> straggler
MIN_STRAGGLER_PIECES = 4    # don't judge a parent on one slow piece
SNAPSHOT_TTL_S = 1.0        # /debug/cluster rebuild cadence (see snapshot)


class _HostAgg:
    __slots__ = ("bytes_down_p2p", "bytes_down_source", "pieces_down",
                 "pieces_served", "serve_cost_ms_sum", "fails",
                 "flights", "last_seen", "last_flight")

    def __init__(self) -> None:
        self.bytes_down_p2p = 0
        self.bytes_down_source = 0
        self.pieces_down = 0
        self.pieces_served = 0
        self.serve_cost_ms_sum = 0.0
        self.fails = 0
        self.flights = 0
        self.last_seen = time.time()
        self.last_flight: dict | None = None

    def mean_serve_ms(self) -> float:
        return (self.serve_cost_ms_sum / self.pieces_served
                if self.pieces_served else 0.0)


class ClusterView:
    def __init__(self, ledger=None, quarantine=None,
                 snapshot_ttl_s: float = SNAPSHOT_TTL_S) -> None:
        self._hosts: dict[str, _HostAgg] = {}
        self.started_at = time.time()
        # /debug/cluster rebuilds walk every host; on a 10k-host fleet a
        # tight poller would turn that O(hosts) sweep into scheduler load.
        # Snapshots are cached for snapshot_ttl_s and the payload reports
        # its own staleness so pollers know what vintage they read.
        self.snapshot_ttl_s = snapshot_ttl_s
        self._snap: dict | None = None
        self._snap_at = 0.0
        # decision ledger (scheduler/decision_ledger.py): its compact
        # counters ride the cluster snapshot so /debug/cluster answers
        # "is the pod herding onto no-slots/bad-node exclusions" next to
        # the throughput it is costing
        self.ledger = ledger
        # quarantine registry (scheduler/quarantine.py): ladder states
        # ride the snapshot so /debug/cluster names quarantined hosts
        self.quarantine = quarantine

    def _agg(self, host_id: str) -> _HostAgg:
        agg = self._hosts.get(host_id)
        if agg is None:
            agg = self._hosts[host_id] = _HostAgg()
        agg.last_seen = time.time()
        return agg

    # -- hooks called by SchedulerService (hot path: O(1)) -------------

    def on_piece(self, peer, result) -> None:
        agg = self._agg(peer.host.id)
        if not result.success:
            agg.fails += 1
            return
        info = result.piece_info
        if info is None:
            return
        agg.pieces_down += 1
        if result.dst_peer_id:
            agg.bytes_down_p2p += info.range_size
            _cluster_bytes.labels("p2p").inc(info.range_size)
            parent = peer.task.peers.get(result.dst_peer_id)
            if parent is not None:
                pagg = self._agg(parent.host.id)
                pagg.pieces_served += 1
                pagg.serve_cost_ms_sum += info.download_cost_ms
        else:
            agg.bytes_down_source += info.range_size
            _cluster_bytes.labels("source").inc(info.range_size)

    def on_flight(self, peer, summary: dict) -> None:
        agg = self._agg(peer.host.id)
        agg.flights += 1
        # keep only the latest per host (bounded by host count, not tasks)
        agg.last_flight = {
            k: summary.get(k) for k in
            ("task_id", "state", "pieces", "bytes_p2p", "bytes_source",
             "back_to_source_ratio", "tail_ms", "slowest_piece",
             "hbm_dma_ms")}
        _flights.inc()

    # -- consumption ---------------------------------------------------

    def stragglers(self) -> list[dict]:
        """Serving hosts whose mean piece cost is far beyond the cluster
        median — the parents a slow fan-out is waiting on."""
        means = [(hid, a.mean_serve_ms(), a.pieces_served)
                 for hid, a in self._hosts.items()
                 if a.pieces_served >= MIN_STRAGGLER_PIECES]
        if len(means) < 2:
            return []
        costs = sorted(m for _, m, _ in means)
        # lower median: with two serving hosts the slow one must be judged
        # against the fast one, not against itself
        median = costs[(len(costs) - 1) // 2]
        if median <= 0:
            return []
        return [{"host_id": hid, "mean_serve_ms": round(m, 3),
                 "pieces_served": n,
                 "slowdown": round(m / median, 2)}
                for hid, m, n in means
                if m > STRAGGLER_FACTOR * median]

    def snapshot(self) -> dict:
        """TTL-cached view; ``staleness_s`` in the payload says how old."""
        now = time.monotonic()
        if (self._snap is not None
                and now - self._snap_at <= self.snapshot_ttl_s):
            snap = dict(self._snap)
            snap["staleness_s"] = round(now - self._snap_at, 3)
            return snap
        snap = self._build_snapshot()
        snap["snapshot_ttl_s"] = self.snapshot_ttl_s
        snap["staleness_s"] = 0.0
        self._snap = snap
        self._snap_at = now
        return snap

    def _build_snapshot(self) -> dict:
        p2p = sum(a.bytes_down_p2p for a in self._hosts.values())
        src = sum(a.bytes_down_source for a in self._hosts.values())
        hosts = {}
        for hid, a in self._hosts.items():
            hosts[hid] = {
                "bytes_p2p": a.bytes_down_p2p,
                "bytes_source": a.bytes_down_source,
                "pieces_down": a.pieces_down,
                "pieces_served": a.pieces_served,
                "mean_serve_ms": round(a.mean_serve_ms(), 3),
                "fails": a.fails,
                "flights": a.flights,
                "last_seen": a.last_seen,
                "last_flight": a.last_flight,
            }
        snap = {
            "since": self.started_at,
            "hosts": hosts,
            "bytes_p2p": p2p,
            "bytes_source": src,
            "back_to_source_ratio": (round(src / (p2p + src), 4)
                                     if (p2p + src) else 0.0),
            "stragglers": self.stragglers(),
        }
        if self.ledger is not None:
            snap["decisions"] = self.ledger.stats()
        if self.quarantine is not None:
            snap["quarantine"] = self.quarantine.snapshot()
        return snap


def add_cluster_routes(router, view: ClusterView) -> None:
    """``GET /debug/cluster`` — mounted on the scheduler launcher's
    --debug-port server next to /metrics."""
    from aiohttp import web

    async def cluster(_r: web.Request) -> web.Response:
        return web.json_response(view.snapshot())

    router.add_get("/debug/cluster", cluster)
