"""Scheduler bootstrap: wire resource, scheduling, seed client, GC, gRPC.

Role parity: reference ``scheduler/scheduler.go`` ``New``/``Serve``
(:110-299, :302) minus manager/Redis (dynconfig + keepalive attach in the
manager stage; job queues ride the manager's queue, not Redis).
"""

from __future__ import annotations

import asyncio
import logging

from ..common.gc import GC, GCTask
from ..rpc.server import RPCServer
from .config import SchedulerConfig
from .evaluator import make_evaluator
from .resource import Resource
from .scheduling import Scheduling
from .seed_client import SeedPeerClient
from .service import SchedulerService, build_service
from .topology_store import TopologyStore

log = logging.getLogger("df.sched.server")


class Scheduler:
    def __init__(self, cfg: SchedulerConfig, *, records=None, infer=None):
        self.cfg = cfg
        self.resource = Resource(peer_ttl_s=cfg.peer_ttl_s,
                                 task_ttl_s=cfg.task_ttl_s,
                                 host_ttl_s=cfg.host_ttl_s,
                                 peer_upload_limit=cfg.peer_upload_limit,
                                 seed_upload_limit=cfg.seed_upload_limit)
        self.topo = TopologyStore()
        evaluator = make_evaluator(cfg.algorithm, topo_store=self.topo,
                                   infer=infer, plugin_dir=cfg.plugin_dir)
        self.scheduling = Scheduling(cfg, evaluator)
        self.seed_client = SeedPeerClient(self.resource, cfg.seed_peers)
        if records is None and (cfg.records_dir or cfg.trainer_address):
            from .records import DownloadRecords
            records = DownloadRecords(cfg.records_dir)
        # decision ledger: every find/refresh ruling explained — live ring
        # at GET /debug/decisions, kind=decision rows into records (when
        # records are on) for the outcome join + dfbench --pr8 replay
        from .decision_ledger import DecisionLedger
        self.ledger = DecisionLedger(records=records)
        self.scheduling.decision_sink = self.ledger.on_decision
        # pod-wide quarantine registry: corrupt verdicts + self-flags in,
        # offer/relay/seed exclusion out, every transition a ledger row
        self.quarantine = None
        if cfg.quarantine_enabled:
            from .quarantine import QuarantineRegistry
            self.quarantine = QuarantineRegistry(
                corrupt_threshold=cfg.quarantine_corrupt_threshold,
                halflife_s=cfg.quarantine_halflife_s,
                probation_delay_s=cfg.quarantine_probation_delay_s,
                probe_successes=cfg.quarantine_probe_successes,
                probe_children=cfg.quarantine_probe_children,
                min_reporters=cfg.quarantine_min_reporters,
                sink=self.ledger.on_decision)
            self.scheduling.quarantine = self.quarantine
            self.seed_client.quarantine = self.quarantine
        # cross-pod federation view: fed from register/announce, consulted
        # by the scheduling filter; off (None) = exact pre-federation path
        self.federation = None
        if cfg.federation_enabled:
            from .federation import PodFederation
            self.federation = PodFederation(
                seeds_per_pod=cfg.federation_seeds_per_pod,
                quarantine=self.quarantine,
                sink=self.ledger.on_decision)
            self.scheduling.federation = self.federation
            # evicted hosts/tasks leave the election electorate too —
            # without this a GC'd (silently dead) pod seed would keep
            # winning elections it can never serve
            self.resource.on_host_evict = self.federation.forget_host
            self.resource.on_task_evict = self.federation.drop_task
        # sharded-checkpoint shard affinity: disjoint tree-fetch subsets
        # ruled at register for requests carrying UrlMeta.shards; the
        # eviction hooks CHAIN with federation's (both views must forget)
        self.sharded = None
        if cfg.shard_affinity_enabled:
            from .shard_affinity import ShardAffinity
            self.sharded = ShardAffinity(sink=self.ledger.on_decision)
            self.scheduling.sharded = self.sharded
            prev_host, prev_task = (self.resource.on_host_evict,
                                    self.resource.on_task_evict)

            def _evict_host(hid, _prev=prev_host, _sh=self.sharded):
                _sh.forget_host(hid)
                if _prev is not None:
                    _prev(hid)

            def _evict_task(tid, _prev=prev_task, _sh=self.sharded):
                _sh.drop_task(tid)
                if _prev is not None:
                    _prev(tid)

            self.resource.on_host_evict = _evict_host
            self.resource.on_task_evict = _evict_task
        # crash-survivable control plane (scheduler/statestore.py): the
        # slow-moving ruling state — quarantine ladder, shard-affinity
        # memos, seed elections, tenant quotas — journals to one
        # versioned snapshot. Event-driven cadence rides the components'
        # existing decision sinks (every covered transition already
        # emits a ledger row), so durability costs one dirty-flag store
        # per ruling and zero new wiring inside the components.
        self.statestore = None
        if cfg.statestore_dir:
            from .statestore import SchedulerStateStore
            self.statestore = SchedulerStateStore(
                cfg.statestore_dir, interval_s=cfg.statestore_interval_s)
            if self.quarantine is not None:
                self.statestore.register("quarantine",
                                         self.quarantine.export_state,
                                         self.quarantine.restore)
                self.quarantine.sink = self.statestore.wrap_sink(
                    self.quarantine.sink)
            if self.federation is not None:
                self.statestore.register("federation",
                                         self.federation.export_state,
                                         self.federation.restore)
                self.federation.sink = self.statestore.wrap_sink(
                    self.federation.sink)
            if self.sharded is not None:
                self.statestore.register("shard_affinity",
                                         self.sharded.export_state,
                                         self.sharded.restore)
                self.sharded.sink = self.statestore.wrap_sink(
                    self.sharded.sink)
        # fleet pulse plane (scheduler/fleetpulse.py): announce-borne
        # telemetry rings + EWMA anomaly detector + incident capture.
        # Anomaly firings ride the decision ledger (decision_kind=anomaly)
        # and the rings register with the statestore so incident history
        # survives a scheduler crash/failover.
        self.fleetpulse = None
        if cfg.fleetpulse_enabled:
            from .fleetpulse import FleetPulse
            self.fleetpulse = FleetPulse(
                sink=self.ledger.on_decision,
                quarantine=self.quarantine,
                federation=self.federation,
                statestore=self.statestore)
            if self.statestore is not None:
                self.statestore.register("fleetpulse",
                                         self.fleetpulse.export_state,
                                         self.fleetpulse.restore)
        self.service = SchedulerService(cfg, self.resource, self.scheduling,
                                        self.seed_client, self.topo,
                                        records=records, ledger=self.ledger,
                                        quarantine=self.quarantine,
                                        federation=self.federation,
                                        fleetpulse=self.fleetpulse)
        if self.statestore is not None:
            svc = self.service

            def _export_tenants() -> dict:
                return {"tenants": svc.tenants,
                        "applications": svc.applications}

            def _restore_tenants(sub: dict) -> int:
                # restored quotas hold until the first manager dynconfig
                # refresh overwrites them — a recovered brain enforces
                # tenant limits from ruling one instead of running
                # quota-blind for a refresh interval
                svc.tenants = dict(sub.get("tenants") or {})
                svc.applications = {k: int(v) for k, v in
                                    (sub.get("applications") or {}).items()}
                return len(svc.tenants)

            def _export_meta() -> dict:
                return {"epoch": svc.epoch}

            def _restore_meta(sub: dict) -> int:
                # strictly-increasing epoch across durable restarts: the
                # daemons' change detection must never see a restart
                # land on the same epoch value
                svc.epoch = max(svc.epoch, int(sub.get("epoch", 0)) + 1)
                return 1

            self.statestore.register("tenants", _export_tenants,
                                     _restore_tenants)
            self.statestore.register("meta", _export_meta, _restore_meta)
        self.announcer = None
        self.rpc: RPCServer | None = None
        self.gc = GC()
        self.port: int | None = None
        self.manager = None

    @property
    def address(self) -> str:
        return f"{self.cfg.advertise_ip}:{self.port}"

    async def start(self) -> None:
        if self.cfg.tracing_jsonl or self.cfg.tracing_otlp:
            from ..common import tracing
            tracing.configure(service="dfscheduler",
                              jsonl_path=self.cfg.tracing_jsonl,
                              otlp_endpoint=self.cfg.tracing_otlp)
        if self.statestore is not None:
            # restore BEFORE the first RPC can land: a ruling made on an
            # amnesiac view and then "corrected" by a late restore would
            # be exactly the half-applied state the store exists to
            # prevent. A refused/missing snapshot degrades to the cold
            # path — recovery must never block boot.
            prov = await asyncio.to_thread(self.statestore.restore)
            if prov.get("recovered") and self.ledger is not None:
                self.service._recovery_seq += 1
                self.ledger.on_decision({
                    "kind": "decision",
                    "decision_kind": "recovery",
                    "decision_id":
                        f"r{self.service._recovery_seq:08d}.snapshot",
                    "host_id": "",
                    "source": "snapshot",
                    "gap_s": prov.get("gap_s", 0.0),
                    "components": {
                        k: v.get("restored", 0)
                        for k, v in (prov.get("components") or {}).items()},
                    "scheduler_epoch": self.service.epoch,
                    "task_id": "",
                    "peer_id": "",
                    "candidates": [],
                    "excluded": [],
                    "chosen": [],
                })
        self.rpc = RPCServer(f"{self.cfg.listen_ip}:{self.cfg.port}")
        self.rpc.register(build_service(self.service))
        await self.rpc.start()
        self.port = self.rpc.port
        if self.cfg.manager_addresses:
            await self._attach_manager()
        if self.cfg.security_issue_token and self.cfg.manager_addresses:
            await self._enroll_security()
        self.gc.add(GCTask("resource", self.cfg.gc_interval_s,
                           self.resource.gc))
        if self.statestore is not None:
            # snapshot ticker rides the GC runner (periodic + dirty):
            # maybe_save never raises, so a sick disk shows up as an
            # error-result counter, not a dead sweeper
            store = self.statestore
            self.gc.add(GCTask("statestore",
                               min(self.cfg.statestore_interval_s, 5.0),
                               lambda: int(store.maybe_save())))
        if self.fleetpulse is not None:
            # silent-daemon detection + series aging ride the GC runner:
            # a daemon that stops announcing can't push its own absence
            fp = self.fleetpulse
            self.gc.add(GCTask("fleetpulse", self.cfg.gc_interval_s,
                               lambda: fp.tick()))
        self.gc.start()
        # records → trainer upload + model → evaluator refresh (ML loop)
        from .announcer import SchedulerAnnouncer
        self.announcer = SchedulerAnnouncer(
            self, upload_interval_s=self.cfg.train_upload_interval_s,
            refresh_interval_s=self.cfg.model_refresh_interval_s)
        self.announcer.start()
        log.info("scheduler up on %s (cluster=%d, algorithm=%s, seeds=%d)",
                 self.address, self.cfg.cluster_id, self.cfg.algorithm,
                 len(self.seed_client.seed_peers))

    async def _enroll_security(self) -> None:
        """Obtain fleet TLS material so seed triggers can reach
        security-enabled seed daemons (their rpc ports require client
        certs)."""
        import os

        from ..rpc.security import obtain_certificate
        try:
            cert, key, ca = await obtain_certificate(
                self.cfg.manager_addresses,
                hosts=[self.cfg.advertise_ip],
                token=self.cfg.security_issue_token,
                out_dir=os.path.join(self.cfg.workdir or ".",
                                     "scheduler-tls"),
                tls_ca=self.cfg.security_ca_cert)
        except Exception as exc:  # noqa: BLE001 - seeds then unreachable
            log.error("fleet TLS enrollment failed (%s): seed triggers to "
                      "mTLS seed daemons WILL fail", exc)
            return
        tls = (cert, key, self.cfg.security_ca_cert or ca)
        await self.seed_client.close()
        self.seed_client = SeedPeerClient(
            self.resource, list(self.seed_client.seed_peers.values()),
            tls=tls, quarantine=self.quarantine)
        self.service.seed_client = self.seed_client

    async def _attach_manager(self) -> None:
        """Register with the manager, keep alive, and adopt its seed-peer
        set when none is configured statically (reference scheduler boots
        the same way off dynconfig)."""
        import socket

        from ..idl.messages import RegisterSchedulerRequest
        from ..rpc.manager_link import ManagerLink
        from ..tpu import topology
        from .config import SeedPeerAddr
        from .seed_client import SeedPeerClient

        hostname = socket.gethostname()
        self.manager = ManagerLink(
            self.cfg.manager_addresses,
            keepalive_interval_s=self.cfg.keepalive_interval_s)
        try:
            # the JAX device probe can take seconds on a cold TPU runtime
            # and touches its cache file — run it off-loop; kept INSIDE
            # the try so a probe failure degrades to standalone mode like
            # any other attach failure instead of aborting scheduler boot
            topo = await asyncio.to_thread(topology.detect)
            await self.manager.register_scheduler(RegisterSchedulerRequest(
                hostname=hostname, ip=self.cfg.advertise_ip, port=self.port,
                scheduler_cluster_id=self.cfg.cluster_id,
                topology=topo))
            self.manager.start_keepalive(source_type="scheduler",
                                         hostname=hostname,
                                         ip=self.cfg.advertise_ip,
                                         cluster_id=self.cfg.cluster_id,
                                         port=self.port)
            if not self.cfg.seed_peers:
                resp = await self.manager.get_seed_peers()
                seeds = [SeedPeerAddr(host_id=f"{e.hostname}-{e.ip}",
                                      ip=e.ip, rpc_port=e.port,
                                      download_port=e.download_port)
                         for e in (resp.seed_peers or [])]
                if seeds:
                    self.seed_client = SeedPeerClient(
                        self.resource, seeds, quarantine=self.quarantine)
                    self.service.seed_client = self.seed_client
        except Exception as exc:  # noqa: BLE001 - manager optional at boot
            log.warning("manager attach failed (%s); running standalone", exc)
            return
        if self.cfg.statestore_handoff:
            await self._import_handoff()
        # applications are OPTIONAL (an older manager may lack the verb):
        # a failed first fetch must neither mislabel the attach as failed
        # nor disable refresh — the loop keeps retrying and recovers when
        # the manager catches up
        self._app_refresh = asyncio.get_running_loop().create_task(
            self._app_refresh_loop())

    def _handoff_signature(self, blob: bytes) -> str:
        import hashlib
        import hmac
        token = self.cfg.security_issue_token
        if not token:
            return ""
        return hmac.new(token.encode(), blob, hashlib.sha256).hexdigest()

    async def _export_handoff(self) -> None:
        """Graceful stop/demotion: park the quarantine/affinity summary
        with the manager (config plane of record) so the ring successor
        can warm itself — sealed with the PEX envelope codec, HMAC'd
        with the cluster issuance token when security is on."""
        if (self.manager is None or self.statestore is None
                or not self.cfg.statestore_handoff):
            return
        from ..daemon.pex import DIGEST_VERSION, seal
        from ..idl.messages import SetSchedulerStateRequest
        body: dict = {"v": DIGEST_VERSION}
        if self.quarantine is not None:
            body["quarantine"] = self.quarantine.export_state()
        if self.sharded is not None:
            body["shard_affinity"] = self.sharded.export_state()
        if len(body) == 1:
            return
        blob = seal(body)
        try:
            await self.manager.set_scheduler_state(SetSchedulerStateRequest(
                scheduler_id=self.address,
                cluster_id=self.cfg.cluster_id,
                blob=blob,
                signature=self._handoff_signature(blob)))
        except Exception as exc:  # noqa: BLE001 - handoff is best-effort
            log.debug("handoff export failed: %s", exc)

    async def _import_handoff(self) -> None:
        """Ring-failover successor: import the demoted member's parked
        summary. The PR 12 anti-slander rule is structural, not
        advisory: imported verdicts land as CIRCUMSTANTIAL (relayed)
        mass via ``QuarantineRegistry.import_summary``, which tops out
        at `suspect` — only fresh first-hand corrupt reports arriving
        HERE can quarantine. Affinity memos import whole (the split is a
        pure observable function, so adopting them only preserves
        stickiness)."""
        if self.manager is None:
            return
        import hmac as _hmac

        from ..daemon.pex import unseal
        from ..idl.messages import GetSchedulerStateRequest
        try:
            resp = await self.manager.get_scheduler_state(
                GetSchedulerStateRequest(cluster_id=self.cfg.cluster_id,
                                         exclude=self.address))
        except Exception as exc:  # noqa: BLE001 - older manager: no verb
            log.debug("handoff import unavailable: %s", exc)
            return
        if resp is None or not resp.blob or resp.scheduler_id == self.address:
            return
        want = self._handoff_signature(resp.blob)
        if want and not _hmac.compare_digest(want, resp.signature or ""):
            log.warning("handoff blob from %s refused: bad signature",
                        resp.scheduler_id)
            return
        body = unseal(resp.blob)
        if body is None:
            log.warning("handoff blob from %s refused: torn/version-skewed",
                        resp.scheduler_id)
            return
        imported = 0
        if self.quarantine is not None \
                and isinstance(body.get("quarantine"), dict):
            imported += self.quarantine.import_summary(
                body["quarantine"], source=resp.scheduler_id)
        if self.sharded is not None \
                and isinstance(body.get("shard_affinity"), dict):
            imported += self.sharded.restore(body["shard_affinity"])
        log.info("handoff import from %s: %d entries warmed",
                 resp.scheduler_id, imported)
        if self.ledger is not None and imported:
            self.service._recovery_seq += 1
            self.ledger.on_decision({
                "kind": "decision",
                "decision_kind": "recovery",
                "decision_id": f"r{self.service._recovery_seq:08d}.handoff",
                "host_id": "",
                "source": "handoff",
                "from_scheduler": resp.scheduler_id,
                "entries_imported": imported,
                "scheduler_epoch": self.service.epoch,
                "task_id": "",
                "peer_id": "",
                "candidates": [],
                "excluded": [],
                "chosen": [],
            })

    async def _refresh_applications(self) -> None:
        """Pull the application priority table into the service (reference
        dynconfig.GetApplications feeding Peer.CalculatePriority), plus
        the tenant quota table (multi-tenant QoS) on the same cadence —
        both optional verbs, each failing independently so an older
        manager serving only applications still feeds them."""
        resp = await self.manager.list_applications()
        self.service.applications = {
            e.name: int(e.priority) for e in (resp.applications or [])}
        try:
            tresp = await self.manager.list_tenants()
        except Exception as exc:  # noqa: BLE001 - older manager: no verb
            log.debug("tenant refresh failed: %s", exc)
            return
        self.service.tenants = {
            t.name: {"qos_class": t.qos_class,
                     "max_running": int(t.max_running),
                     "shed_retry_after_ms": int(t.shed_retry_after_ms)}
            for t in (tresp.tenants or [])}

    async def _app_refresh_loop(self) -> None:
        while True:
            try:
                await self._refresh_applications()
            except Exception as exc:  # noqa: BLE001 - manager flaky is fine
                log.debug("application refresh failed: %s", exc)
            await asyncio.sleep(self.cfg.keepalive_interval_s * 6)

    async def stop(self) -> None:
        if getattr(self, "_app_refresh", None) is not None:
            self._app_refresh.cancel()
        if self.announcer is not None:
            await self.announcer.stop()
        if self.statestore is not None:
            # final snapshot + manager handoff BEFORE the manager link
            # closes; both swallow failures — shutdown never wedges on a
            # sick disk or an absent manager
            await asyncio.to_thread(self.statestore.save,
                                    reason="shutdown")
            await self._export_handoff()
        if self.service.records is not None:
            await self.service.records.aclose()
        if getattr(self, "manager", None) is not None:
            await self.manager.close()
        await self.gc.stop()
        for t in list(self.service._seed_tasks):
            t.cancel()
        await self.seed_client.close()
        if self.rpc is not None:
            await self.rpc.stop(0.5)
