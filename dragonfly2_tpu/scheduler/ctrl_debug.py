"""Control-plane observatory surface: ``GET /debug/ctrl``.

Role parity: none in the reference — the live half of the PR-16
control-plane observatory. Joins the ruling profiler's aggregates
(common/phasetimer.py: rulings/sec, per-phase p50/p99, queue-wait vs
compute) with bytes-of-state accounting across every control-plane
component (Resource, DecisionLedger, PodFederation, QuarantineRegistry,
ShardAffinity — each exposing ``state_bytes()``), served on the
scheduler launcher's ``--debug-port`` next to /debug/cluster and
rendered by ``dfdiag --ctrl``.

The state-bytes walk is O(every object the scheduler holds) — at 10k
peers that is seconds, which must never ride the ruling loop. It is
computed lazily behind a short TTL cache, and the payload reports its
own ``state_staleness_s`` so a poller knows what vintage it is reading
(the same honesty contract as the /debug/cluster snapshot cache).

``GET /debug/ctrl?arm=1`` / ``?arm=0`` arms/disarms the profiler live —
the operator's "profile this incident now" switch; the scheduler does
not need a restart (and the disarmed tax on rulings stays near zero, so
shipping with it armed is also fine).
"""

from __future__ import annotations

import time

from ..common import phasetimer
from ..common.metrics import REGISTRY

_state_bytes_gauge = REGISTRY.gauge(
    "df_ctrl_state_bytes",
    "bytes of control-plane state held per component (deep-sizeof walk, "
    "refreshed at the /debug/ctrl TTL cadence)", ("component",))

STATE_TTL_S = 5.0       # state-bytes walk cache; staleness is reported


class CtrlObservatory:
    """Holds the component refs and the TTL-cached state-bytes walk."""

    def __init__(self, *, resource=None, ledger=None, federation=None,
                 quarantine=None, sharded=None, statestore=None,
                 model_provenance=None, ttl_s: float = STATE_TTL_S,
                 clock=time.monotonic) -> None:
        self.components = {
            "resource": resource,
            "ledger": ledger,
            "federation": federation,
            "quarantine": quarantine,
            "shard_affinity": sharded,
        }
        self.statestore = statestore
        # zero-arg callable → rollout-provenance dict (the announcer's
        # model_provenance); None on schedulers without a learning loop
        self.model_provenance = model_provenance
        self.ttl_s = ttl_s
        self.clock = clock
        self._state_cache: dict | None = None
        self._state_at = 0.0

    def peer_count(self) -> int:
        res = self.components.get("resource")
        if res is None:
            return 0
        return sum(len(t.peers) for t in res.tasks.values())

    def state_bytes(self) -> dict:
        """Per-component bytes + per-peer quotient, behind the TTL."""
        now = self.clock()
        if (self._state_cache is not None
                and now - self._state_at <= self.ttl_s):
            return self._state_cache
        per = {name: comp.state_bytes()
               for name, comp in self.components.items()
               if comp is not None}
        for name, b in per.items():
            _state_bytes_gauge.labels(name).set(b)
        total = sum(per.values())
        peers = self.peer_count()
        self._state_cache = {
            "components": per,
            "total": total,
            "peers": peers,
            "per_peer": round(total / peers, 1) if peers else 0.0,
        }
        self._state_at = now
        return self._state_cache

    def snapshot(self) -> dict:
        snap = phasetimer.snapshot()
        snap["state_bytes"] = self.state_bytes()
        snap["state_staleness_s"] = round(
            max(self.clock() - self._state_at, 0.0), 3)
        snap["state_ttl_s"] = self.ttl_s
        # recovered-vs-rebuilt provenance: which slices of this brain's
        # view came back from the durable snapshot (statestore.restore)
        # vs were relearned live from announce/register traffic — an
        # operator reading /debug/ctrl after an incident can tell whether
        # the scheduler is ruling from memory or from hearsay
        if self.statestore is not None:
            snap["recovery"] = self.statestore.provenance
        # model-rollout provenance: which trained brain (if any) the ml
        # evaluator is serving, every blob refused at bind time, and the
        # serve-time fallback tally — dfdiag --ctrl names a degraded
        # evaluator from this block
        if self.model_provenance is not None:
            snap["model"] = self.model_provenance()
        return snap


def add_ctrl_routes(router, obs: CtrlObservatory) -> None:
    """``GET /debug/ctrl`` — mounted on the scheduler launcher's
    --debug-port server next to /debug/cluster and /debug/decisions."""
    from aiohttp import web

    async def ctrl(req: web.Request) -> web.Response:
        arm = req.query.get("arm", "")
        if arm in ("1", "true"):
            phasetimer.arm()
        elif arm in ("0", "false"):
            phasetimer.disarm()
        return web.json_response(obs.snapshot())

    router.add_get("/debug/ctrl", ctrl)
