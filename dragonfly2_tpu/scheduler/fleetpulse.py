"""Fleet pulse: push-based continuous telemetry + anomaly detection.

Role parity: none in the reference — Dragonfly2 observability is either
per-process (flight recorder, health plane) or a pull-based operator
sweep (podscope fetches every daemon's /debug/* over HTTP, point in
time, no history). At the 16-pod x 256-daemon regime ROADMAP item 3
targets, an O(pod) HTTP sweep is infeasible and a transient stall that
resolved before anyone ran ``dfdiag --pod`` is simply unobservable.

Here telemetry is PUSHED: each daemon folds its existing counters into
a compact versioned ``PulseDigest`` (idl/messages.py, built by
daemon/pulse.py) and piggybacks it on the ``AnnounceHost`` heartbeat it
already sends — zero new connections, bounded bytes per announce
(dfbench --pr18 gates the overhead at <= 512 B). The scheduler side
(this module) keeps a bounded ring of samples per daemon plus fleet
rollups, runs an EWMA/z-score detector over the streams, emits each
firing as a ``decision_kind=anomaly`` ledger row, and auto-captures an
incident bundle (the offending daemon's recent pulse history + its
quarantine/federation standing) into a bounded ring for post-hoc
reconstruction — all served at ``GET /debug/fleet`` and rendered by
``dfdiag --fleet``.

Purity contract (the same bar every observer in this tree clears):
``ingest`` mutates ONLY FleetPulse state, metrics, and the decision
ledger — never the Resource model, never a ruling input. dfbench --pr18
proves it: the ctrl storm's ruling digest is byte-identical with the
pulse plane armed or disarmed, and the baseline schedule digest stays
byte-identical to BENCH_pr3.

The anomaly vocabulary is CLOSED (dflint DF006 anomaly-vocabulary rule:
registry here, fire sites package-wide, backticks in
docs/OBSERVABILITY.md must agree):

* ``loop-stall``    — a daemon's event-loop lag high-water spiked
* ``slo-storm``     — per-stage SLO breaches burst past baseline
* ``rung-escalation`` — serves escalated off the primary ladder rung
* ``shed-wave``     — QoS admissions shed in a burst (brownout/shed)
* ``corrupt-burst`` — corrupt verdicts / shunned parents burst, or the
  daemon self-quarantined
* ``silent-daemon`` — announces stopped arriving (missed heartbeats)

Detection is deliberately boring: per-(daemon, signal) EWMA mean/var,
fire when the z-score AND an absolute floor are both crossed, latch the
episode so a sustained anomaly fires exactly once, freeze the baseline
while latched so the anomaly never becomes the new normal, and suppress
everything until ``WARMUP_SAMPLES`` announces have been seen. All
clocks are injectable — dfbench replays detection byte-identically on a
virtual clock.
"""

from __future__ import annotations

import logging
import math
import time
from collections import deque
from typing import Any, Callable

from ..common.metrics import REGISTRY

log = logging.getLogger("df.sched.fleetpulse")

# The closed anomaly vocabulary (dflint DF006 anomaly-vocabulary rule).
# Adding a kind means: fire it below, document it in
# docs/OBSERVABILITY.md, and extend the dfbench --pr18 injection matrix.
ANOMALY_KINDS = (
    "loop-stall",
    "slo-storm",
    "rung-escalation",
    "shed-wave",
    "corrupt-burst",
    "silent-daemon",
)

PULSE_RING = 32             # samples retained per daemon
INCIDENT_RING = 64          # incident bundles retained fleet-wide
ANOMALY_LOG = 256           # recent anomaly rows kept for /debug/fleet
EWMA_ALPHA = 0.3            # per-signal EWMA smoothing
Z_THRESHOLD = 4.0           # fire at this z-score (and the abs floor)
Z_CLEAR = 2.0               # episode clears back under this z-score
WARMUP_SAMPLES = 8          # announces before a daemon's detector arms
SILENT_AFTER_INTERVALS = 2.5   # missed-announce factor -> silent-daemon
EVICT_AFTER_INTERVALS = 20.0   # missed-announce factor -> series aged out
PRIMARY_RUNG = "p2p"        # ladder rung that does NOT count as escalated

# signal name -> (anomaly kind, absolute floor the value must also cross:
# a z-spike on near-zero noise is arithmetic, not an incident)
_SIGNALS = {
    "lag_ms": ("loop-stall", 50.0),
    "slo_delta": ("slo-storm", 3.0),
    "rung_delta": ("rung-escalation", 3.0),
    "shed_delta": ("shed-wave", 3.0),
    "corrupt_delta": ("corrupt-burst", 2.0),
}

_daemons_gauge = REGISTRY.gauge(
    "df_fleet_daemons", "daemons with a live fleet-pulse series")
_pulse_total = REGISTRY.counter(
    "df_fleet_pulse_total",
    "pulse digests ingested from announces, by result "
    "(ok / ignored_version / malformed)", ("result",))
_anomalies_total = REGISTRY.counter(
    "df_fleet_anomalies_total",
    "fleet anomaly episodes fired, by kind", ("kind",))
_incidents_gauge = REGISTRY.gauge(
    "df_fleet_incidents", "incident bundles held in the bounded ring")
_pulse_bytes = REGISTRY.gauge(
    "df_fleet_pulse_bytes",
    "encoded size of the last ingested pulse digest (the per-announce "
    "piggyback overhead; dfbench --pr18 gates it at <= 512 B)")


class _Ewma:
    """EWMA mean/variance over one signal of one daemon's stream."""

    __slots__ = ("mean", "var", "n")

    def __init__(self) -> None:
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, x: float) -> None:
        if self.n == 0:
            self.mean = x
        else:
            d = x - self.mean
            self.mean += EWMA_ALPHA * d
            self.var = (1.0 - EWMA_ALPHA) * (self.var + EWMA_ALPHA * d * d)
        self.n += 1

    def z(self, x: float) -> float:
        # sd floor: a flat stream must not turn the first wiggle into an
        # infinite z — absolute floors in _SIGNALS carry the real gate
        sd = max(math.sqrt(max(self.var, 0.0)), 1.0, 0.1 * abs(self.mean))
        return (x - self.mean) / sd


class _Series:
    """One daemon's bounded pulse history + detector state."""

    __slots__ = ("ring", "last", "last_at", "first_at", "interval_s",
                 "ewma", "active", "silent", "samples")

    def __init__(self, ring: int) -> None:
        self.ring: deque = deque(maxlen=ring)
        self.last: dict[str, Any] = {}
        self.last_at = 0.0
        self.first_at = 0.0
        self.interval_s = 30.0
        self.ewma: dict[str, _Ewma] = {s: _Ewma() for s in _SIGNALS}
        self.active: dict[str, float] = {}   # anomaly kind -> since
        self.silent = False
        self.samples = 0


def _pulse_dict(pulse: Any) -> dict | None:
    """Accept a PulseDigest message or a plain dict; None on junk."""
    if pulse is None:
        return None
    if isinstance(pulse, dict):
        return pulse
    d = getattr(pulse, "__dict__", None)
    return dict(d) if isinstance(d, dict) else None


def _escalated(rungs: Any) -> int:
    """Serves beyond the primary ladder rung (docs/RESILIENCE.md): the
    count that grows when a pod degrades down the ladder."""
    if not isinstance(rungs, dict):
        return 0
    total = 0
    for name, n in rungs.items():
        if name not in (PRIMARY_RUNG, ""):
            try:
                total += int(n)
            except (TypeError, ValueError):
                continue
    return total


class FleetPulse:
    """Scheduler-side pulse ingest, rings, detector, incident capture.

    ``sink`` is the decision-ledger hook (``DecisionLedger.on_decision``
    in production, a plain list append in dfbench) — every anomaly
    firing lands there as a ``decision_kind=anomaly`` row. ``clock`` is
    injectable monotonic; dfbench drives it virtually so detection
    latency replays byte-identically.
    """

    def __init__(self, *, sink: Callable[[dict], None] | None = None,
                 quarantine=None, federation=None, statestore=None,
                 ring: int = PULSE_RING, incident_ring: int = INCIDENT_RING,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.sink = sink
        self.quarantine = quarantine
        self.federation = federation
        self.statestore = statestore
        self.ring = ring
        self.clock = clock
        self._series: dict[str, _Series] = {}
        self.incidents: deque = deque(maxlen=incident_ring)
        self.anomalies: deque = deque(maxlen=ANOMALY_LOG)
        self.anomaly_counts: dict[str, int] = {k: 0 for k in ANOMALY_KINDS}
        self.seq = 0                 # anomaly decision-id counter
        self.ingested = 0
        self.ignored = 0

    # -- ingest (the announce path: must never raise) -------------------

    def ingest(self, host_id: str, pulse: Any, *,
               interval_s: float = 30.0) -> bool:
        """Fold one announce's pulse into the rings and run the
        detector. Total: version skew, junk fields, or a crash anywhere
        inside is counted and swallowed — a daemon's telemetry must
        never be able to take the announce plane down."""
        try:
            return self._ingest(host_id, pulse, interval_s)
        except Exception as exc:  # noqa: BLE001 - announce path, never raise
            self.ignored += 1
            _pulse_total.labels("malformed").inc()
            log.warning("pulse from %s refused: %s", host_id, exc)
            return False

    def _ingest(self, host_id: str, pulse: Any, interval_s: float) -> bool:
        from ..idl.base import dumps
        from ..idl.messages import PULSE_VERSION

        p = _pulse_dict(pulse)
        if p is None or not host_id:
            self.ignored += 1
            _pulse_total.labels("malformed").inc()
            return False
        if p.get("v") != PULSE_VERSION:
            # unknown-version digest: a newer (or torn) daemon — ignored
            # WHOLESALE, never half-applied (the PEX schema-refusal rule)
            self.ignored += 1
            _pulse_total.labels("ignored_version").inc()
            return False
        now = self.clock()
        s = self._series.get(host_id)
        if s is None:
            s = self._series[host_id] = _Series(self.ring)
            s.first_at = now
            _daemons_gauge.set(len(self._series))
        if interval_s > 0:
            s.interval_s = float(interval_s)
        if s.silent:
            # the daemon is back: the silent-daemon episode ends here
            s.silent = False
            s.active.pop("silent-daemon", None)

        lag_ms = float(p.get("loop_lag_max_ms") or 0.0)
        cum = {
            "slo": int(p.get("slo_breaches") or 0),
            "rung": _escalated(p.get("served_rungs")),
            "shed": int(p.get("qos_shed") or 0),
            "corrupt": (int(p.get("corrupt_verdicts") or 0)
                        + int(p.get("shunned_parents") or 0)),
        }
        # counters are since-boot monotonic; a daemon restart resets them
        # (negative delta) — clamp to zero and re-baseline
        deltas = {k: max(v - int(s.last.get(k, 0)), 0)
                  for k, v in cum.items()}
        values = {
            "lag_ms": lag_ms,
            "slo_delta": float(deltas["slo"]),
            "rung_delta": float(deltas["rung"]),
            "shed_delta": float(deltas["shed"]),
            "corrupt_delta": float(deltas["corrupt"]),
        }

        sample = {
            "at": round(now, 3),
            "seq": int(p.get("seq") or 0),
            "flight": int(p.get("flight_tasks") or 0),
            "lag_ms": round(lag_ms, 3),
            "slo": cum["slo"],
            "rung_hi": cum["rung"],
            "shed": cum["shed"],
            "corrupt": cum["corrupt"],
            "qos": str(p.get("qos_state") or "normal"),
            "quar": bool(p.get("self_quarantined")),
        }
        prev_quar = bool(s.last.get("quar"))
        s.ring.append(sample)
        s.samples += 1
        s.last = dict(cum)
        s.last["quar"] = sample["quar"]
        s.last_at = now
        self.ingested += 1
        _pulse_total.labels("ok").inc()
        try:
            if not isinstance(pulse, dict):
                _pulse_bytes.set(len(dumps(pulse)))
        except Exception:  # noqa: BLE001 - size gauge is best-effort
            pass

        # -- detector: one pass per signal, exactly-once per episode
        for sig, value in values.items():
            kind, floor = _SIGNALS[sig]
            ew = s.ewma[sig]
            if kind in s.active:
                # latched: clear when the stream is back under both gates;
                # baseline stays FROZEN so the anomaly never becomes normal.
                # A corrupt-burst latched by the self-quarantine flag holds
                # until the flag clears, whatever the verdict deltas do.
                held = (kind == "corrupt-burst" and sample["quar"])
                if not held and (value < floor or ew.z(value) < Z_CLEAR):
                    s.active.pop(kind, None)
                    ew.update(value)
                continue
            if ew.n >= WARMUP_SAMPLES and value >= floor \
                    and ew.z(value) >= Z_THRESHOLD:
                self._fire(kind, host_id, s, now,
                           value=value, zscore=ew.z(value), signal=sig)
                continue
            ew.update(value)
        # self-quarantine flip is hard first-hand evidence, not a z-score
        # call: fire on the False->True transition, no warm-up required
        if sample["quar"] and not prev_quar \
                and "corrupt-burst" not in s.active:
            self._fire("corrupt-burst", host_id, s, now,
                       value=1.0, zscore=0.0, signal="self_quarantined")
        return True

    # -- tick (GC cadence): silent daemons + ring aging ------------------

    def tick(self) -> int:
        """Sweep for daemons whose announces stopped (``silent-daemon``)
        and age out series long gone (bounded memory under churn).
        Runs on the scheduler's GC ticker; returns fired + evicted."""
        now = self.clock()
        fired = 0
        evict: list[str] = []
        for host_id, s in self._series.items():
            gone_s = now - s.last_at
            if gone_s > EVICT_AFTER_INTERVALS * s.interval_s:
                # a tick cadence coarser than the silent window can jump
                # a dead daemon straight past the eviction horizon — the
                # death must still fire ONCE before the series goes
                if not s.silent and s.samples >= 1:
                    s.silent = True
                    self._fire("silent-daemon", host_id, s, now,
                               value=round(gone_s, 1), zscore=0.0,
                               signal="announce_gap_s")
                    fired += 1
                evict.append(host_id)
                continue
            if not s.silent and s.samples >= 1 \
                    and gone_s > SILENT_AFTER_INTERVALS * s.interval_s:
                s.silent = True
                self._fire("silent-daemon", host_id, s, now,
                           value=round(gone_s, 1), zscore=0.0,
                           signal="announce_gap_s")
                fired += 1
        for host_id in evict:
            del self._series[host_id]
        if evict:
            _daemons_gauge.set(len(self._series))
        return fired + len(evict)

    # -- anomaly firing + incident capture -------------------------------

    def _fire(self, kind: str, host_id: str, s: _Series, now: float, *,
              value: float, zscore: float, signal: str) -> None:
        s.active[kind] = now
        self.seq += 1
        self.anomaly_counts[kind] = self.anomaly_counts.get(kind, 0) + 1
        _anomalies_total.labels(kind).inc()
        row = {
            "kind": "decision",
            "decision_kind": "anomaly",
            "decision_id": f"a{self.seq:08d}.{kind}",
            "anomaly": kind,
            "host_id": host_id,
            "signal": signal,
            "value": round(float(value), 3),
            "zscore": round(float(zscore), 2),
            "at": round(now, 3),
            "task_id": "",
            "peer_id": "",
            "candidates": [],
            "excluded": [],
            "chosen": [host_id],
        }
        self.anomalies.append({k: row[k] for k in
                               ("decision_id", "anomaly", "host_id",
                                "signal", "value", "zscore", "at")})
        if self.sink is not None:
            self.sink(row)
        self.incidents.append(self._bundle(row, s))
        _incidents_gauge.set(len(self.incidents))
        log.warning("fleet anomaly %s on %s (%s=%.3f z=%.2f)",
                    kind, host_id, signal, value, zscore)

    def _bundle(self, row: dict, s: _Series) -> dict:
        """The post-hoc reconstruction kit: the offending daemon's recent
        pulse ring plus its standing in the quarantine ladder and the
        federation's pod map, captured AT firing time (state later moves
        on; the bundle is what the operator wishes they had screenshotted)."""
        bundle = {
            "id": row["decision_id"],
            "anomaly": row["anomaly"],
            "host_id": row["host_id"],
            "signal": row["signal"],
            "value": row["value"],
            "zscore": row["zscore"],
            "at": row["at"],
            "active": sorted(s.active),
            "pulses": list(s.ring),
        }
        if self.quarantine is not None:
            try:
                bundle["quarantine"] = self.quarantine.state(row["host_id"])
            except Exception:  # noqa: BLE001 - capture is best-effort
                bundle["quarantine"] = None
        if self.federation is not None:
            try:
                bundle["pod"] = self.federation.pod_of_host(row["host_id"])
            except Exception:  # noqa: BLE001 - capture is best-effort
                bundle["pod"] = ""
        return bundle

    # -- statestore integration (PR 17): incidents survive a crash -------

    def export_state(self) -> dict:
        """Incident history + anomaly totals for the scheduler snapshot.
        Per-daemon rings are trimmed to their tail: the full streams are
        fast-moving live telemetry the announce plane rebuilds within a
        few intervals — incident bundles are the part amnesia destroys."""
        return {
            "seq": self.seq,
            "anomaly_counts": dict(self.anomaly_counts),
            "incidents": list(self.incidents),
            "anomalies": list(self.anomalies)[-64:],
            "rings": {hid: list(s.ring)[-8:]
                      for hid, s in self._series.items()},
        }

    def restore(self, state: dict, *, gap_s: float = 0.0) -> int:
        """Refill the incident/anomaly rings from the snapshot. Detector
        baselines deliberately re-warm live (EWMA over a restart gap is
        stale evidence); restored ring tails give /debug/fleet history
        continuity across the failover."""
        n = 0
        self.seq = max(self.seq, int(state.get("seq") or 0))
        for kind, c in (state.get("anomaly_counts") or {}).items():
            if kind in self.anomaly_counts:
                self.anomaly_counts[kind] = max(
                    self.anomaly_counts[kind], int(c))
        for bundle in (state.get("incidents") or []):
            if isinstance(bundle, dict):
                self.incidents.append(bundle)
                n += 1
        for row in (state.get("anomalies") or []):
            if isinstance(row, dict):
                self.anomalies.append(row)
        for hid, tail in (state.get("rings") or {}).items():
            if not isinstance(tail, list):
                continue
            s = self._series.get(hid)
            if s is None:
                s = self._series[hid] = _Series(self.ring)
            for sample in tail:
                if isinstance(sample, dict):
                    s.ring.append(sample)
            n += 1
        _incidents_gauge.set(len(self.incidents))
        _daemons_gauge.set(len(self._series))
        return n

    def state_bytes(self) -> int:
        import sys
        return sum(sys.getsizeof(s.ring) + sys.getsizeof(s.last)
                   for s in self._series.values()) \
            + sys.getsizeof(self.incidents)

    # -- /debug/fleet -----------------------------------------------------

    def snapshot(self, *, compact: bool = False) -> dict:
        """The ``GET /debug/fleet`` payload: fleet rollups over each
        daemon's LATEST sample, active episodes, recent anomalies, and
        the incident ring (ids only when ``compact`` — stress reports
        attach this; the full bundles stay behind the debug port)."""
        now = self.clock()
        latest = [(hid, s.ring[-1]) for hid, s in self._series.items()
                  if s.ring]
        active = [{"host_id": hid, "anomaly": kind,
                   "since_s": round(now - since, 1)}
                  for hid, s in self._series.items()
                  for kind, since in sorted(s.active.items())]
        qos_states: dict[str, int] = {}
        for _, smp in latest:
            qos_states[smp["qos"]] = qos_states.get(smp["qos"], 0) + 1
        fleet = {
            "flight_tasks": sum(smp["flight"] for _, smp in latest),
            "loop_lag_max_ms": round(
                max((smp["lag_ms"] for _, smp in latest), default=0.0), 3),
            "slo_breaches": sum(smp["slo"] for _, smp in latest),
            "escalated_serves": sum(smp["rung_hi"] for _, smp in latest),
            "qos_shed": sum(smp["shed"] for _, smp in latest),
            "corrupt_verdicts": sum(smp["corrupt"] for _, smp in latest),
            "self_quarantined": sum(1 for _, smp in latest if smp["quar"]),
            "qos_states": qos_states,
        }
        out = {
            "daemons": len(self._series),
            "samples": sum(s.samples for s in self._series.values()),
            "ingested": self.ingested,
            "ignored": self.ignored,
            "ring": {"per_daemon": self.ring,
                     "incidents_max": self.incidents.maxlen},
            "fleet": fleet,
            "active": sorted(active, key=lambda a: (a["anomaly"],
                                                    a["host_id"])),
            "anomaly_counts": {k: v for k, v in
                               sorted(self.anomaly_counts.items()) if v},
            "recent_anomalies": list(self.anomalies)[-20:],
            "incidents": len(self.incidents),
        }
        if compact:
            out["incident_ids"] = [b.get("id") for b in
                                   list(self.incidents)[-10:]]
        else:
            out["incident_bundles"] = list(self.incidents)[-10:]
        # recovered-vs-rebuilt provenance (same honesty contract as
        # /debug/ctrl): did this incident history survive a failover?
        if self.statestore is not None:
            out["recovery"] = self.statestore.provenance
        return out


def add_fleet_routes(router, fp: FleetPulse) -> None:
    """``GET /debug/fleet`` — mounted on the scheduler launcher's
    --debug-port server next to /debug/cluster and /debug/ctrl.
    ``?compact=1`` returns incident ids instead of full bundles (the
    stress.py --fleet-report shape)."""
    from aiohttp import web

    async def fleet(req: web.Request) -> web.Response:
        compact = req.query.get("compact", "") in ("1", "true")
        return web.json_response(fp.snapshot(compact=compact))

    router.add_get("/debug/fleet", fleet)
