"""Cross-pod federation: per-pod seed election + DCN routing policy.

Role parity: none in the reference — Dragonfly2's scheduler treats the
whole cluster as one flat peer pool, which at TPU scale recreates the
feeder-limited regime of the MLPerf-on-pods papers: every pod's daemons
independently cross the thin DCN links (or hammer the origin) while
4.8 TB/s of ICI sits idle. This module gives the scheduler the second
tree level (ROADMAP item 2): for each (task, pod) a small SEED SET is
elected by hash-ring over the pod's announced members — quarantine-aware,
exactly like ``SeedPeerClient._elect`` walks the origin-seed ring — and
only those seeds may take cross-pod parents. Everyone else stays inside
the pod, so the distribution chain is origin → pod-seed (one DCN copy
per pod) → in-pod ICI relay tree (PR 9 cut-through).

The view is fed from the announce plane (``observe_host`` on every
register/AnnounceHost, ``forget_host`` on leave — the same cadence the
quarantine registry rides), so elections are a pure deterministic
function of {task id, pod membership, quarantine state}. A seed that
dies (host leave / stream gone) or walks into quarantine is replaced by
the next clockwise ring member on the next ruling that needs it — the
mid-pull seed-kill chaos path — and every (re)election is emitted as a
``kind=decision`` row (``decision_kind="federation"``) so federation
fairness is offline-replayable like every other ruling.

Hosts with NO pod identity (``tpu.topology.pod_id`` == "", the plain
DCN peer fallback) are never restricted: a topology-less cluster runs
the exact pre-federation scoring path.
"""

from __future__ import annotations

import logging

from ..common.metrics import REGISTRY
from ..idl.messages import TopologyInfo
from ..rpc.balancer import HashRing
from ..tpu.topology import pod_id

log = logging.getLogger("df.sched.federation")

_pods_gauge = REGISTRY.gauge(
    "df_federation_pods",
    "pods (ICI bandwidth domains) currently known to the federation view")
_elections = REGISTRY.counter(
    "df_federation_elections_total",
    "per-pod seed-set elections, by outcome (elected = a fresh ruling, "
    "reelected = a dead/quarantined seed replaced mid-task, exhausted = "
    "every pod member unusable so the hashed members serve anyway)",
    ("result",))


def walk_ring(ring: HashRing, key: str, members: int, quarantine,
              n: int = 1) -> list[str]:
    """The shared quarantine-aware ring walk: the ``n`` first hashed
    members that are offerable, walking clockwise past QUARANTINED ones.
    With every member quarantined the hashed prefix still serves — a
    wholly quarantined membership beats no injection path at all
    (``SeedPeerClient._elect`` semantics, now shared with the per-pod
    election so both tiers of the tree skip poisoned roots the same
    way)."""
    cands = ring.pick_n(key, members)
    if quarantine is None:
        return cands[:n]
    ok = [hid for hid in cands if quarantine.offerable(hid)]
    return ok[:n] if ok else cands[:n]


class PodFederation:
    """The scheduler's pod view + per-task seed elections.

    Synchronous dict work on the scheduler loop; membership churns at
    announce cadence and elections are memoized per (task, pod), so
    nothing here rides the per-piece hot path."""

    MAX_ELECTIONS = 4096      # (task, pod) memo bound; see seeds_for

    def __init__(self, *, seeds_per_pod: int = 1, quarantine=None,
                 sink=None):
        self.seeds_per_pod = max(1, seeds_per_pod)
        self.quarantine = quarantine
        # decision-ledger hook: callable(row dict) per (re)election ruling
        self.sink = sink
        self._pod_of: dict[str, str] = {}          # host_id -> pod
        self._members: dict[str, set[str]] = {}    # pod -> host ids
        self._rings: dict[str, HashRing] = {}      # pod -> member ring
        self._elected: dict[tuple[str, str], list[str]] = {}
        self._result: dict[tuple[str, str], str] = {}   # last emitted kind
        self._seq = 0

    # -- membership (announce plane) -----------------------------------

    def observe_host(self, host_id: str,
                     topology: TopologyInfo | None) -> None:
        """Register/announce hook. Re-announcing the same coordinates is
        a no-op (pod id is a pure function of them), so elections stay
        sticky across the announce cadence; a host whose pod CHANGES
        (re-scheduled onto another slice) moves rings."""
        pod = pod_id(topology)
        prev = self._pod_of.get(host_id)
        if prev == pod:
            return
        if prev is not None:
            self._drop_member(host_id, prev)
        self._pod_of[host_id] = pod
        if pod:
            self._members.setdefault(pod, set()).add(host_id)
            ring = self._rings.get(pod)
            if ring is None:
                ring = self._rings[pod] = HashRing()
            ring.add(host_id)
        _pods_gauge.set(len(self._members))

    def forget_host(self, host_id: str) -> None:
        """Leave/GC/stream-gone hook: the host stops being electable NOW;
        tasks it was seeding re-elect on their next ruling."""
        pod = self._pod_of.pop(host_id, None)
        if pod:
            self._drop_member(host_id, pod)
        _pods_gauge.set(len(self._members))

    def _drop_member(self, host_id: str, pod: str) -> None:
        members = self._members.get(pod)
        if members is not None:
            members.discard(host_id)
            if not members:
                del self._members[pod]
                self._rings.pop(pod, None)
        ring = self._rings.get(pod)
        if ring is not None:
            ring.remove(host_id)

    def pod_of_host(self, host_id: str) -> str:
        return self._pod_of.get(host_id, "")

    # -- election ------------------------------------------------------

    def _usable(self, host_id: str, pod: str) -> bool:
        if host_id not in self._members.get(pod, ()):
            return False
        return self.quarantine is None or self.quarantine.offerable(host_id)

    def seeds_for(self, task_id: str, pod: str) -> list[str]:
        """The pod's elected seed set for this task — sticky while every
        elected seed stays usable, re-walked (and re-journaled) the
        moment one dies or walks into quarantine."""
        if not pod:
            return []
        key = (task_id, pod)
        cached = self._elected.get(key)
        if cached is not None and all(self._usable(h, pod) for h in cached) \
                and self._result.get(key) != "exhausted":
            # fast path: the election stands. An 'exhausted' memo whose
            # seeds became usable again falls through so the recovery is
            # re-classified (and journaled) instead of silently reusing
            # a ruling made under duress.
            return cached
        ring = self._rings.get(pod)
        members = self._members.get(pod, ())
        if ring is None or not members:
            self._elected.pop(key, None)
            return []
        elected = walk_ring(ring, task_id, len(members), self.quarantine,
                            n=self.seeds_per_pod)
        if self.quarantine is not None \
                and not any(self.quarantine.offerable(h) for h in elected):
            result = "exhausted"
        else:
            result = "reelected" if cached is not None else "elected"
        if cached is not None and elected == cached \
                and self._result.get(key) == result:
            # the re-walk landed on the same ruling IN THE SAME state
            # (the wholly-quarantined exhaustion fallback re-walks per
            # call): refresh the memo silently — re-emitting an
            # identical ruling per allows()/note() call would flood the
            # ledger and the counter at per-candidate rate. A CHANGED
            # classification over the same seeds (healthy -> exhausted,
            # or the recovery back) still emits: operators must see the
            # pod start/stop routing through a quarantined seed.
            self._elected[key] = elected
            return elected
        _elections.labels(result).inc()
        self._result[key] = result
        if len(self._elected) >= self.MAX_ELECTIONS:
            # bounded memo: tasks are GC'd by the resource plane, not
            # here — evict the oldest ruling (insertion-ordered dict);
            # a live task that loses its memo just re-elects the same
            # seeds (pure function of membership + quarantine state)
            oldest = next(iter(self._elected))
            self._elected.pop(oldest)
            self._result.pop(oldest, None)
        self._elected[key] = elected
        if cached is not None:
            log.info("federation: pod %s re-elected seeds %s for task %s "
                     "(was %s)", pod, elected, task_id[:12], cached)
        self._emit(task_id, pod, elected, cached, result)
        return elected

    def _emit(self, task_id: str, pod: str, elected: list[str],
              prev: list[str] | None, result: str) -> None:
        if self.sink is None:
            return
        self._seq += 1
        self.sink({
            "kind": "decision",
            "decision_id": f"f{self._seq:08d}.{pod[-12:]}",
            "decision_kind": "federation",
            "task_id": task_id,
            "pod": pod,
            "result": result,
            "elected": list(elected),
            "previous": list(prev) if prev is not None else None,
            "pod_members": len(self._members.get(pod, ())),
            "candidates": [],
            "excluded": [],
            "chosen": list(elected),
        })

    def drop_task(self, task_id: str) -> None:
        """Task GC (``Resource.on_task_evict``): elections die with the
        task."""
        for key in [k for k in self._elected if k[0] == task_id]:
            del self._elected[key]
            self._result.pop(key, None)

    # -- routing policy (scheduling filter) ----------------------------

    def allows(self, child, parent) -> bool:
        """May ``parent`` serve ``child``? Same pod (or either side
        pod-less): always. Cross-pod: only when the child is one of its
        pod's elected seeds — everyone else gets the bytes one in-pod
        hop later, off the pod seed's ICI tree, instead of opening one
        more DCN stream per child."""
        ctopo = child.host.msg.topology
        ptopo = parent.host.msg.topology
        cpod, ppod = pod_id(ctopo), pod_id(ptopo)
        if not cpod or not ppod or cpod == ppod:
            return True
        # READ-ONLY on purpose: re-observing the child here would
        # re-admit a host forget_host just evicted (a dead seed's OTHER
        # task rules between its two streams' death detections) — the
        # announce plane is the only admission path. A child the view
        # has not seen yet simply is not a seed, and joins the
        # electorate at its next announce.
        return child.host.id in self.seeds_for(child.task.id, cpod)

    def note(self, child) -> dict | None:
        """Per-ruling ledger annotation: the child's pod, its elected
        seed set, and whether this child IS one — why its candidate set
        does or does not cross the DCN, answerable from the row alone."""
        cpod = pod_id(child.host.msg.topology)
        if not cpod:
            return None
        seeds = self.seeds_for(child.task.id, cpod)
        return {"pod": cpod, "pod_seeds": seeds,
                "is_pod_seed": child.host.id in seeds}

    # -- debug ---------------------------------------------------------

    def state_bytes(self) -> int:
        """Bytes of federation state (pod membership, rings, election
        memos) for the /debug/ctrl bytes-per-peer accounting. Deep
        sizeof walk — snapshot cadence only, never on a ruling path."""
        from ..common.sizeof import deep_sizeof
        seen: set = set()
        return sum(deep_sizeof(o, seen) for o in (
            self._pod_of, self._members, self._rings,
            self._elected, self._result))

    def describe(self) -> dict:
        return {
            "seeds_per_pod": self.seeds_per_pod,
            "pods": {pod: sorted(members)
                     for pod, members in sorted(self._members.items())},
            "elections": {f"{tid[:12]}/{pod}": seeds
                          for (tid, pod), seeds in
                          sorted(self._elected.items())},
        }

    # -- durable state (scheduler/statestore.py) -------------------------

    def export_state(self) -> dict:
        """Seed elections + the pod map they stand on. ``pod_of`` IS
        persisted even though membership is announce-fed: ``seeds_for``
        destroys an election memo the moment its pod has no ring, so a
        restore that carried elections without the membership they were
        ruled over would discard every one of them on first query —
        exactly the re-election stampede durability exists to prevent.
        Hosts that died during the outage are evicted the normal way
        (host GC / leave → ``forget_host``) once the live view catches
        up."""
        return {
            "seq": self._seq,
            "pod_of": dict(self._pod_of),
            "elected": [[tid, pod, seeds]
                        for (tid, pod), seeds in self._elected.items()],
            "result": [[tid, pod, res]
                       for (tid, pod), res in self._result.items()],
        }

    def restore(self, state: dict) -> int:
        """Rebuild pods, rings, and election memos from
        :meth:`export_state` output — membership FIRST (rings must exist
        before any ``seeds_for`` runs), memos second, silently: a
        restored election that still stands emits no fresh ledger row."""
        for hid, pod in (state.get("pod_of") or {}).items():
            if pod and hid not in self._pod_of:
                self._pod_of[hid] = pod
                self._members.setdefault(pod, set()).add(hid)
                ring = self._rings.get(pod)
                if ring is None:
                    ring = self._rings[pod] = HashRing()
                ring.add(hid)
        restored = 0
        for tid, pod, seeds in (state.get("elected") or ()):
            self._elected[(tid, pod)] = list(seeds)
            restored += 1
        for tid, pod, res in (state.get("result") or ()):
            self._result[(tid, pod)] = res
        self._seq = max(self._seq, int(state.get("seq", 0)))
        _pods_gauge.set(len(self._members))
        return restored
