"""``ml`` evaluator: scores parents with the trained bandwidth predictor.

Role parity: the slot the reference left as a TODO
(``scheduler/scheduling/evaluator/evaluator.go:84-86`` falls back to base).
Completing this loop is BASELINE config #5: records written by
``scheduler/records.py`` flow to the trainer (``trainer/service.py``), the
MLP fits on TPU (``trainer/training.py``), the manager versions the result,
and the scheduler serves it here via ``trainer/serving.py``.

``parent_feature_row`` is the single feature extractor used BOTH at record
time and at scoring time (layout: ``trainer/features.PARENT_FEATURES``) —
train/serve skew is a schema violation, not a runtime possibility.

Falls back to the rule-based score whenever inference is unavailable, the
feature row cannot be built, or the model emits a non-finite score —
the heuristic floor is the worst case, never a crashed or NaN ranking.
Every fallback while a model is bound increments ``df_ml_fallback_total``
and is remembered in ``health()`` so ``/debug/ctrl`` and dfdiag can name
the degraded evaluator. ``infer`` may be (re)bound at runtime as new model
versions land.
"""

from __future__ import annotations

import logging
import math

from ..common.metrics import REGISTRY
from .evaluator import Evaluator
from .resource import Peer

log = logging.getLogger("df.sched.eval_ml")

_BASE = Evaluator()

_scored_total = REGISTRY.counter(
    "df_ml_scored_total",
    "candidate scorings answered by the served model (not the fallback)")
_fallback_total = REGISTRY.counter(
    "df_ml_fallback_total",
    "candidate scorings that fell back to the heuristic floor while a "
    "model was bound", ("reason",))


def parent_feature_row(child: Peer, parent: Peer, *,
                       total_piece_count: int) -> list[float]:
    """Feature layout per ``trainer/features.PARENT_FEATURES`` — keep in sync."""
    return [
        _BASE._piece_score(parent, total_piece_count),
        parent.host.upload_success_ratio(),
        _BASE._free_upload_score(parent),
        _BASE._host_type_score(parent),
        _BASE._locality_score(child, parent),
        float(len(parent.finished_pieces)),
        float(parent.host.concurrent_upload_count),
    ]


class MLEvaluator(Evaluator):
    def __init__(self, infer=None):
        """``infer(features: list[list[float]]) -> list[float]`` returns a
        predicted goodness per row (higher = better parent). ``None`` until
        a model is served; the base score covers the cold start."""
        self.infer = infer
        self.scored = 0              # rulings the model actually answered
        self.fallbacks = 0           # rulings pushed back to the floor
        self.last_fallback_reason = ""

    def _predict(self, child: Peer, parent: Peer, *,
                 total_piece_count: int) -> float | None:
        """One model score, or None → caller uses the heuristic floor.
        The floor is guaranteed: any exception AND any non-finite output
        degrade to base — a garbage model can slow nothing down and rank
        nothing below what the heuristic would have ruled."""
        try:
            row = self.feature_row(child, parent,
                                   total_piece_count=total_piece_count)
            out = self.infer([row])
            if not out:
                return None
            score = float(out[0])
            if not math.isfinite(score):
                raise ValueError(f"non-finite model score {score!r}")
        except Exception as exc:  # noqa: BLE001 - model serving is optional
            reason = ("non_finite" if "non-finite" in str(exc) else "error")
            self.fallbacks += 1
            self.last_fallback_reason = f"{reason}: {exc}"
            _fallback_total.labels(reason).inc()
            log.debug("ml inference failed (%s); using base score", exc)
            return None
        self.scored += 1
        _scored_total.inc()
        return score

    def health(self) -> dict:
        """Serving provenance for ``/debug/ctrl``: which model version is
        answering, how often it answered vs fell back, and why the last
        fallback happened. ``degraded`` means a model is bound but the
        floor is doing (some of) the ruling."""
        return {
            "version": getattr(self.infer, "version", "") or "",
            "bound": self.infer is not None,
            "scored": self.scored,
            "fallbacks": self.fallbacks,
            "last_fallback_reason": self.last_fallback_reason,
            "degraded": self.infer is not None and self.fallbacks > 0,
        }

    def evaluate(self, child: Peer, parent: Peer, *,
                 total_piece_count: int) -> float:
        if self.infer is not None:
            score = self._predict(child, parent,
                                  total_piece_count=total_piece_count)
            if score is not None:
                return score
        return super().evaluate(child, parent,
                                total_piece_count=total_piece_count)

    def explain(self, child: Peer, parent: Peer, *,
                total_piece_count: int) -> dict:
        """Decision-ledger decomposition: base terms stay for context;
        when the served model answered, the total is the model's and the
        row says so (``substituted: {"total": "ml"}``, heuristic total
        preserved as ``base_total``). Mirrors ``evaluate``'s control flow
        exactly — including the fallback — so the logged total is always
        the score the ranking actually used."""
        out = super().explain(child, parent,
                              total_piece_count=total_piece_count)
        if self.infer is not None:
            score = self._predict(child, parent,
                                  total_piece_count=total_piece_count)
            if score is not None:
                out["base_total"] = out["total"]
                out["total"] = score
                out["substituted"] = {"total": "ml"}
        return out

    def feature_row(self, child: Peer, parent: Peer, *,
                    total_piece_count: int) -> list[float]:
        return parent_feature_row(child, parent,
                                  total_piece_count=total_piece_count)
