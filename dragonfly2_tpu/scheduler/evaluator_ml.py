"""``ml`` evaluator: scores parents with the trained bandwidth predictor.

Role parity: the slot the reference left as a TODO
(``scheduler/scheduling/evaluator/evaluator.go:84-86`` falls back to base).
Completing this loop is BASELINE config #5: records written by
``scheduler/records.py`` flow to the trainer (``trainer/service.py``), the
MLP fits on TPU (``trainer/training.py``), the manager versions the result,
and the scheduler serves it here via ``trainer/serving.py``.

``parent_feature_row`` is the single feature extractor used BOTH at record
time and at scoring time (layout: ``trainer/features.PARENT_FEATURES``) —
train/serve skew is a schema violation, not a runtime possibility.

Falls back to the rule-based score whenever inference is unavailable or the
feature row cannot be built; ``infer`` may be (re)bound at runtime as new
model versions land.
"""

from __future__ import annotations

import logging

from .evaluator import Evaluator
from .resource import Peer

log = logging.getLogger("df.sched.eval_ml")

_BASE = Evaluator()


def parent_feature_row(child: Peer, parent: Peer, *,
                       total_piece_count: int) -> list[float]:
    """Feature layout per ``trainer/features.PARENT_FEATURES`` — keep in sync."""
    return [
        _BASE._piece_score(parent, total_piece_count),
        parent.host.upload_success_ratio(),
        _BASE._free_upload_score(parent),
        _BASE._host_type_score(parent),
        _BASE._locality_score(child, parent),
        float(len(parent.finished_pieces)),
        float(parent.host.concurrent_upload_count),
    ]


class MLEvaluator(Evaluator):
    def __init__(self, infer=None):
        """``infer(features: list[list[float]]) -> list[float]`` returns a
        predicted goodness per row (higher = better parent). ``None`` until
        a model is served; the base score covers the cold start."""
        self.infer = infer

    def evaluate(self, child: Peer, parent: Peer, *,
                 total_piece_count: int) -> float:
        if self.infer is not None:
            try:
                row = self.feature_row(child, parent,
                                       total_piece_count=total_piece_count)
                out = self.infer([row])
                if out:
                    return float(out[0])
            except Exception as exc:  # noqa: BLE001 - model serving is optional
                log.debug("ml inference failed (%s); using base score", exc)
        return super().evaluate(child, parent,
                                total_piece_count=total_piece_count)

    def explain(self, child: Peer, parent: Peer, *,
                total_piece_count: int) -> dict:
        """Decision-ledger decomposition: base terms stay for context;
        when the served model answered, the total is the model's and the
        row says so (``substituted: {"total": "ml"}``, heuristic total
        preserved as ``base_total``). Mirrors ``evaluate``'s control flow
        exactly — including the fallback — so the logged total is always
        the score the ranking actually used."""
        out = super().explain(child, parent,
                              total_piece_count=total_piece_count)
        if self.infer is not None:
            try:
                row = self.feature_row(child, parent,
                                       total_piece_count=total_piece_count)
                pred = self.infer([row])
                if pred:
                    out["base_total"] = out["total"]
                    out["total"] = float(pred[0])
                    out["substituted"] = {"total": "ml"}
            except Exception as exc:  # noqa: BLE001 - model serving is optional
                log.debug("ml inference failed (%s); explaining base score",
                          exc)
        return out

    def feature_row(self, child: Peer, parent: Peer, *,
                    total_piece_count: int) -> list[float]:
        return parent_feature_row(child, parent,
                                  total_piece_count=total_piece_count)
