"""``ml`` evaluator: scores parents with the trained bandwidth predictor.

Role parity: the slot the reference left as a TODO
(``scheduler/scheduling/evaluator/evaluator.go:84-86`` falls back to base).
Completing this loop is BASELINE config #5: the trainer fits the model on
TPU (``trainer/training.py``) and the scheduler queries it here.

Falls back to the rule-based score whenever inference is unavailable or the
feature row cannot be built.
"""

from __future__ import annotations

import logging

from .evaluator import Evaluator
from .resource import Peer

log = logging.getLogger("df.sched.eval_ml")


class MLEvaluator(Evaluator):
    def __init__(self, infer):
        """``infer(features: list[list[float]]) -> list[float]`` returns a
        predicted goodness per row (higher = better parent)."""
        self.infer = infer

    def evaluate(self, child: Peer, parent: Peer, *,
                 total_piece_count: int) -> float:
        try:
            row = self.feature_row(child, parent,
                                   total_piece_count=total_piece_count)
            out = self.infer([row])
            if out:
                return float(out[0])
        except Exception as exc:  # noqa: BLE001 - model serving is optional
            log.debug("ml inference failed (%s); using base score", exc)
        return super().evaluate(child, parent,
                                total_piece_count=total_piece_count)

    def feature_row(self, child: Peer, parent: Peer, *,
                    total_piece_count: int) -> list[float]:
        """Feature layout shared with ``trainer/features.py`` — keep in sync."""
        return [
            self._piece_score(parent, total_piece_count),
            parent.host.upload_success_ratio(),
            self._free_upload_score(parent),
            self._host_type_score(parent),
            self._locality_score(child, parent),
            float(len(parent.finished_pieces)),
            float(parent.host.concurrent_upload_count),
        ]
