"""Download-record storage: the trainer's dataset, written at report time.

Role parity: reference ``scheduler/storage/storage.go:142`` (CreateDownload
CSV append with rotation) + the record schemas in
``scheduler/storage/types.go:30-297``. TPU-native change: rows carry the
exact ``trainer/features.py`` feature vector computed at piece-report time,
so the trainer fits on precisely what the ``ml`` evaluator will see at
scoring time — no train/serve skew (the reference's CSVs logged raw
entities and left feature extraction to the unfinished trainer).

Rows are JSONL: an in-memory ring for the announcer to drain + an optional
append-only file with size rotation for post-mortems.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time

from ..common.metrics import REGISTRY
from ..trainer.features import FEATURE_DIM, label_from_cost
from .evaluator_ml import parent_feature_row
from .resource import Peer

log = logging.getLogger("df.sched.records")

_rows_total = REGISTRY.counter(
    "df_records_rows_total", "record rows appended to the ring", ("kind",))
_dropped = REGISTRY.counter(
    "df_records_dropped_total",
    "record rows dropped by the drop-oldest ring bound")
_flush_failures = REGISTRY.counter(
    "df_records_flush_failures_total",
    "record-file flush batches that failed (rows lost from the file copy)")
_rotations = REGISTRY.counter(
    "df_records_rotations_total", "download.jsonl size rotations")

MAX_BUFFERED_ROWS = 50_000          # ring bound: drop-oldest beyond this
ROTATE_BYTES = 64 << 20             # rotate download.jsonl past 64 MiB
FLUSH_BATCH_ROWS = 64               # file-write batch size
FLUSH_MAX_AGE_S = 1.0               # flush at least this often while rows flow


class DownloadRecords:
    """Implements the ``records`` hook of ``SchedulerService``."""

    def __init__(self, records_dir: str = ""):
        self.records_dir = records_dir
        self._rows: list[dict] = []
        self._peer_rows: list[dict] = []
        self._file = None
        self._file_bytes = 0
        self._pending: list[str] = []
        self._flush_task: asyncio.Task | None = None
        self._timer_task: asyncio.Task | None = None
        self._last_flush = time.time()
        if records_dir:
            os.makedirs(records_dir, exist_ok=True)
            self._open_file()

    def _open_file(self) -> None:
        path = os.path.join(self.records_dir, "download.jsonl")
        # dflint: disable=DF001 — rotation check: two stats per rotation boundary, not per row
        if os.path.exists(path) and os.path.getsize(path) > ROTATE_BYTES:
            # dflint: disable=DF001 — rare size-boundary rotation, metadata syscall
            os.replace(path, path + ".1")
            _rotations.inc()
        # dflint: disable=DF001 — append-mode open once per rotation window
        self._file = open(path, "a", encoding="utf-8")
        self._file_bytes = self._file.tell()

    # -- hooks called by SchedulerService ------------------------------

    def on_piece(self, peer: Peer, result) -> None:
        """One row per successful piece fetched from a parent: the features
        the scheduler saw + the throughput label it observed."""
        if not result.dst_peer_id or result.piece_info is None:
            return
        parent = peer.task.peers.get(result.dst_peer_id)
        if parent is None:
            return
        info = result.piece_info
        features = parent_feature_row(
            peer, parent, total_piece_count=peer.task.total_piece_count)
        row = {
            "kind": "piece",
            "task_id": peer.task.id,
            "peer_id": peer.id,
            "host_id": peer.host.id,
            # join key to the kind=decision row whose offer this piece
            # acted on (the child's newest ruling at scoring time)
            "decision_id": peer.last_decision_id,
            "parent_peer_id": parent.id,
            "parent_host_id": parent.host.id,
            "piece_num": info.piece_num,
            "piece_length": info.range_size,
            "cost_ms": info.download_cost_ms,
            "success": True,
            "fail_code": "",
            "features": features,
            "label": label_from_cost(info.range_size, info.download_cost_ms),
            "created_at": time.time(),
        }
        self._append(row)

    def on_piece_fail(self, peer: Peer, result) -> None:
        """One row per FAILED piece fetch, carrying the typed
        ``fail_code`` (idl.FAIL_CODES): the outcome join can now learn
        what KIND of failure a ruling produced — a ``corrupt`` verdict
        against a chosen parent is the signal the quarantine ladder
        promoted, and an offline replay should see it too. Label 0.0: a
        failed fetch is a zero-quality outcome for the (decision,
        parent) pair."""
        if not result.dst_peer_id:
            return
        if not getattr(result, "fail_code", ""):
            # untyped failures are backpressure shapes (the engine leaves
            # busy 503s codeless on purpose): a loaded-but-good parent
            # must not teach the trainer that offering it was a
            # zero-quality ruling
            return
        parent = peer.task.peers.get(result.dst_peer_id)
        if parent is None:
            return
        info = result.piece_info
        features = parent_feature_row(
            peer, parent, total_piece_count=peer.task.total_piece_count)
        row = {
            "kind": "piece",
            "task_id": peer.task.id,
            "peer_id": peer.id,
            "host_id": peer.host.id,
            "decision_id": peer.last_decision_id,
            "parent_peer_id": parent.id,
            "parent_host_id": parent.host.id,
            "piece_num": info.piece_num if info is not None else -1,
            "piece_length": info.range_size if info is not None else 0,
            "cost_ms": 0,
            "success": False,
            "fail_code": str(getattr(result, "fail_code", "") or ""),
            "relayed": bool(getattr(result, "relayed", False)),
            "features": features,
            "label": 0.0,
            "created_at": time.time(),
        }
        self._append(row)

    def on_peer(self, peer: Peer, result) -> None:
        """Terminal row per peer run (reference Download record: one line
        per finished download with task/host/parent context)."""
        row = {
            "kind": "peer",
            "task_id": peer.task.id,
            "peer_id": peer.id,
            "host_id": peer.host.id,
            "state": peer.state.value,
            "success": bool(result.success),
            "content_length": result.content_length,
            "total_piece_count": result.total_piece_count,
            "cost_ms": result.cost_ms,
            "finished_pieces": len(peer.finished_pieces),
            "schedule_count": peer.schedule_count,
            "report_fail_count": peer.report_fail_count,
            "created_at": time.time(),
        }
        self._append_peer_row(row)

    def on_flight(self, peer: Peer, summary: dict) -> None:
        """Latency-attribution row per finished peer run, from the daemon's
        flight recorder: where the time went (queue/wire/HBM), per-parent
        throughput, tail latencies. The trainer learns from attribution
        the piece rows alone cannot carry (a slow piece row does not say
        WHY it was slow)."""
        row = {
            "kind": "flight",
            "task_id": peer.task.id,
            "peer_id": peer.id,
            "host_id": peer.host.id,
            "summary": summary,
            "created_at": time.time(),
        }
        self._append_peer_row(row)
        # per-edge bandwidth rows (podscope schema): one row per parent
        # that served this flight, with the observed edge throughput —
        # the feature/label source the learned parent-quality model
        # (ROADMAP item 1) trains on, and the same shape `dfdiag --pod`
        # reconstructs live from the daemon set
        from ..common.podscope import edges_from_summary
        now = time.time()
        for edge in edges_from_summary(peer.task.id, peer.id,
                                       peer.host.id, summary):
            edge["created_at"] = now
            self._append_peer_row(edge)

    def on_decision(self, row: dict) -> None:
        """One row per scheduler ruling (``Scheduling._decide`` via the
        decision ledger): the candidate set with per-term decomposition,
        exclusions, and the chosen offer — the decision half that
        ``kind=piece``/``kind=edge`` outcome rows join against."""
        if "created_at" not in row:
            row = dict(row)
            row["created_at"] = time.time()
        self._append_peer_row(row)

    # -- internals -----------------------------------------------------

    def _append_peer_row(self, row: dict) -> None:
        """Ring-append a non-piece (peer/flight/edge/decision) row +
        buffer its line."""
        self._peer_rows.append(row)
        _rows_total.labels(str(row.get("kind", ""))).inc()
        if len(self._peer_rows) > MAX_BUFFERED_ROWS:
            _dropped.inc(len(self._peer_rows) - MAX_BUFFERED_ROWS)
            self._peer_rows = self._peer_rows[-MAX_BUFFERED_ROWS:]
        self._write(row)

    def _append(self, row: dict) -> None:
        self._rows.append(row)
        _rows_total.labels(str(row.get("kind", ""))).inc()
        if len(self._rows) > MAX_BUFFERED_ROWS:
            _dropped.inc(len(self._rows) - MAX_BUFFERED_ROWS)
            self._rows = self._rows[-MAX_BUFFERED_ROWS:]
        self._write(row)

    def _write(self, row: dict) -> None:
        """Buffer the row's line; file IO happens in worker threads in
        batches. This runs inside ``_handle_piece_result`` — one synchronous
        disk write per piece report would stall every scheduling RPC on the
        event loop at fan-out rates (thousands of reports/s)."""
        if self._file is None:
            return
        self._pending.append(json.dumps(row) + "\n")
        self._ensure_timer()   # from the FIRST buffered row, not first flush
        if (len(self._pending) >= FLUSH_BATCH_ROWS
                or time.time() - self._last_flush > FLUSH_MAX_AGE_S):
            self._schedule_flush()

    def _ensure_timer(self) -> None:
        if self._timer_task is not None:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        self._timer_task = loop.create_task(self._timer_flush())

    def _schedule_flush(self) -> None:
        batch, self._pending = self._pending, []
        self._last_flush = time.time()
        prev = self._flush_task

        async def run() -> None:
            if prev is not None and not prev.done():
                try:
                    await asyncio.shield(prev)  # keep append order
                except Exception:               # noqa: BLE001
                    # a failed earlier batch must not take this one with it
                    log.warning("previous record flush failed", exc_info=True)
            await asyncio.to_thread(self._flush_sync, batch)

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:                    # no loop (sync tests/tools)
            self._flush_sync(batch)
            return
        self._flush_task = loop.create_task(run())

    async def _timer_flush(self) -> None:
        """Age-based flush: _write only checks FLUSH_MAX_AGE_S on the next
        row, so under a trickle the last <64 rows would sit buffered
        indefinitely without this."""
        while self._file is not None:
            await asyncio.sleep(FLUSH_MAX_AGE_S)
            if (self._pending
                    and time.time() - self._last_flush > FLUSH_MAX_AGE_S):
                self._schedule_flush()

    def _flush_sync(self, batch: list[str]) -> None:
        if self._file is None:
            return
        data = "".join(batch)
        try:
            self._file.write(data)
        except (OSError, ValueError):
            # counted at the raise site so every flush path (batch task,
            # timer, sync fallback, close) is covered; ValueError is the
            # closed-file race. The batch is lost from the FILE copy only
            # — the ring already holds the rows
            _flush_failures.inc()
            raise
        self._file_bytes += len(data)
        if self._file_bytes > ROTATE_BYTES:
            self._file.close()
            self._open_file()

    # -- consumption ---------------------------------------------------

    def piece_row_count(self) -> int:
        return len(self._rows)

    def drain(self) -> list[dict]:
        """Hand all buffered piece+peer rows to the announcer and clear the
        ring (the file copy, if any, is untouched)."""
        rows, self._rows = self._rows, []
        peer_rows, self._peer_rows = self._peer_rows, []
        return rows + peer_rows

    def requeue(self, rows: list[dict]) -> None:
        """Return drained rows after a failed upload (oldest first; the
        ring bound still applies)."""
        piece = [r for r in rows if r.get("kind") == "piece"]
        # peer + flight + edge + decision
        peer = [r for r in rows if r.get("kind") != "piece"]
        over = (max(0, len(piece) + len(self._rows) - MAX_BUFFERED_ROWS)
                + max(0, len(peer) + len(self._peer_rows)
                      - MAX_BUFFERED_ROWS))
        if over:
            _dropped.inc(over)
        self._rows = (piece + self._rows)[-MAX_BUFFERED_ROWS:]
        self._peer_rows = (peer + self._peer_rows)[-MAX_BUFFERED_ROWS:]

    async def aclose(self) -> None:
        """Drain the in-flight flush chain, write the tail, close the file.
        The async variant is the correct one inside a running scheduler —
        ``close()`` alone can race a background ``to_thread`` write against
        the file close (rows lost or write-to-closed-file)."""
        if self._timer_task is not None:
            self._timer_task.cancel()
            self._timer_task = None
        task = self._flush_task
        if task is not None and not task.done():
            try:
                await task
            except Exception:                   # noqa: BLE001
                log.warning("final record flush failed", exc_info=True)
        self._flush_task = None
        self.close()

    def close(self) -> None:
        if self._timer_task is not None:
            self._timer_task.cancel()
            self._timer_task = None
        if self._pending:
            try:
                self._flush_sync(self._pending)
            except (OSError, ValueError):
                # counted ONCE at the raise site in _flush_sync; the tail
                # batch is lost from the file copy only. Swallowed here
                # because close() runs inside the scheduler's shutdown
                # sequence — a disk that died (or a file something closed
                # first) must not abort the rest of teardown behind us
                # (statestore save, handoff export, manager close)
                log.warning("tail record flush failed at close",
                            exc_info=True)
            self._pending = []
        if self._file is not None:
            self._file.close()
            self._file = None


# drift guard: schema changes must touch all parties (not an assert — that
# would be silently stripped under `python -O`)
if FEATURE_DIM != 7:
    raise RuntimeError(f"records schema expects FEATURE_DIM=7, trainer "
                       f"declares {FEATURE_DIM}; update on_piece/features.py "
                       f"together")
