"""RTT graph between hosts, fed by daemon probe reports.

Role parity: reference ``scheduler/networktopology/`` — per-(src,dst) probe
queues with sliding EWMA avgRTT (α=0.1), neighbour queries for the ``nt``
evaluator, and snapshot rows for the trainer dataset. The reference keeps
this in Redis for cross-scheduler sharing; here it is the scheduler's own
memory (single control-plane store per SURVEY §2.8 note).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

_EWMA_ALPHA = 0.1


@dataclass
class ProbeStat:
    avg_rtt_us: float
    count: int
    updated_at: float


IMPUTE_TTL_S = 60.0


class TopologyStore:
    def __init__(self, *, probe_targets: int = 5):
        self.probe_targets = probe_targets
        self._stats: dict[tuple[str, str], ProbeStat] = {}
        # GNN-imputed RTTs for unprobed pairs (announcer binds the model;
        # reference intent: networktopology.go:334 Neighbours)
        self._imputer = None
        self._imputed: dict[tuple[str, str], tuple[float, float]] = {}

    def record(self, src: str, dst: str, rtt_us: int) -> None:
        key = (src, dst)
        st = self._stats.get(key)
        now = time.time()
        if st is None:
            self._stats[key] = ProbeStat(float(rtt_us), 1, now)
        else:
            st.avg_rtt_us += _EWMA_ALPHA * (rtt_us - st.avg_rtt_us)
            st.count += 1
            st.updated_at = now

    def fail(self, src: str, dst: str) -> None:
        self._stats.pop((src, dst), None)

    def bind_imputer(self, impute) -> None:
        """Attach a ``topology_gnn`` imputer (trainer/serving
        make_gnn_impute); clears stale imputations from any prior model."""
        self._imputer = impute
        self._imputed.clear()

    def avg_rtt_us(self, src: str, dst: str) -> float | None:
        """Measured RTT when probed; GNN-imputed otherwise (the ``nt``/
        ``ml`` evaluators then score unprobed pairs instead of treating
        them as unknowable). None when neither is available."""
        st = self._stats.get((src, dst)) or self._stats.get((dst, src))
        if st is not None:
            return st.avg_rtt_us
        return self._impute(src, dst)

    def _impute(self, src: str, dst: str) -> float | None:
        """Runs on the evaluator hot path: one cache miss imputes ALL
        currently-unprobed pairs among seen hosts in a single forward
        (the imputer's batch API) instead of one graph build per pair."""
        if self._imputer is None or src == dst:
            return None
        now = time.time()
        hit = self._imputed.get((src, dst)) or self._imputed.get((dst, src))
        if hit is not None and now - hit[1] < IMPUTE_TTL_S:
            return hit[0] if hit[0] > 0 else None
        rows = self.snapshot_rows()
        hosts = sorted({h for (s, d) in self._stats for h in (s, d)}
                       | {src, dst})
        pairs = [(a, b) for i, a in enumerate(hosts) for b in hosts[i + 1:]
                 if (a, b) not in self._stats and (b, a) not in self._stats]
        out = self._imputer(rows, pairs)
        self._imputed = {p: (out.get(p, -1.0), now) for p in pairs}
        got = (self._imputed.get((src, dst))
               or self._imputed.get((dst, src)) or (-1.0, now))
        return got[0] if got[0] > 0 else None

    def probed_count(self, src: str) -> int:
        return sum(1 for (s, _d) in self._stats if s == src)

    def pick_targets(self, src: str, all_hosts: list[str]) -> list[str]:
        """Least-probed-first target selection for a prober."""
        others = [h for h in all_hosts if h != src]
        others.sort(key=lambda h: (self._stats.get((src, h)) is not None,
                                   (self._stats.get((src, h)) or
                                    ProbeStat(0, 0, 0)).updated_at))
        return others[:self.probe_targets]

    def snapshot_rows(self) -> list[dict]:
        """Feature rows for the trainer dataset."""
        return [{"src": s, "dst": d, "avg_rtt_us": st.avg_rtt_us,
                 "count": st.count, "updated_at": st.updated_at}
                for (s, d), st in self._stats.items()]
