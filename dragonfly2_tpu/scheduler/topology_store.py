"""RTT graph between hosts, fed by daemon probe reports.

Role parity: reference ``scheduler/networktopology/`` — per-(src,dst) probe
queues with sliding EWMA avgRTT (α=0.1), neighbour queries for the ``nt``
evaluator, and snapshot rows for the trainer dataset. The reference keeps
this in Redis for cross-scheduler sharing; here it is the scheduler's own
memory (single control-plane store per SURVEY §2.8 note).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

_EWMA_ALPHA = 0.1


@dataclass
class ProbeStat:
    avg_rtt_us: float
    count: int
    updated_at: float


class TopologyStore:
    def __init__(self, *, probe_targets: int = 5):
        self.probe_targets = probe_targets
        self._stats: dict[tuple[str, str], ProbeStat] = {}

    def record(self, src: str, dst: str, rtt_us: int) -> None:
        key = (src, dst)
        st = self._stats.get(key)
        now = time.time()
        if st is None:
            self._stats[key] = ProbeStat(float(rtt_us), 1, now)
        else:
            st.avg_rtt_us += _EWMA_ALPHA * (rtt_us - st.avg_rtt_us)
            st.count += 1
            st.updated_at = now

    def fail(self, src: str, dst: str) -> None:
        self._stats.pop((src, dst), None)

    def avg_rtt_us(self, src: str, dst: str) -> float | None:
        st = self._stats.get((src, dst)) or self._stats.get((dst, src))
        return st.avg_rtt_us if st else None

    def probed_count(self, src: str) -> int:
        return sum(1 for (s, _d) in self._stats if s == src)

    def pick_targets(self, src: str, all_hosts: list[str]) -> list[str]:
        """Least-probed-first target selection for a prober."""
        others = [h for h in all_hosts if h != src]
        others.sort(key=lambda h: (self._stats.get((src, h)) is not None,
                                   (self._stats.get((src, h)) or
                                    ProbeStat(0, 0, 0)).updated_at))
        return others[:self.probe_targets]

    def snapshot_rows(self) -> list[dict]:
        """Feature rows for the trainer dataset."""
        return [{"src": s, "dst": d, "avg_rtt_us": st.avg_rtt_us,
                 "count": st.count, "updated_at": st.updated_at}
                for (s, d), st in self._stats.items()]
