"""Durable scheduler state: the crash-survivable snapshot journal.

Role parity: none in the reference — Dragonfly2's scheduler keeps every
ruling input in process memory and leans on Redis for nothing but job
queues; a crashed scheduler restarts with amnesia and the cluster pays
for it in re-elections, re-offered poisoners, and an origin stampede.
Here the slow-moving, expensive-to-relearn control state — the
quarantine ladder (minutes of cross-reporter evidence), shard-affinity
memos (whose loss scatters ≥90 %-sticky assignments), federation seed
elections (whose loss re-elects per pod), and the tenant quota table —
is journaled to ONE versioned JSON blob with the ``TaskMetadata.save``
crash-safety idiom (PR 10): write ``.tmp``, flush, fsync, atomic
rename, fsync the directory. A reader sees the old complete snapshot or
the new complete snapshot, never a torn one.

Deliberately NOT covered: per-peer download FSMs, piece maps, and host
liveness — the announce/register plane rebuilds those within one
announce interval (daemons re-announce held content when they see the
scheduler's epoch change), and persisting them would turn a KB-scale
snapshot into a GB-scale one that is stale the moment it lands.

Cadence is periodic + event-driven: components mark the store dirty on
quarantine/affinity/election transitions (their ledger sinks are
wrapped), and the ticker persists when dirty or when ``interval_s`` has
elapsed. The persist path carries the ``sched.snapshot.io`` faultgate
site (torn / ENOSPC / wedged disk) and swallows EVERY failure into a
counter — a snapshot that cannot land must never block or perturb a
ruling; the next tick retries.

Load refuses wholesale (the PR 13 PEX schema-refusal guard): a blob
that is not a dict, carries the wrong ``v``, or fails JSON parse is
counted and ignored — never half-applied. Restore hands each component
its own sub-blob plus the wall-clock downtime gap, so evidence decay
keeps running across the outage.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable

from ..common import faultgate
from ..common.metrics import REGISTRY

log = logging.getLogger("df.sched.statestore")

SCHEMA_VERSION = 1
STATE_FILE = "scheduler_state.json"

_snapshots = REGISTRY.counter(
    "df_sched_snapshot_total",
    "scheduler state-snapshot persist attempts, by result", ("result",))
_snapshot_bytes = REGISTRY.gauge(
    "df_sched_snapshot_bytes",
    "size of the last successfully persisted scheduler state snapshot")
_rejected = REGISTRY.counter(
    "df_sched_snapshot_rejected_total",
    "scheduler state snapshots refused wholesale at load, by reason",
    ("reason",))
_recovered = REGISTRY.counter(
    "df_sched_recovery_restored_total",
    "control-plane entries restored from the snapshot at recovery, "
    "by component", ("component",))
_recovery_gap = REGISTRY.gauge(
    "df_sched_recovery_gap_seconds",
    "wall-clock downtime between the recovered snapshot's export and "
    "the restore that loaded it")


class SchedulerStateStore:
    """One snapshot file, many registered components.

    Each component registers an ``export`` (returns a JSON-safe dict)
    and a ``restore`` (takes that dict back, returns entries restored).
    ``wall`` is injectable wall-clock (snapshot age / downtime gap);
    ``clock`` is injectable monotonic (cadence) — dfbench drives both
    virtually so the recovery digest replays byte-identically.
    """

    def __init__(self, directory: str, *, interval_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.dir = directory
        self.path = os.path.join(directory, STATE_FILE)
        self.interval_s = interval_s
        self.clock = clock
        self.wall = wall
        self._exports: dict[str, Callable[[], dict]] = {}
        self._restores: dict[str, Callable[..., int]] = {}
        self._dirty = False
        self._last_save = clock()
        # recovered-vs-rebuilt provenance for /debug/ctrl: what the last
        # restore() brought back, per component, plus the downtime gap
        self.provenance: dict[str, Any] = {"recovered": False}

    def register(self, name: str, export: Callable[[], dict],
                 restore: Callable[..., int]) -> None:
        self._exports[name] = export
        self._restores[name] = restore

    # -- event-driven cadence -------------------------------------------

    def mark_dirty(self) -> None:
        """A covered component transitioned (quarantine ruling, shard
        re-assignment, seed (re)election, quota refresh): persist on the
        next tick instead of waiting out the periodic interval."""
        self._dirty = True

    def wrap_sink(self, sink: Callable[[dict], None] | None,
                  ) -> Callable[[dict], None]:
        """Interpose dirty-marking on a component's decision sink — the
        transitions that matter already flow through the ledger hook, so
        the event-driven cadence costs one extra attribute store per
        ruling, not a new wiring surface."""
        def _wrapped(row: dict) -> None:
            self._dirty = True
            if sink is not None:
                sink(row)
        return _wrapped

    def maybe_save(self) -> bool:
        """Ticker body: persist when dirty or when the periodic interval
        elapsed. Never raises."""
        now = self.clock()
        if not self._dirty and now - self._last_save < self.interval_s:
            return False
        return self.save(reason="dirty" if self._dirty else "periodic")

    # -- persist ---------------------------------------------------------

    def save(self, *, reason: str = "explicit") -> bool:
        """Serialize every registered component and land the blob with
        the tmp+fsync+rename idiom. Returns True on success; every
        failure (serialization, injected fault, real disk error) is
        counted and swallowed — rulings must never wait on, or die with,
        a snapshot."""
        try:
            body = {"v": SCHEMA_VERSION, "saved_at": self.wall(),
                    "components": {name: export()
                                   for name, export in self._exports.items()}}
            payload = json.dumps(body, sort_keys=True,
                                 separators=(",", ":")).encode()
            if faultgate.ARMED:
                faultgate.fire_sync("sched.snapshot.io", reason)
                payload = faultgate.corrupt("sched.snapshot.io", payload)
            self._write(payload)
        except Exception as exc:  # noqa: BLE001 - snapshot must not raise
            _snapshots.labels("error").inc()
            log.warning("state snapshot failed (%s): %s — next tick "
                        "retries", reason, exc)
            return False
        self._dirty = False
        self._last_save = self.clock()
        _snapshots.labels("ok").inc()
        _snapshot_bytes.set(len(payload))
        return True

    def _write(self, payload: bytes) -> None:
        os.makedirs(self.dir, exist_ok=True)
        tmp = self.path + ".tmp"
        f = open(tmp, "wb")
        try:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        finally:
            f.close()               # fd released even on a torn write
        os.replace(tmp, self.path)
        try:
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass                    # dir fsync is best-effort (metadata)

    # -- load / restore --------------------------------------------------

    def load(self) -> dict | None:
        """Read + verify the snapshot. Refusal is WHOLESALE (the PEX
        digest-codec rule): wrong version, non-dict, or unparseable JSON
        rejects the entire blob — a half-applied snapshot is worse than
        amnesia, because it looks like knowledge."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        except OSError as exc:
            _rejected.labels("io").inc()
            log.warning("state snapshot unreadable: %s", exc)
            return None
        try:
            body = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            _rejected.labels("parse").inc()
            log.warning("state snapshot refused: torn/corrupt JSON "
                        "(%d bytes)", len(raw))
            return None
        if not isinstance(body, dict) or body.get("v") != SCHEMA_VERSION:
            _rejected.labels("version").inc()
            log.warning("state snapshot refused: schema v%r != v%d",
                        body.get("v") if isinstance(body, dict) else None,
                        SCHEMA_VERSION)
            return None
        return body

    def restore(self) -> dict:
        """Load + hand each component its sub-blob. Components missing
        from the snapshot (older writer) or raising on restore are
        skipped independently — partial recovery of the components that
        DO verify beats discarding the lot. Returns (and retains, for
        /debug/ctrl) the provenance map."""
        body = self.load()
        if body is None:
            self.provenance = {"recovered": False}
            return self.provenance
        gap = max(self.wall() - float(body.get("saved_at", 0.0)), 0.0)
        _recovery_gap.set(round(gap, 3))
        components: dict[str, Any] = {}
        for name, restore in self._restores.items():
            sub = (body.get("components") or {}).get(name)
            if not isinstance(sub, dict):
                components[name] = {"restored": 0, "present": False}
                continue
            try:
                try:
                    n = restore(sub, gap_s=gap)
                except TypeError:
                    n = restore(sub)    # component ignores downtime gap
            except Exception as exc:  # noqa: BLE001 - per-component gate
                log.warning("restore of %s failed: %s — rebuilding live",
                            name, exc)
                components[name] = {"restored": 0, "present": True,
                                    "error": str(exc)}
                continue
            _recovered.labels(name).inc(max(int(n or 0), 0))
            components[name] = {"restored": int(n or 0), "present": True}
        self.provenance = {"recovered": True, "gap_s": round(gap, 3),
                           "components": components}
        log.info("control-plane state recovered (gap %.1fs): %s", gap,
                 {k: v.get("restored") for k, v in components.items()})
        return self.provenance
