"""Dedicated bounded executor for storage IO (and the off-loop hash work
that rides it).

Before this module, every storage call went through ``asyncio.to_thread``
— i.e. the event loop's SHARED default executor, the same pool that runs
proxy TLS handshakes (``ssl.create_default_context`` et al), tracer
flushes, and any library's incidental ``run_in_executor``. Under a
connect burst a 4-16 MiB piece write (with its verify hash) queued behind
multi-ms handshakes, and vice versa — the two workloads have nothing in
common except the pool they were defaulted into.

Storage IO now runs on a small dedicated pool:

* **bounded** — ``MAX_WORKERS`` threads; piece landings beyond that queue
  here (visible as ``df_storage_io_queue_depth``) instead of growing the
  default executor toward its 32-thread ceiling;
* **isolated** — nothing but storage (and conductor finalize/verify) work
  is submitted, so piece hashing can't sit behind a TLS handshake;
* **loop-independent** — plain ``concurrent.futures`` pool wrapped per
  call with ``run_in_executor``, so sequential ``asyncio.run`` loops (the
  test suite) share it safely.

Use ``run_io(fn, *args)`` from async code; the pool threads are daemonic
and live for the process (parity with the default executor's lifetime).
"""

from __future__ import annotations

import asyncio
import functools
import threading
from concurrent.futures import ThreadPoolExecutor

from ..common.metrics import REGISTRY

# Small on purpose: storage on one host is one disk (or tmpfs); more
# threads than ~4 only shuffle the same bandwidth while adding GIL churn.
MAX_WORKERS = 4

_depth = REGISTRY.gauge(
    "df_storage_io_queue_depth",
    "storage-executor jobs submitted and not yet finished")

_executor: ThreadPoolExecutor | None = None
_lock = threading.Lock()


def executor() -> ThreadPoolExecutor:
    global _executor
    if _executor is None:
        with _lock:
            if _executor is None:
                _executor = ThreadPoolExecutor(
                    max_workers=MAX_WORKERS,
                    thread_name_prefix="df-storage")
    return _executor


async def run_io(fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` on the storage pool; awaitable."""
    loop = asyncio.get_running_loop()
    _depth.inc()
    try:
        return await loop.run_in_executor(
            executor(), functools.partial(fn, *args, **kwargs))
    finally:
        _depth.dec()
