"""CAStore: the daemon's content-addressed index over task piece files.

Role parity: none in the reference — Dragonfly2 keys storage by task id,
so the same model pulled under two URLs is stored AND transferred twice,
and a restarted daemon re-pulls bytes it already holds. This module makes
content identity a first-class storage concept:

* **piece index** — every verified piece recorded in any task's metadata
  is indexed by its content digest (``crc32c:...`` per PieceMeta). A
  piece a new task needs that is already on disk under ANY task is
  **placed** (a local verified copy) instead of transferred — the
  conductor/engine consult ``find_piece`` before dispatching a pull, and
  a hit lands as a ``placed`` flight event plus ``df_store_dedupe_*``
  metrics, with zero wire bytes.
* **content identity** — a completed task is fingerprinted by its piece
  geometry + ordered piece-digest vector (works even when no whole-file
  digest was ever provided). When two completed tasks carry the same
  fingerprint, the later one's data file is replaced by a **hardlink**
  to the first (one inode: the bytes exist once on disk, served under
  both task ids). ``adopt`` short-circuits an entire download when the
  requested content digest is already held.
* **popularity** — serve/placement traffic feeds a half-life-decayed
  per-task score the storage GC orders eviction by (cold content leaves
  first; a piece's bytes are reclaimable only when the last task naming
  its digest is deleted — hardlink refcounts make partial reclaims safe).

Everything here is synchronous dict/file work guarded by one lock; the
byte-moving entry points (``place_piece``, ``on_task_complete``) are
called off-loop on the storage executor (io_executor.py), never the
event loop. The index is rebuilt from task metadata on boot
(``StorageManager.reload``) — task metadata stays the single crash-safe
source of truth, so there is no separate index file to tear.
"""

from __future__ import annotations

import hashlib
import logging
import math
import os
import threading
import time
from typing import Callable

from ..common import digest as digestlib
from ..common.metrics import REGISTRY

log = logging.getLogger("df.storage.cas")

_dedupe_hits = REGISTRY.counter(
    "df_store_dedupe_hits_total",
    "pieces or whole tasks served from the content-addressed store "
    "instead of the wire", ("kind",))
_dedupe_bytes = REGISTRY.counter(
    "df_store_dedupe_bytes_total",
    "bytes placed from already-held content instead of transferred")
_digests_gauge = REGISTRY.gauge(
    "df_store_digests",
    "distinct piece digests currently indexed by the content store")
_shared_gauge = REGISTRY.gauge(
    "df_store_shared_bytes",
    "bytes saved on disk by hardlink-shared task content (logical minus "
    "physical)")
_place_failures = REGISTRY.counter(
    "df_store_place_failures_total",
    "dedupe placements abandoned mid-flight (holder evicted or bytes "
    "failed re-verification)", ("reason",))


class _Pop:
    """Half-life-decayed popularity counter (serves + dedupe placements)."""

    __slots__ = ("score", "at")

    def __init__(self) -> None:
        self.score = 0.0
        self.at = time.monotonic()

    def bump(self, weight: float, halflife_s: float) -> None:
        now = time.monotonic()
        if halflife_s > 0:
            self.score *= 0.5 ** ((now - self.at) / halflife_s)
        self.score += weight
        self.at = now

    def value(self, now: float, halflife_s: float) -> float:
        if halflife_s <= 0:
            return self.score
        return self.score * (0.5 ** ((now - self.at) / halflife_s))


def content_key(md) -> tuple | None:
    """The content fingerprint of a COMPLETE task: geometry + the ordered
    piece-digest vector, hashed. Two tasks with the same key hold
    byte-identical content even when no whole-file digest was ever known
    (the digest vector covers every byte). None while incomplete or while
    any piece lacks a digest."""
    if not (md.done and md.success) or md.content_length < 0 \
            or not md.pieces:
        return None
    if md.total_piece_count >= 0 and len(md.pieces) < md.total_piece_count:
        return None
    vec = []
    for num in sorted(md.pieces):
        dg = md.pieces[num].digest
        if not dg:
            return None
        vec.append(dg)
    h = hashlib.sha256("\n".join(vec).encode()).hexdigest()
    return (md.content_length, md.piece_size, h)


class CAStore:
    """Digest → on-disk location index with popularity accounting.

    ``resolve`` maps a task id to its live TaskStorage (StorageManager
    wires its own lookup in) — the index never outlives the tasks it
    points into because ``drop_task`` runs inside every delete path.
    """

    def __init__(self, *, resolve: Callable | None = None,
                 popularity_halflife_s: float = 600.0):
        self.resolve = resolve or (lambda _tid: None)
        self.popularity_halflife_s = popularity_halflife_s
        # local bit-rot observer (daemon/verdicts.py self-quarantine): a
        # placement whose source bytes fail re-verification means THIS
        # daemon's disk lied — the callable gets the failing task id and
        # decides whether the daemon should stop advertising pod-wide
        self.on_rot: Callable[[str], None] | None = None
        self._lock = threading.Lock()
        # digest -> {task_id -> (start, size)}
        self._locs: dict[str, dict[str, tuple[int, int]]] = {}
        self._task_digests: dict[str, set[str]] = {}
        # content fingerprint -> live completed holders (first = canonical;
        # a LIST so evicting the canonical alias promotes the next holder
        # instead of forgetting that the content is still on disk)
        self._content: dict[tuple, list[str]] = {}
        # whole-content digest ("sha256:...") -> live completed holders
        self._content_digest: dict[str, list[str]] = {}
        self._pop: dict[str, _Pop] = {}

    # -- indexing ------------------------------------------------------

    def add_piece(self, task_id: str, num: int, start: int, size: int,
                  digest: str) -> None:
        if not digest:
            return
        with self._lock:
            self._locs.setdefault(digest, {})[task_id] = (start, size)
            self._task_digests.setdefault(task_id, set()).add(digest)
            _digests_gauge.set(len(self._locs))

    def add_task(self, ts) -> None:
        """Index every recorded piece of a (reloaded or completed) task."""
        md = ts.md
        for num, p in md.pieces.items():
            self.add_piece(md.task_id, num, p.start, p.size, p.digest)
        if md.done and md.success:
            key = content_key(md)
            with self._lock:
                if key is not None:
                    holders = self._content.setdefault(key, [])
                    if md.task_id not in holders:
                        holders.append(md.task_id)
                if md.digest:
                    holders = self._content_digest.setdefault(md.digest, [])
                    if md.task_id not in holders:
                        holders.append(md.task_id)

    def drop_task(self, task_id: str) -> None:
        with self._lock:
            for dg in self._task_digests.pop(task_id, ()):
                holders = self._locs.get(dg)
                if holders is not None:
                    holders.pop(task_id, None)
                    if not holders:
                        del self._locs[dg]
            for index in (self._content, self._content_digest):
                for key in [k for k, ids in index.items()
                            if task_id in ids]:
                    index[key] = [t for t in index[key] if t != task_id]
                    if not index[key]:
                        del index[key]
            self._pop.pop(task_id, None)
            _digests_gauge.set(len(self._locs))

    # -- lookups -------------------------------------------------------

    def find_piece(self, digest: str, size: int,
                   *, exclude_task: str = "") -> tuple[str, int] | None:
        """A live (task_id, start) holding ``digest`` at ``size`` bytes."""
        if not digest:
            return None
        with self._lock:
            holders = self._locs.get(digest)
            if not holders:
                return None
            for tid, (start, sz) in holders.items():
                if sz == size and tid != exclude_task:
                    return tid, start
        return None

    def find_content(self, content_digest: str) -> str | None:
        """A live completed task id holding the given whole-content
        digest (the first holder whose storage still resolves)."""
        with self._lock:
            ids = list(self._content_digest.get(content_digest) or ())
        for tid in ids:
            if self.resolve(tid) is not None:
                return tid
        return None

    # -- byte movement (storage executor only) -------------------------

    def place_piece(self, dst, num: int, offset: int, size: int,
                    digest: str) -> bool:
        """Copy an already-held piece into ``dst`` (a TaskStorage), with
        the bytes re-verified against ``digest`` during the hop — a local
        disk copy instead of a network transfer. BLOCKING: run on the
        storage executor. False = no live holder survived verification
        (the caller falls back to a normal pull)."""
        tried: set[str] = set()
        while True:
            loc = self.find_piece(digest, size, exclude_task=dst.md.task_id)
            if loc is None:
                return False
            src_tid, start = loc
            if src_tid in tried:
                return False
            tried.add(src_tid)
            src = self.resolve(src_tid)
            if src is None:
                self._drop_loc(digest, src_tid)
                continue
            try:
                data = src.read_range(start, size)
            except Exception:  # noqa: BLE001 - holder evicted mid-read
                self._drop_loc(digest, src_tid)
                _place_failures.labels("holder_gone").inc()
                continue
            if len(data) != size or not digestlib.verify(digest, data):
                # bit-rot (or a lying index entry): drop the loc so the
                # next placement never trusts it again
                self._drop_loc(digest, src_tid)
                _place_failures.labels("verify").inc()
                log.warning("cas placement of %s from %s failed "
                            "verification; dropped", digest, src_tid[:12])
                if self.on_rot is not None:
                    # first-hand evidence of our OWN rot: the verdict
                    # plane self-quarantines so the swarm stops hearing
                    # bytes this disk can no longer be trusted to serve
                    self.on_rot(src_tid)
                continue
            dst.write_piece(num, offset, data, digest, source="cas",
                            pre_verified=True)
            _dedupe_hits.labels("piece").inc()
            _dedupe_bytes.inc(size)
            self.record_serve(src_tid, size, weight=0.25)
            return True

    def note_hit(self, kind: str, nbytes: int) -> None:
        """Count a dedupe hit landed by a caller that moved (or skipped)
        the bytes itself — ``task`` = pieces already recorded under the
        requesting task (warm restart), ``content`` = whole-task adoption."""
        _dedupe_hits.labels(kind).inc()
        _dedupe_bytes.inc(nbytes)

    def _drop_loc(self, digest: str, task_id: str) -> None:
        with self._lock:
            holders = self._locs.get(digest)
            if holders is not None:
                holders.pop(task_id, None)
                if not holders:
                    del self._locs[digest]

    def on_task_complete(self, ts) -> bool:
        """Register a freshly completed task; when another completed task
        already carries the identical content fingerprint, replace this
        task's data file with a hardlink to the canonical copy so the
        bytes exist ONCE on disk. BLOCKING (rides mark_done's run_io hop).
        Returns True when the file became shared."""
        md = ts.md
        key = content_key(md)
        canonical_id = None
        if key is not None:
            with self._lock:
                holders = [t for t in self._content.get(key, ())
                           if t != md.task_id]
            canonical_id = next(
                (t for t in holders if self.resolve(t) is not None), None)
        self.add_task(ts)
        if canonical_id is None or canonical_id == md.task_id:
            return False
        src = self.resolve(canonical_id)
        if src is None:
            return False
        already = src.inode() is not None and src.inode() == ts.inode()
        try:
            if self.link_shared(src, ts):
                if not already:
                    # only a NEW coalescing counts: mark_done re-runs on
                    # adopted tasks and must not re-count the same link
                    _dedupe_hits.labels("content").inc()
                return True
        except OSError as exc:
            log.debug("content dedupe link failed (%s); keeping the copy",
                      exc)
        return False

    @staticmethod
    def link_shared(src, dst) -> bool:
        """Atomically swap ``dst``'s data file for a hardlink to ``src``'s.
        Both tasks are complete and immutable; readers mid-flight keep
        their old fd (same bytes), new opens see the shared inode."""
        src_path, dst_path = src.data_path(), dst.data_path()
        st_src, st_dst = os.stat(src_path), os.stat(dst_path)
        if st_src.st_dev != st_dst.st_dev:
            return False               # hardlinks need one filesystem
        if st_src.st_ino == st_dst.st_ino:
            return True                # already shared
        tmp = dst_path + ".cas"
        try:
            os.link(src_path, tmp)
            os.replace(tmp, dst_path)
        finally:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
        dst.close()                    # next lease opens the shared inode
        return True

    # -- popularity ----------------------------------------------------

    def record_serve(self, task_id: str, nbytes: int,
                     *, weight: float = 1.0) -> None:
        """Feed the eviction score: one serve (or placement read) of this
        task. Byte-weighted so a task serving whole models outranks one
        serving crumbs; decayed so yesterday's hot model can leave."""
        with self._lock:
            pop = self._pop.get(task_id)
            if pop is None:
                pop = self._pop[task_id] = _Pop()
            pop.bump(weight * (1.0 + math.log2(1 + nbytes / (1 << 20))),
                     self.popularity_halflife_s)

    def popularity(self, task_id: str, *, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            pop = self._pop.get(task_id)
            if pop is None:
                return 0.0
            return pop.value(now, self.popularity_halflife_s)

    # -- accounting ----------------------------------------------------

    def update_shared_gauge(self, logical: int, physical: int) -> None:
        _shared_gauge.set(max(logical - physical, 0))

    def stats(self) -> dict:
        with self._lock:
            return {
                "digests": len(self._locs),
                "piece_refs": sum(len(h) for h in self._locs.values()),
                "contents": len(self._content),
                "content_digests": len(self._content_digest),
                "popular_tasks": len(self._pop),
            }
