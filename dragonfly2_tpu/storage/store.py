"""TaskStorage: the piece-addressed store for one task.

Role parity: reference ``client/daemon/storage/local_storage.go`` (file-per-
task driver) and ``local_storage_subtask.go`` (ranged sub-tasks share the
parent's file). Pieces are written at their offsets with per-piece digest
verification; reads serve other peers (upload server) and the final sink.

Piece hashing rides the native C++ crc32c path when the library is built
(see native.py); file IO is buffered Python on a sparse file.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time

from ..common import digest as digestlib
from ..common.errors import Code, DFError
from . import native
from .metadata import DATA_FILE, TaskMetadata, PieceMeta

log = logging.getLogger("df.storage.task")


class TaskStorage:
    """One task's on-disk state. Thread-safe for concurrent piece writes."""

    def __init__(self, task_dir: str, metadata: TaskMetadata):
        self.dir = task_dir
        self.md = metadata
        self._lock = threading.Lock()
        self._data_path = os.path.join(task_dir, DATA_FILE)
        os.makedirs(task_dir, exist_ok=True)
        if not os.path.exists(self._data_path):
            with open(self._data_path, "wb"):
                pass

    # -- writes --------------------------------------------------------

    def write_piece(self, num: int, offset: int, data: bytes | memoryview,
                    piece_digest: str = "", *, cost_ms: int = 0,
                    source: str = "", pre_verified: bool = False) -> PieceMeta:
        """Verify + persist one piece. Idempotent per piece number.

        ``pre_verified`` skips the redundant re-hash when the transport
        already checked the bytes against ``piece_digest`` (the P2P
        downloader does) — hashing each piece twice shows up directly in
        end-to-end GB/s.

        Hot path: when the piece digest is crc32c (the default), the
        native library pwrite()s the piece while folding the bytes into
        the crc in the SAME pass (``native.piece_write``) — one memory
        traversal for verify+persist instead of two. A fused-path
        mismatch is detected after the bytes hit the file, which is safe:
        the piece is never recorded in ``md.pieces``, so the region stays
        "absent" (never served, re-written by the retry)."""
        with self._lock:
            existing = self.md.pieces.get(num)
            if existing is not None:
                return existing
        algo = want = ""
        if piece_digest:
            algo, want = digestlib.parse(piece_digest)
        crc_capable = not piece_digest or algo == "crc32c"
        fused_crc = None
        if crc_capable:
            try:
                fused_crc = native.piece_write(self._data_path, offset, data)
            except OSError as exc:
                raise DFError(Code.CLIENT_STORAGE_ERROR,
                              f"piece {num} write failed: {exc}") from None
        if fused_crc is not None:
            if not piece_digest:
                piece_digest = f"crc32c:{fused_crc}"
            elif fused_crc != want:
                # free double-check even for pre_verified pieces (the crc
                # came out of the write pass anyway)
                raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                              f"piece {num} digest mismatch")
        else:
            if piece_digest:
                if not pre_verified and not digestlib.verify(piece_digest,
                                                             data):
                    raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                                  f"piece {num} digest mismatch")
            else:
                piece_digest = digestlib.for_bytes(
                    digestlib.preferred_piece_algo(), data)
            with open(self._data_path, "r+b") as f:
                f.seek(offset)
                f.write(data)
        meta = PieceMeta(num=num, start=offset, size=len(data),
                         digest=piece_digest, cost_ms=cost_ms, source=source)
        with self._lock:
            self.md.pieces[num] = meta
            self.md.access_time = time.time()
        return meta

    def mark_done(self, *, success: bool, content_length: int | None = None,
                  total_piece_count: int | None = None, digest: str = "") -> None:
        with self._lock:
            if content_length is not None:
                self.md.content_length = content_length
            if total_piece_count is not None:
                self.md.total_piece_count = total_piece_count
            if digest:
                self.md.digest = digest
            self.md.done = True
            self.md.success = success
            self.md.save(self.dir)

    def persist(self) -> None:
        with self._lock:
            self.md.save(self.dir)

    # -- reads ---------------------------------------------------------

    def read_piece(self, num: int) -> bytes:
        meta = self.md.pieces.get(num)
        if meta is None:
            raise DFError(Code.CLIENT_PIECE_NOT_FOUND,
                          f"piece {num} not in task {self.md.task_id[:12]}")
        data = native.piece_read(self._data_path, meta.start, meta.size)
        if data is None:   # no native lib: plain Python file IO
            with open(self._data_path, "rb") as f:
                f.seek(meta.start)
                data = f.read(meta.size)
        if len(data) != meta.size:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"short read piece {num}: {len(data)}/{meta.size}")
        self.md.access_time = time.time()
        return data

    def read_range(self, start: int, length: int) -> bytes:
        with open(self._data_path, "rb") as f:
            f.seek(start)
            return f.read(length)

    def has_range(self, start: int, length: int) -> bool:
        """True if stored pieces fully cover [start, start+length)."""
        end = start + length
        covered = start
        with self._lock:
            spans = sorted((p.start, p.start + p.size)
                           for p in self.md.pieces.values())
        for s, e in spans:
            if s > covered:
                return False
            if e > covered:
                covered = e
            if covered >= end:
                return True
        return covered >= end

    def piece_infos(self, start_num: int = 0, limit: int = 0) -> list[PieceMeta]:
        with self._lock:
            nums = sorted(n for n in self.md.pieces if n >= start_num)
        if limit > 0:
            nums = nums[:limit]
        return [self.md.pieces[n] for n in nums]

    def verify_content(self) -> bool:
        """Re-hash the whole file against the recorded content digest."""
        if not self.md.digest:
            return True
        algo, _ = digestlib.parse(self.md.digest)
        def chunks():
            with open(self._data_path, "rb") as f:
                while True:
                    b = f.read(4 << 20)
                    if not b:
                        return
                    yield b
        return f"{algo}:{digestlib.hash_stream(algo, chunks())}" == self.md.digest

    # -- sinks ---------------------------------------------------------

    def store_to(self, output_path: str, *, range_start: int = 0,
                 range_length: int = -1) -> None:
        """Land the completed content at ``output_path``.

        Hardlink when possible (same filesystem, whole file), else copy —
        the reference's ``Store`` fast path.
        """
        os.makedirs(os.path.dirname(os.path.abspath(output_path)) or ".", exist_ok=True)
        whole = range_start == 0 and (
            range_length < 0 or range_length == self.md.content_length)
        if whole:
            try:
                if os.path.exists(output_path):
                    os.unlink(output_path)
                os.link(self._data_path, output_path)
                return
            except OSError:
                shutil.copyfile(self._data_path, output_path)
                return
        length = range_length if range_length >= 0 else self.md.content_length - range_start
        with open(self._data_path, "rb") as src, open(output_path, "wb") as dst:
            src.seek(range_start)
            remaining = length
            while remaining > 0:
                b = src.read(min(4 << 20, remaining))
                if not b:
                    break
                dst.write(b)
                remaining -= len(b)

    def data_path(self) -> str:
        return self._data_path

    def disk_usage(self) -> int:
        try:
            return os.path.getsize(self._data_path)
        except OSError:
            return 0

    def destroy(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)


class SubTaskStorage:
    """A ranged sub-task view over a parent TaskStorage.

    Role parity: ``local_storage_subtask.go`` — piece offsets are relative to
    the sub-range; bytes live in the parent's file at ``range_start + offset``.
    Completing the sub-range does not complete the parent, but the parent's
    piece table gains nothing — the sub-task keeps its own metadata.
    """

    def __init__(self, parent: TaskStorage, metadata: TaskMetadata):
        if metadata.range_length < 0:
            raise ValueError("subtask needs range_length")
        self.parent = parent
        self.md = metadata
        self._lock = threading.Lock()

    def write_piece(self, num: int, offset: int, data: bytes | memoryview,
                    piece_digest: str = "", *, cost_ms: int = 0,
                    source: str = "", pre_verified: bool = False) -> PieceMeta:
        if offset + len(data) > self.md.range_length:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"piece {num} spills past sub-range: "
                          f"{offset}+{len(data)} > {self.md.range_length}")
        if piece_digest and not pre_verified \
                and not digestlib.verify(piece_digest, data):
            raise DFError(Code.CLIENT_DIGEST_MISMATCH, f"piece {num} digest mismatch")
        if not piece_digest:
            piece_digest = digestlib.for_bytes(
                digestlib.preferred_piece_algo(), data)
        with self._lock:
            existing = self.md.pieces.get(num)
            if existing is not None:
                return existing
        abs_off = self.md.range_start + offset
        with open(self.parent.data_path(), "r+b") as f:
            f.seek(abs_off)
            f.write(data)
        meta = PieceMeta(num=num, start=offset, size=len(data),
                         digest=piece_digest, cost_ms=cost_ms, source=source)
        with self._lock:
            self.md.pieces[num] = meta
            self.md.access_time = time.time()
        self.parent.md.access_time = time.time()
        return meta

    def read_piece(self, num: int) -> bytes:
        meta = self.md.pieces.get(num)
        if meta is None:
            raise DFError(Code.CLIENT_PIECE_NOT_FOUND, f"piece {num} missing")
        return self.parent.read_range(self.md.range_start + meta.start, meta.size)

    def piece_infos(self, start_num: int = 0, limit: int = 0) -> list[PieceMeta]:
        with self._lock:
            nums = sorted(n for n in self.md.pieces if n >= start_num)
        if limit > 0:
            nums = nums[:limit]
        return [self.md.pieces[n] for n in nums]

    def mark_done(self, *, success: bool) -> None:
        with self._lock:
            self.md.done = True
            self.md.success = success

    def store_to(self, output_path: str) -> None:
        self.parent.store_to(output_path, range_start=self.md.range_start,
                             range_length=self.md.range_length)
