"""TaskStorage: the piece-addressed store for one task.

Role parity: reference ``client/daemon/storage/local_storage.go`` (file-per-
task driver) and ``local_storage_subtask.go`` (ranged sub-tasks share the
parent's file). Pieces are written at their offsets with per-piece digest
verification; reads serve other peers (upload server) and the final sink.

Piece hashing rides the native C++ crc32c path when the library is built
(see native.py); file IO is positioned pread/pwrite on a per-task CACHED
fd (opening the data file per piece was a measurable per-piece tax at
fan-out), issued from the dedicated storage executor (io_executor.py) —
never the event loop.

``write_span`` is the one-pass landing path: a whole contiguous
downloaded span costs ONE buffer traversal (pwrite + per-piece crc32c
fused in the native library, or one pwrite + off-loop hashing in the
Python fallback) and one write syscall chain instead of N of each.
"""

from __future__ import annotations

import bisect
import contextlib
import logging
import os
import shutil
import threading
import time

from ..common import digest as digestlib
from ..common.errors import Code, DFError
from . import native
from .metadata import DATA_FILE, TaskMetadata, PieceMeta

log = logging.getLogger("df.storage.task")


def _pread_all(fd: int, length: int, offset: int) -> bytes:
    """pread ``length`` bytes at ``offset``; short only at EOF."""
    out = os.pread(fd, length, offset)
    if len(out) == length or not out:
        return out
    parts = [out]
    got = len(out)
    while got < length:
        b = os.pread(fd, length - got, offset + got)
        if not b:
            break
        parts.append(b)
        got += len(b)
    return b"".join(parts)


def _pwrite_all(fd: int, data, offset: int) -> None:
    """pwrite the whole buffer (kernel may write short); EINTR-safe via
    os.pwrite's PEP 475 retry."""
    view = memoryview(data)
    while len(view):
        n = os.pwrite(fd, view, offset)
        view = view[n:]
        offset += n


class TaskStorage:
    """One task's on-disk state. Thread-safe for concurrent piece writes."""

    def __init__(self, task_dir: str, metadata: TaskMetadata,
                 castore=None):
        self.dir = task_dir
        self.md = metadata
        # content-addressed index (storage/castore.py): every verified
        # piece this task lands is registered by digest so other tasks
        # can place (not transfer) identical bytes; None = dedupe off
        self.castore = castore
        self._lock = threading.Lock()
        self._fd: int | None = None        # cached O_RDWR fd (lazy)
        self._fd_users = 0                 # leases out via _data_fd()
        self._fd_close_deferred = False    # close() arrived mid-lease
        # covered_prefix memo: (piece_count, merged [start, end) spans)
        self._cover_cache: tuple[int, list[list[int]]] | None = None
        self._data_path = os.path.join(task_dir, DATA_FILE)
        os.makedirs(task_dir, exist_ok=True)
        if not os.path.exists(self._data_path):
            with open(self._data_path, "wb"):
                pass

    @contextlib.contextmanager
    def _data_fd(self):
        """Refcounted lease on the task's cached data fd. Piece IO is
        pread/pwrite against this one descriptor — per-call open() was
        pure per-piece overhead and capped the storage executor at the
        dentry lock, not the disk.

        The refcount exists because close() (GC eviction, destroy) can
        race in-flight IO on the storage executor: closing the fd under a
        lease would at best EBADF the IO and at worst — once the fd
        number is reused by another task's open() — land the bytes in the
        WRONG task's file. close() during a lease is deferred to the last
        releaser; an acquire after destroy() re-opens the unlinked path
        and fails safe (FileNotFoundError), same as the per-call-open
        behavior this cache replaced. While a close is DEFERRED the
        cached fd is doomed — it may point at an already-unlinked inode
        (destroy closes then rmtrees), so new leases must not extend it:
        they open a private fd from the path, which fails safe post-
        destroy instead of silently writing bytes that vanish with the
        inode."""
        private = None
        with self._lock:
            if self._fd_close_deferred:
                private = True           # opened below, outside the lock
            else:
                if self._fd is None:
                    self._fd = os.open(self._data_path, os.O_RDWR)
                fd = self._fd
                self._fd_users += 1
        if private:
            fd = os.open(self._data_path, os.O_RDWR)
            try:
                yield fd
            finally:
                try:
                    os.close(fd)
                except OSError:
                    pass
            return
        try:
            yield fd
        finally:
            with self._lock:
                self._fd_users -= 1
                close_now = (self._fd_close_deferred
                             and self._fd_users == 0
                             and self._fd is not None)
                if close_now:
                    fd, self._fd = self._fd, None
                    self._fd_close_deferred = False
            if close_now:
                try:
                    os.close(fd)
                except OSError:
                    pass

    def close(self) -> None:
        """Drop the cached fd (destroy() and GC call this; reopening after
        close is transparent). With IO in flight the close is deferred to
        the last lease holder — never yanked out from under a pread/pwrite."""
        with self._lock:
            if self._fd is None:
                self._fd_close_deferred = False
                return
            if self._fd_users:
                self._fd_close_deferred = True
                return
            fd, self._fd = self._fd, None
        try:
            os.close(fd)
        except OSError:
            pass

    # -- writes --------------------------------------------------------

    def write_piece(self, num: int, offset: int, data: bytes | memoryview,
                    piece_digest: str = "", *, cost_ms: int = 0,
                    source: str = "", pre_verified: bool = False) -> PieceMeta:
        """Verify + persist one piece. Idempotent per piece number.

        ``pre_verified`` skips the redundant re-hash when the transport
        already checked the bytes against ``piece_digest`` (the P2P
        downloader does) — hashing each piece twice shows up directly in
        end-to-end GB/s.

        Hot path: when the piece digest is crc32c (the default), the
        native library pwrite()s the piece while folding the bytes into
        the crc in the SAME pass (``native.piece_write``) — one memory
        traversal for verify+persist instead of two. A fused-path
        mismatch is detected after the bytes hit the file, which is safe:
        the piece is never recorded in ``md.pieces``, so the region stays
        "absent" (never served, re-written by the retry)."""
        with self._lock:
            existing = self.md.pieces.get(num)
            if existing is not None:
                return existing
        algo = want = ""
        if piece_digest:
            algo, want = digestlib.parse(piece_digest)
        crc_capable = not piece_digest or algo == "crc32c"
        fused_crc = None
        if crc_capable:
            try:
                # fd-based fused span write (one piece = a span of one)
                # first — cached fd, no per-call open; fall back to the
                # path-based export for a stale .so
                with self._data_fd() as fd:
                    crcs = native.span_write(fd, offset, data,
                                             [len(data)])
                fused_crc = (crcs[0] if crcs is not None
                             else native.piece_write(self._data_path,
                                                     offset, data))
            except OSError as exc:
                raise DFError(Code.CLIENT_STORAGE_ERROR,
                              f"piece {num} write failed: {exc}") from None
        if fused_crc is not None:
            if not piece_digest:
                piece_digest = f"crc32c:{fused_crc}"
            elif fused_crc != want:
                # free double-check even for pre_verified pieces (the crc
                # came out of the write pass anyway)
                raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                              f"piece {num} digest mismatch")
        else:
            if piece_digest:
                if not pre_verified and not digestlib.verify(piece_digest,
                                                             data):
                    raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                                  f"piece {num} digest mismatch")
            else:
                piece_digest = digestlib.for_bytes(
                    digestlib.preferred_piece_algo(), data)
            try:
                with self._data_fd() as fd:
                    _pwrite_all(fd, data, offset)
            except OSError as exc:
                raise DFError(Code.CLIENT_STORAGE_ERROR,
                              f"piece {num} write failed: {exc}") from None
        meta = PieceMeta(num=num, start=offset, size=len(data),
                         digest=piece_digest, cost_ms=cost_ms, source=source)
        with self._lock:
            self.md.pieces[num] = meta
            self.md.access_time = time.time()
        if self.castore is not None:
            self.castore.add_piece(self.md.task_id, num, offset,
                                   len(data), piece_digest)
        return meta

    def write_span(self, pieces: list[tuple[int, int, int, str]], data,
                   *, base: int | None = None, cost_ms: int = 0,
                   source: str = "") -> tuple[list[PieceMeta], list[int], str]:
        """Land a whole contiguous downloaded span in ONE pass.

        ``pieces``: ``(num, offset, size, digest)`` in ascending offset
        order; ``data`` holds their bytes contiguously, ``data[i]`` being
        content offset ``base + i`` (``base`` defaults to the first
        piece's offset). Returns ``(landed_metas, corrupt_nums, path)``
        where ``path`` names the traversal used (``"native"`` fused
        pwrite+crc32c, ``"python"`` one pwrite + off-loop hashing).

        Per-piece verdicts: a digest-mismatched piece is returned in
        ``corrupt_nums`` — its bytes hit the file but are never recorded
        in ``md.pieces``, so the region stays "absent" (never served,
        re-written by the retry) and its groupmates land normally.
        Already-recorded pieces (endgame duplicates) are skipped without
        being re-written: overwriting a verified region with a racer's
        unverified bytes would let a corrupt duplicate trash good data.
        """
        if base is None:
            base = pieces[0][1]
        mv = memoryview(data)
        with self._lock:
            fresh = [p for p in pieces if p[0] not in self.md.pieces]
        # contiguous runs: normally one covering the whole span; a landed
        # duplicate mid-span splits it (each run is still one write+pass)
        runs: list[list[tuple[int, int, int, str]]] = []
        for p in fresh:
            if runs and runs[-1][-1][1] + runs[-1][-1][2] == p[1]:
                runs[-1].append(p)
            else:
                runs.append([p])
        metas: list[PieceMeta] = []
        corrupt: list[int] = []
        used_native = False
        for run in runs:
            run_off = run[0][1]
            sizes = [p[2] for p in run]
            run_len = sum(sizes)
            lo = run_off - base
            run_view = mv[lo:lo + run_len]
            digests = [digestlib.parse(p[3]) if p[3] else ("", "")
                       for p in run]
            crc_capable = all(a in ("", "crc32c") for a, _ in digests)
            crcs = None
            try:
                with self._data_fd() as fd:
                    if crc_capable:
                        crcs = native.span_write(fd, run_off,
                                                 run_view, sizes)
                    if crcs is None:
                        _pwrite_all(fd, run_view, run_off)
            except OSError as exc:
                raise DFError(Code.CLIENT_STORAGE_ERROR,
                              f"span write @{run_off}+{run_len} failed: "
                              f"{exc}") from None
            pos = 0
            for i, (num, off, size, dg) in enumerate(run):
                piece_view = run_view[pos:pos + size]
                pos += size
                if crcs is not None:
                    used_native = True
                    if dg and crcs[i] != digests[i][1]:
                        corrupt.append(num)
                        continue
                    if not dg:
                        dg = f"crc32c:{crcs[i]}"
                else:
                    # python fallback: bytes already written above in one
                    # pwrite; verify by hashing the slice here — we are on
                    # the storage executor, never the event loop
                    if dg:
                        if not digestlib.verify(dg, piece_view):
                            corrupt.append(num)
                            continue
                    else:
                        dg = digestlib.for_bytes(
                            digestlib.preferred_piece_algo(), piece_view)
                metas.append(PieceMeta(num=num, start=off, size=size,
                                       digest=dg, cost_ms=cost_ms,
                                       source=source))
        with self._lock:
            for meta in metas:
                self.md.pieces.setdefault(meta.num, meta)
            self.md.access_time = time.time()
        if self.castore is not None:
            for meta in metas:
                self.castore.add_piece(self.md.task_id, meta.num,
                                       meta.start, meta.size, meta.digest)
        return metas, corrupt, ("native" if used_native else "python")

    def adopt_from(self, src: "TaskStorage") -> None:
        """Adopt ``src``'s geometry + piece table — used when this task's
        data file has just become a hardlink of ``src``'s (content-
        identical, both immutable). Lives here so the lock discipline and
        the coverage-cache invalidation stay TaskStorage's own business:
        the piece table is replaced wholesale, and the covered_prefix
        memo (keyed on piece COUNT) would otherwise serve stale spans."""
        with self._lock:
            self.md.pieces = {
                num: PieceMeta(num=p.num, start=p.start, size=p.size,
                               digest=p.digest, source="cas")
                for num, p in src.md.pieces.items()}
            self.md.content_length = src.md.content_length
            self.md.total_piece_count = src.md.total_piece_count
            self.md.piece_size = src.md.piece_size
            self._cover_cache = None

    def mark_done(self, *, success: bool, content_length: int | None = None,
                  total_piece_count: int | None = None, digest: str = "") -> None:
        with self._lock:
            if content_length is not None:
                self.md.content_length = content_length
            if total_piece_count is not None:
                self.md.total_piece_count = total_piece_count
            if digest:
                self.md.digest = digest
            self.md.done = True
            self.md.success = success
            self.md.save(self.dir)
        if success and self.castore is not None:
            # content-identity dedupe: an identical completed task already
            # on disk absorbs this one's bytes via hardlink (castore.py);
            # runs here because mark_done already rides the storage
            # executor — never the event loop
            self.castore.on_task_complete(self)

    def persist(self) -> None:
        with self._lock:
            self.md.save(self.dir)

    # -- reads ---------------------------------------------------------

    def read_piece(self, num: int) -> bytes:
        meta = self.md.pieces.get(num)
        if meta is None:
            raise DFError(Code.CLIENT_PIECE_NOT_FOUND,
                          f"piece {num} not in task {self.md.task_id[:12]}")
        # one pread on the cached fd: no per-call open, no Python file
        # object, no intermediate copies
        try:
            with self._data_fd() as fd:
                data = _pread_all(fd, meta.size, meta.start)
        except OSError as exc:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"piece {num} read failed: {exc}") from None
        if len(data) != meta.size:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"short read piece {num}: {len(data)}/{meta.size}")
        self.md.access_time = time.time()
        return data

    def read_range(self, start: int, length: int) -> bytes:
        try:
            with self._data_fd() as fd:
                return _pread_all(fd, length, start)
        except OSError as exc:
            # evicted/destroyed task (or real IO failure): a typed error
            # the upload server maps to 404 instead of a bare 500
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"range read @{start}+{length} failed: "
                          f"{exc}") from None

    def covered_prefix(self, start: int, end: int) -> int:
        """How far recorded (verified) pieces contiguously cover from
        ``start``, clipped to ``end`` — the landed half of the relay
        plane's progress watermark (daemon/relay.py). Returns ``start``
        when the byte at ``start`` is not stored.

        Called per served chunk AND per progress wake by the streaming
        relay path, on the event loop — so the merged coverage spans are
        cached and rebuilt only when a piece lands (the piece table only
        ever grows, so the count is a valid cache key), making each call
        one bisect instead of an O(P log P) sort."""
        if end <= start:
            return start
        with self._lock:
            key = len(self.md.pieces)
            cache = self._cover_cache
            if cache is None or cache[0] != key:
                merged: list[list[int]] = []
                for s, e in sorted((p.start, p.start + p.size)
                                   for p in self.md.pieces.values()):
                    if merged and s <= merged[-1][1]:
                        if e > merged[-1][1]:
                            merged[-1][1] = e
                    else:
                        merged.append([s, e])
                cache = (key, merged)
                self._cover_cache = cache
        spans = cache[1]
        i = bisect.bisect_right(spans, [start, 1 << 62]) - 1
        if i < 0 or spans[i][1] <= start:
            return start
        return min(spans[i][1], end)

    def has_range(self, start: int, length: int) -> bool:
        """True if stored pieces fully cover [start, start+length)."""
        end = start + length
        covered = start
        with self._lock:
            spans = sorted((p.start, p.start + p.size)
                           for p in self.md.pieces.values())
        for s, e in spans:
            if s > covered:
                return False
            if e > covered:
                covered = e
            if covered >= end:
                return True
        return covered >= end

    def piece_infos(self, start_num: int = 0, limit: int = 0) -> list[PieceMeta]:
        with self._lock:
            nums = sorted(n for n in self.md.pieces if n >= start_num)
        if limit > 0:
            nums = nums[:limit]
        return [self.md.pieces[n] for n in nums]

    def verify_content(self) -> bool:
        """Re-hash the whole file against the recorded content digest."""
        if not self.md.digest:
            return True
        algo, _ = digestlib.parse(self.md.digest)
        def chunks():
            with open(self._data_path, "rb") as f:
                while True:
                    b = f.read(4 << 20)
                    if not b:
                        return
                    yield b
        return f"{algo}:{digestlib.hash_stream(algo, chunks())}" == self.md.digest

    # -- sinks ---------------------------------------------------------

    def store_to(self, output_path: str, *, range_start: int = 0,
                 range_length: int = -1) -> None:
        """Land the completed content at ``output_path``.

        Hardlink when possible (same filesystem, whole file), else copy —
        the reference's ``Store`` fast path.
        """
        os.makedirs(os.path.dirname(os.path.abspath(output_path)) or ".", exist_ok=True)
        whole = range_start == 0 and (
            range_length < 0 or range_length == self.md.content_length)
        if whole:
            try:
                if os.path.exists(output_path):
                    os.unlink(output_path)
                os.link(self._data_path, output_path)
                return
            except OSError:
                shutil.copyfile(self._data_path, output_path)
                return
        length = range_length if range_length >= 0 else self.md.content_length - range_start
        with open(self._data_path, "rb") as src, open(output_path, "wb") as dst:
            src.seek(range_start)
            remaining = length
            while remaining > 0:
                b = src.read(min(4 << 20, remaining))
                if not b:
                    break
                dst.write(b)
                remaining -= len(b)

    def data_path(self) -> str:
        return self._data_path

    def disk_usage(self) -> int:
        """LOGICAL bytes: what this task's content occupies from its own
        point of view. Digest-shared (hardlinked) data counts once per
        task here; StorageManager.usage() dedupes by inode for the
        physical number GC watermarks act on."""
        try:
            return os.path.getsize(self._data_path)
        except OSError:
            return 0

    def inode(self) -> tuple[int, int] | None:
        """(st_dev, st_ino) of the data file — the physical identity
        shared pieces coalesce on. None when the file is gone."""
        try:
            st = os.stat(self._data_path)
            return st.st_dev, st.st_ino
        except OSError:
            return None

    def nlink(self) -> int:
        try:
            return os.stat(self._data_path).st_nlink
        except OSError:
            return 0

    def destroy(self) -> None:
        self.close()
        shutil.rmtree(self.dir, ignore_errors=True)


class SubTaskStorage:
    """A ranged sub-task view over a parent TaskStorage.

    Role parity: ``local_storage_subtask.go`` — piece offsets are relative to
    the sub-range; bytes live in the parent's file at ``range_start + offset``.
    Completing the sub-range does not complete the parent, but the parent's
    piece table gains nothing — the sub-task keeps its own metadata.
    """

    def __init__(self, parent: TaskStorage, metadata: TaskMetadata):
        if metadata.range_length < 0:
            raise ValueError("subtask needs range_length")
        self.parent = parent
        self.md = metadata
        self._lock = threading.Lock()

    def write_piece(self, num: int, offset: int, data: bytes | memoryview,
                    piece_digest: str = "", *, cost_ms: int = 0,
                    source: str = "", pre_verified: bool = False) -> PieceMeta:
        if offset + len(data) > self.md.range_length:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"piece {num} spills past sub-range: "
                          f"{offset}+{len(data)} > {self.md.range_length}")
        if piece_digest and not pre_verified \
                and not digestlib.verify(piece_digest, data):
            raise DFError(Code.CLIENT_DIGEST_MISMATCH, f"piece {num} digest mismatch")
        if not piece_digest:
            piece_digest = digestlib.for_bytes(
                digestlib.preferred_piece_algo(), data)
        with self._lock:
            existing = self.md.pieces.get(num)
            if existing is not None:
                return existing
        abs_off = self.md.range_start + offset
        try:
            with self.parent._data_fd() as fd:
                _pwrite_all(fd, data, abs_off)
        except OSError as exc:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"piece {num} write failed: {exc}") from None
        meta = PieceMeta(num=num, start=offset, size=len(data),
                         digest=piece_digest, cost_ms=cost_ms, source=source)
        with self._lock:
            self.md.pieces[num] = meta
            self.md.access_time = time.time()
        self.parent.md.access_time = time.time()
        return meta

    def read_piece(self, num: int) -> bytes:
        meta = self.md.pieces.get(num)
        if meta is None:
            raise DFError(Code.CLIENT_PIECE_NOT_FOUND, f"piece {num} missing")
        return self.parent.read_range(self.md.range_start + meta.start, meta.size)

    def piece_infos(self, start_num: int = 0, limit: int = 0) -> list[PieceMeta]:
        with self._lock:
            nums = sorted(n for n in self.md.pieces if n >= start_num)
        if limit > 0:
            nums = nums[:limit]
        return [self.md.pieces[n] for n in nums]

    def mark_done(self, *, success: bool) -> None:
        with self._lock:
            self.md.done = True
            self.md.success = success

    def store_to(self, output_path: str) -> None:
        self.parent.store_to(output_path, range_start=self.md.range_start,
                             range_length=self.md.range_length)
