"""ctypes bindings to the C++ hot-path library (``native/``).

The native library accelerates what the reference's Rust client (`client-rs`)
and Go hot loops do natively: piece hashing (sha256/md5/crc32c) and aligned
file piece IO. Loading is best-effort — every caller has a pure-Python
fallback, so the framework runs (slower) without the .so. Build with
``make -C native`` (see native/Makefile).
"""

from __future__ import annotations

import ctypes
import os
import threading

_LIB_NAMES = ("libdfnative.so",)
_lib = None
_lib_lock = threading.Lock()
_load_attempted = False


def _candidate_paths():
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    for name in _LIB_NAMES:
        yield os.path.join(repo, "native", "build", name)
        yield os.path.join(repo, "native", name)
        yield name  # system path


def load():
    """Load the native library once; returns None if unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        for path in _candidate_paths():
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            try:
                _bind(lib)
            except AttributeError:
                continue
            _lib = lib
            break
    return _lib


def _bind(lib) -> None:
    # int df_hash(const char* algo, const uint8_t* data, size_t n, char* hex_out, size_t hex_cap)
    lib.df_hash.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
                            ctypes.c_char_p, ctypes.c_size_t]
    lib.df_hash.restype = ctypes.c_int
    # uint32 df_crc32c(const uint8_t* data, size_t n, uint32 seed) — chainable
    lib.df_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
    lib.df_crc32c.restype = ctypes.c_uint32
    # Newer exports bind OPTIONALLY: a stale .so built before they existed
    # must keep its working hash path (losing ALL native acceleration to an
    # AttributeError here would silently drop crc32c to the pure-Python
    # fallback fleet-wide).
    try:
        # int df_piece_write(path, offset, data, n, uint32* crc_out)
        lib.df_piece_write.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_char_p, ctypes.c_size_t,
                                       ctypes.POINTER(ctypes.c_uint32)]
        lib.df_piece_write.restype = ctypes.c_int
        # int64 df_piece_read(path, offset, uint8* out, n)
        lib.df_piece_read.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_char_p, ctypes.c_size_t]
        lib.df_piece_read.restype = ctypes.c_int64
        lib._df_has_piece_io = True
    except AttributeError:
        lib._df_has_piece_io = False
    try:
        # int df_span_write(fd, offset, data, uint64* piece_sizes,
        #                   n_pieces, uint32* crcs_out) — fused span landing
        # over a cached fd; bound separately so a pre-span .so keeps its
        # working piece IO
        lib.df_span_write.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                      ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.c_size_t,
                                      ctypes.POINTER(ctypes.c_uint32)]
        lib.df_span_write.restype = ctypes.c_int
        lib._df_has_span_io = True
    except AttributeError:
        lib._df_has_span_io = False


def available() -> bool:
    return load() is not None


def _buf_arg(data) -> tuple:
    """(c_char_p-compatible pointer, length) WITHOUT copying writable
    buffers: bytes pass through; bytearray / writable memoryview expose
    their storage via from_buffer. Only readonly views pay a copy. The
    download path hands 4-16 MiB bytearrays here — a per-piece bytes()
    conversion would re-copy every P2P byte."""
    if isinstance(data, bytes):
        return data, len(data)
    mv = data if isinstance(data, memoryview) else memoryview(data)
    if mv.readonly or not mv.contiguous:
        b = mv.tobytes()
        return b, len(b)
    n = mv.nbytes
    return ctypes.cast((ctypes.c_char * n).from_buffer(mv),
                       ctypes.c_char_p), n


def crc32c_update(data: bytes | bytearray | memoryview, seed: int) -> int | None:
    """Chainable crc32c via the native lib, or None to signal fallback."""
    lib = load()
    if lib is None:
        return None
    ptr, n = _buf_arg(data)
    return int(lib.df_crc32c(ptr, n, seed))


def hash_bytes(algo: str, data: bytes | bytearray | memoryview) -> str | None:
    """Hex digest via native lib, or None to signal fallback."""
    lib = load()
    if lib is None:
        return None
    ptr, n = _buf_arg(data)
    out = ctypes.create_string_buffer(129)
    rc = lib.df_hash(algo.encode(), ptr, n, out, len(out))
    if rc != 0:
        return None
    return out.value.decode()


def piece_write(path: str, offset: int, data: bytes | memoryview
                ) -> str | None:
    """Fused write+hash: pwrite ``data`` at ``offset`` while computing its
    crc32c in the same pass (one memory traversal instead of Python's
    hash-then-write two). Returns the crc32c hex, or None to signal
    fallback to the pure-Python path. Raises OSError on IO failure."""
    lib = load()
    if lib is None or not getattr(lib, "_df_has_piece_io", False):
        return None
    ptr, n = _buf_arg(data)
    crc = ctypes.c_uint32(0)
    rc = lib.df_piece_write(path.encode(), offset, ptr, n,
                            ctypes.byref(crc))
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return f"{crc.value:08x}"


def span_write(fd: int, offset: int, data: bytes | bytearray | memoryview,
               piece_sizes: list[int]) -> list[str] | None:
    """Fused span landing: ONE pwrite traversal of ``data`` at ``offset``
    through an already-open ``fd``, folding per-piece crc32c as it goes.
    Returns the per-piece crc32c hex list, or None to signal fallback to
    the pure-Python path (no .so, or a stale .so without the export).
    Raises OSError on IO failure."""
    lib = load()
    if lib is None or not getattr(lib, "_df_has_span_io", False):
        return None
    ptr, n = _buf_arg(data)
    if n != sum(piece_sizes):
        raise ValueError(f"span buffer {n} != sum(piece_sizes) "
                         f"{sum(piece_sizes)}")
    sizes = (ctypes.c_uint64 * len(piece_sizes))(*piece_sizes)
    crcs = (ctypes.c_uint32 * len(piece_sizes))()
    rc = lib.df_span_write(fd, offset, ptr, sizes, len(piece_sizes), crcs)
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc))
    return [f"{c:08x}" for c in crcs]


def piece_read(path: str, offset: int, length: int) -> bytes | None:
    """pread a piece straight into a fresh buffer via the native lib, or
    None to signal fallback. Raises OSError on IO failure; short reads
    past EOF return the available bytes.

    LEGACY: the store's hot read path moved to plain os.pread on the
    cached per-task fd (store._data_fd) — same zero-copy profile without
    a ctypes hop. Kept for external tooling against the path-based ABI
    (exercised by tests/test_storage.py)."""
    lib = load()
    if lib is None or not getattr(lib, "_df_has_piece_io", False):
        return None
    # one allocation, no zero-fill pass, no .raw copy: pread fills the
    # bytearray in place and full reads (the normal case) return it as-is
    buf = bytearray(length)
    got = lib.df_piece_read(path.encode(), offset,
                            (ctypes.c_char * length).from_buffer(buf),
                            length)
    if got < 0:
        raise OSError(-got, os.strerror(-got), path)
    return bytes(buf) if got == length else bytes(buf[:got])
