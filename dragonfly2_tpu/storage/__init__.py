"""Piece-addressed local storage engine + native (C++) hot path + HBM sink."""
