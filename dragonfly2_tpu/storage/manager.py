"""StorageManager: the daemon's registry of TaskStorages with reload + GC.

Role parity: reference ``client/daemon/storage/storage_manager.go`` —
``RegisterTask`` (:239), piece IO dispatch (:293-344),
``ReloadPersistentTask`` (:674), ``TryGC`` (:804) with reclaim marks driven
by TTL and disk high/low watermarks; persistent (dfcache) tasks are pinned.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass

from ..common.errors import Code, DFError
from ..idl.messages import TaskType
from .metadata import METADATA_FILE, TaskMetadata
from .store import SubTaskStorage, TaskStorage

log = logging.getLogger("df.storage.manager")


@dataclass
class StorageConfig:
    data_dir: str = ""
    task_ttl_s: float = 6 * 3600.0
    # GC starts above high watermark and stops below low watermark
    disk_gc_high_ratio: float = 0.90
    disk_gc_low_ratio: float = 0.80
    capacity_bytes: int = 0          # 0: use the filesystem's capacity
    gc_interval_s: float = 60.0

    def validate(self) -> None:
        if not (0 < self.disk_gc_low_ratio <= self.disk_gc_high_ratio <= 1):
            raise ValueError("bad GC watermarks")


class StorageManager:
    def __init__(self, cfg: StorageConfig):
        cfg.validate()
        self.cfg = cfg
        os.makedirs(cfg.data_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._tasks: dict[str, TaskStorage] = {}
        self._subtasks: dict[str, SubTaskStorage] = {}
        self.reload()

    # -- registration --------------------------------------------------

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.cfg.data_dir, task_id[:3], task_id)

    def register_task(self, md: TaskMetadata) -> TaskStorage:
        with self._lock:
            ts = self._tasks.get(md.task_id)
            if ts is not None:
                return ts
            ts = TaskStorage(self._task_dir(md.task_id), md)
            self._tasks[md.task_id] = ts
            return ts

    def register_subtask(self, md: TaskMetadata) -> SubTaskStorage:
        """Ranged sub-task sharing the parent's data file; the parent task is
        created (empty) if unknown so the range lands at its final offset."""
        if not md.parent_task_id:
            raise DFError(Code.INVALID_ARGUMENT, "subtask needs parent_task_id")
        with self._lock:
            st = self._subtasks.get(md.task_id)
            if st is not None:
                return st
        parent = self._tasks.get(md.parent_task_id)
        if parent is None:
            parent = self.register_task(TaskMetadata(
                task_id=md.parent_task_id, url=md.url, tag=md.tag))
        st = SubTaskStorage(parent, md)
        with self._lock:
            self._subtasks[md.task_id] = st
        return st

    def get(self, task_id: str) -> TaskStorage | SubTaskStorage | None:
        with self._lock:
            return self._tasks.get(task_id) or self._subtasks.get(task_id)

    def find_completed_task(self, task_id: str) -> TaskStorage | None:
        ts = self._tasks.get(task_id)
        if ts is not None and ts.md.done and ts.md.success:
            ts.md.access_time = time.time()
            return ts
        return None

    def find_partial_completed_task(self, parent_task_id: str,
                                    start: int, length: int) -> TaskStorage | None:
        """A completed whole-file task can serve any sub-range directly
        (reference ``FindPartialCompletedTask``)."""
        ts = self.find_completed_task(parent_task_id)
        if ts is None:
            return None
        if ts.md.content_length >= 0 and start + length <= ts.md.content_length:
            return ts
        return None

    def tasks(self) -> list[TaskStorage]:
        with self._lock:
            return list(self._tasks.values())

    def delete_task(self, task_id: str) -> bool:
        with self._lock:
            ts = self._tasks.pop(task_id, None)
            self._subtasks.pop(task_id, None)
        if ts is None:
            return False
        ts.destroy()
        return True

    # -- restart recovery ---------------------------------------------

    def reload(self) -> int:
        """Re-index completed tasks from disk; drop invalid/partial ones.

        Partial downloads are discarded (their piece table can't be trusted
        against a crashed writer) — same policy as the reference
        (``storage_manager.go:662 IsInvalid``).
        """
        n = 0
        root = self.cfg.data_dir
        for prefix in os.listdir(root) if os.path.isdir(root) else []:
            pdir = os.path.join(root, prefix)
            if not os.path.isdir(pdir):
                continue
            for tid in os.listdir(pdir):
                tdir = os.path.join(pdir, tid)
                mpath = os.path.join(tdir, METADATA_FILE)
                if not os.path.exists(mpath):
                    shutil.rmtree(tdir, ignore_errors=True)
                    continue
                try:
                    md = TaskMetadata.load(tdir)
                except (OSError, ValueError, KeyError, TypeError):
                    shutil.rmtree(tdir, ignore_errors=True)
                    continue
                if not (md.done and md.success):
                    shutil.rmtree(tdir, ignore_errors=True)
                    continue
                with self._lock:
                    self._tasks[md.task_id] = TaskStorage(tdir, md)
                n += 1
        if n:
            log.info("reloaded %d completed tasks", n)
        return n

    # -- GC ------------------------------------------------------------

    def _usage(self) -> tuple[int, int]:
        """(used_bytes_by_store, capacity_bytes)."""
        used = sum(ts.disk_usage() for ts in self.tasks())
        if self.cfg.capacity_bytes:
            return used, self.cfg.capacity_bytes
        try:
            stat = shutil.disk_usage(self.cfg.data_dir)
            return used, stat.total
        except OSError:
            return used, 0

    def try_gc(self) -> int:
        """TTL sweep + usage-driven eviction, oldest-access first.

        Not-done tasks are treated as active while their access_time is
        fresh (pieces still landing); once stale past the TTL they are
        abandoned downloads and reclaimed too. Sub-task views whose parent
        is gone (or stale) are dropped with them.
        """
        reclaimed = 0
        now = time.time()
        candidates: list[TaskStorage] = []
        for ts in self.tasks():
            if ts.md.task_type != TaskType.STANDARD:
                continue  # persistent cache entries are pinned
            stale = now - ts.md.access_time > self.cfg.task_ttl_s
            if not ts.md.done and not stale:
                continue  # active download
            if stale:
                if self.delete_task(ts.md.task_id):
                    reclaimed += 1
            else:
                candidates.append(ts)
        with self._lock:
            dead_subs = [tid for tid, st in self._subtasks.items()
                         if st.parent.md.task_id not in self._tasks
                         or now - st.md.access_time > self.cfg.task_ttl_s]
            for tid in dead_subs:
                del self._subtasks[tid]
        used, cap = self._usage()
        if cap and used / cap > self.cfg.disk_gc_high_ratio:
            target = int(cap * self.cfg.disk_gc_low_ratio)
            # eviction order: lowest download priority first (numeric
            # DESC — LEVEL6 before LEVEL0), then oldest access
            candidates.sort(key=lambda t: (-t.md.priority, t.md.access_time))
            for ts in candidates:
                if used <= target:
                    break
                sz = ts.disk_usage()
                if self.delete_task(ts.md.task_id):
                    used -= sz
                    reclaimed += 1
        return reclaimed
