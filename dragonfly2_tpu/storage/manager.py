"""StorageManager: the daemon's registry of TaskStorages with reload + GC.

Role parity: reference ``client/daemon/storage/storage_manager.go`` —
``RegisterTask`` (:239), piece IO dispatch (:293-344),
``ReloadPersistentTask`` (:674), ``TryGC`` (:804) — extended with the
content-addressed layer (castore.py):

* every task shares one daemon-wide ``CAStore``, so pieces land indexed
  by digest and identical completed content coalesces onto one inode;
* **warm restart**: ``reload()`` re-indexes EVERY task whose metadata
  loads — completed AND partial (their per-piece crc32c records make the
  pieces trustworthy after re-verification, unlike the reference, which
  discards partial downloads wholesale). ``verify_reloaded()`` re-hashes
  the recorded pieces off-loop (crc32c via the native path) and drops
  only what actually fails — a restarted daemon rejoins the swarm as a
  holder instead of a cold leecher;
* **popularity-aware GC**: eviction orders by priority, then the
  CAStore's decayed serve-popularity, then recency — and the capacity
  watermarks act on PHYSICAL bytes (inode-deduped), so digest-shared
  content is neither double-counted nor double-"reclaimed".
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass

from ..common import digest as digestlib
from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import TaskType
from .castore import CAStore
from .metadata import METADATA_FILE, TaskMetadata
from .store import SubTaskStorage, TaskStorage

log = logging.getLogger("df.storage.manager")

# QoS class multipliers on serve-popularity at capacity eviction
# (StorageManager.try_gc): scores the same observed serve rate 4x higher
# for critical content and 4x lower for bulk ("" = pre-QoS tasks score
# unweighted). Priority stays the primary key; the weight breaks
# popularity ties WITHIN a priority band.
CLASS_EVICT_WEIGHTS = {"critical": 4.0, "standard": 1.0, "bulk": 0.25}

_logical_gauge = REGISTRY.gauge(
    "df_storage_logical_bytes",
    "bytes the store's tasks occupy before digest-sharing (sum of "
    "per-task content)")
_physical_gauge = REGISTRY.gauge(
    "df_storage_physical_bytes",
    "bytes the store's tasks actually occupy on disk (hardlink-shared "
    "inodes counted once)")
_reload_pieces = REGISTRY.counter(
    "df_store_reload_pieces_total",
    "pieces re-indexed from disk at boot, by re-verification outcome",
    ("result",))


@dataclass
class StorageConfig:
    data_dir: str = ""
    task_ttl_s: float = 6 * 3600.0
    # GC starts above high watermark and stops below low watermark
    disk_gc_high_ratio: float = 0.90
    disk_gc_low_ratio: float = 0.80
    capacity_bytes: int = 0          # 0: use the filesystem's capacity
    gc_interval_s: float = 60.0
    # content-addressed dedupe (castore.py): cross-task piece placement +
    # completed-content hardlink coalescing
    dedupe_enabled: bool = True
    # crc-verify reloaded pieces before trusting them (verify_reloaded)
    reload_verify: bool = True
    # serve-popularity decay half-life feeding GC eviction order
    popularity_halflife_s: float = 600.0

    def validate(self) -> None:
        if not (0 < self.disk_gc_low_ratio <= self.disk_gc_high_ratio <= 1):
            raise ValueError("bad GC watermarks")


class StorageManager:
    def __init__(self, cfg: StorageConfig):
        cfg.validate()
        self.cfg = cfg
        os.makedirs(cfg.data_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._tasks: dict[str, TaskStorage] = {}
        self._subtasks: dict[str, SubTaskStorage] = {}
        self.castore = CAStore(
            resolve=self._tasks.get,
            popularity_halflife_s=cfg.popularity_halflife_s) \
            if cfg.dedupe_enabled else None
        self.reloaded_tasks = 0       # tasks re-indexed by the last reload
        self.last_gc_stats: dict = {}
        self.reload()

    # -- registration --------------------------------------------------

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.cfg.data_dir, task_id[:3], task_id)

    def register_task(self, md: TaskMetadata) -> TaskStorage:
        with self._lock:
            ts = self._tasks.get(md.task_id)
            if ts is not None:
                return ts
            ts = TaskStorage(self._task_dir(md.task_id), md,
                             castore=self.castore)
            self._tasks[md.task_id] = ts
            return ts

    def register_subtask(self, md: TaskMetadata) -> SubTaskStorage:
        """Ranged sub-task sharing the parent's data file; the parent task is
        created (empty) if unknown so the range lands at its final offset."""
        if not md.parent_task_id:
            raise DFError(Code.INVALID_ARGUMENT, "subtask needs parent_task_id")
        with self._lock:
            st = self._subtasks.get(md.task_id)
            if st is not None:
                return st
        parent = self._tasks.get(md.parent_task_id)
        if parent is None:
            parent = self.register_task(TaskMetadata(
                task_id=md.parent_task_id, url=md.url, tag=md.tag))
        st = SubTaskStorage(parent, md)
        with self._lock:
            self._subtasks[md.task_id] = st
        return st

    def get(self, task_id: str) -> TaskStorage | SubTaskStorage | None:
        with self._lock:
            return self._tasks.get(task_id) or self._subtasks.get(task_id)

    def find_completed_task(self, task_id: str) -> TaskStorage | None:
        ts = self._tasks.get(task_id)
        if ts is not None and ts.md.done and ts.md.success:
            ts.md.access_time = time.time()
            return ts
        return None

    def find_partial_completed_task(self, parent_task_id: str,
                                    start: int, length: int) -> TaskStorage | None:
        """A completed whole-file task can serve any sub-range directly
        (reference ``FindPartialCompletedTask``)."""
        ts = self.find_completed_task(parent_task_id)
        if ts is None:
            return None
        if ts.md.content_length >= 0 and start + length <= ts.md.content_length:
            return ts
        return None

    def adopt_content(self, md: TaskMetadata) -> TaskStorage | None:
        """Materialize a whole task from already-held identical content:
        when ``md.digest`` names content a completed task holds, the new
        task is built as a HARDLINK of the canonical data file plus a
        copy of its piece table — done before a single byte is pulled.
        BLOCKING (file ops): run on the storage executor. None = no hit.
        """
        if self.castore is None or not md.digest:
            return None
        src_tid = self.castore.find_content(md.digest)
        src = self._tasks.get(src_tid) if src_tid else None
        if src is None or not (src.md.done and src.md.success):
            return None
        if src.md.task_id == md.task_id:
            return src
        ts = self.register_task(md)
        if ts.md.done and ts.md.success:
            return ts                  # already materialized earlier
        try:
            if not CAStore.link_shared(src, ts):
                return None
        except OSError:
            return None
        ts.adopt_from(src)
        ts.mark_done(success=True,
                     content_length=src.md.content_length,
                     total_piece_count=src.md.total_piece_count)
        self.castore.record_serve(src.md.task_id, src.md.content_length,
                                  weight=0.5)
        return ts

    def tasks(self) -> list[TaskStorage]:
        with self._lock:
            return list(self._tasks.values())

    def delete_task(self, task_id: str) -> bool:
        with self._lock:
            ts = self._tasks.pop(task_id, None)
            self._subtasks.pop(task_id, None)
        if ts is None:
            return False
        if self.castore is not None:
            self.castore.drop_task(task_id)
        ts.destroy()
        return True

    # -- restart recovery ---------------------------------------------

    def reload(self) -> int:
        """Re-index tasks from disk: completed ones AND partials that
        recorded verified pieces — their per-piece digests make the bytes
        re-checkable, so a restarted daemon keeps its working set instead
        of re-pulling it (the reference's IsInvalid discard threw the
        whole fleet's warm state away on every rolling restart). Torn or
        digest-less metadata is still discarded; actual byte verification
        happens in ``verify_reloaded`` (off-loop).
        """
        n = 0
        root = self.cfg.data_dir
        for prefix in os.listdir(root) if os.path.isdir(root) else []:
            pdir = os.path.join(root, prefix)
            if not os.path.isdir(pdir):
                continue
            for tid in os.listdir(pdir):
                tdir = os.path.join(pdir, tid)
                mpath = os.path.join(tdir, METADATA_FILE)
                if not os.path.exists(mpath):
                    shutil.rmtree(tdir, ignore_errors=True)
                    continue
                try:
                    md = TaskMetadata.load(tdir)
                except (OSError, ValueError, KeyError, TypeError):
                    # torn metadata: with crash-safe persist this means
                    # real corruption, not a mid-write crash — discard
                    shutil.rmtree(tdir, ignore_errors=True)
                    continue
                complete = md.done and md.success
                # a partial is only as good as its piece records: keep it
                # when every recorded piece carries a digest to re-verify
                warm = (md.pieces
                        and all(p.digest for p in md.pieces.values()))
                if not complete and not warm:
                    shutil.rmtree(tdir, ignore_errors=True)
                    continue
                ts = TaskStorage(tdir, md, castore=self.castore)
                with self._lock:
                    self._tasks[md.task_id] = ts
                if self.castore is not None:
                    self.castore.add_task(ts)
                n += 1
        self.reloaded_tasks = n
        if n:
            log.info("reloaded %d tasks (completed + warm partials)", n)
        return n

    def _verify_task(self, ts: TaskStorage) -> tuple[int, int, bool, int]:
        """Re-hash one reloaded task's recorded pieces against their
        metadata digests (crc32c rides the native path). BLOCKING — one
        unit of storage-executor work. Returns (pieces_ok,
        pieces_dropped, task_dropped, pieces_rot); a task that loses
        pieces is demoted to partial (the next conductor re-pulls just
        the holes), one that loses everything is deleted.

        ``pieces_rot`` counts drops from tasks that were COMPLETE
        (done+success) when reloaded: those bytes once verified and
        were finalized, so failing now is disk bit-rot — the
        self-quarantine signal. Drops from PARTIAL tasks are the
        ordinary crash-torn-write shape (data files are not fsynced per
        write) and must NOT sideline an otherwise healthy daemon at
        every unclean restart."""
        md = ts.md
        was_complete = bool(md.done and md.success)
        bad: list[int] = []
        n_ok = 0
        for num, p in sorted(md.pieces.items()):
            ok = False
            if p.digest:
                try:
                    data = ts.read_range(p.start, p.size)
                    ok = (len(data) == p.size
                          and digestlib.verify(p.digest, data))
                except (DFError, OSError, ValueError):
                    ok = False
            if ok:
                n_ok += 1
                _reload_pieces.labels("ok").inc()
            else:
                bad.append(num)
                _reload_pieces.labels("dropped").inc()
        if not bad:
            return n_ok, 0, False, 0
        rot = len(bad) if was_complete else 0
        if len(bad) == len(md.pieces):
            self.delete_task(md.task_id)
            return n_ok, len(bad), True, rot
        with ts._lock:
            for num in bad:
                del md.pieces[num]
            # holes mean the task is no longer complete: demote so
            # find_completed_task stops offering it whole and the
            # next conductor re-pulls exactly the missing pieces
            md.done = md.success = False
            md.save(ts.dir)
        if self.castore is not None:
            self.castore.drop_task(md.task_id)
            self.castore.add_task(ts)
        return n_ok, len(bad), False, rot

    def verify_reloaded(self) -> dict:
        """Re-verification of reloaded pieces — a crashed writer's torn
        piece (the data file is not fsynced per write, unlike metadata)
        must never be served or counted as held. BLOCKING; boot runs the
        async form below, which fans the per-task work across the whole
        storage pool instead of serializing a cache-sized scan on one
        thread."""
        stats = {"tasks": 0, "pieces_ok": 0, "pieces_dropped": 0,
                 "tasks_dropped": 0, "pieces_rot": 0}
        if not self.cfg.reload_verify:
            return stats
        for ts in self.tasks():
            if not ts.md.pieces:
                continue
            stats["tasks"] += 1
            ok, dropped, gone, rot = self._verify_task(ts)
            stats["pieces_ok"] += ok
            stats["pieces_dropped"] += dropped
            stats["tasks_dropped"] += 1 if gone else 0
            stats["pieces_rot"] += rot
        if stats["pieces_dropped"] or stats["tasks_dropped"]:
            log.warning("reload verification dropped %d piece(s), "
                        "%d task(s)", stats["pieces_dropped"],
                        stats["tasks_dropped"])
        return stats

    async def verify_reloaded_async(self) -> dict:
        """Boot-time form: one storage-executor job PER TASK, gathered —
        the re-hash parallelizes across the pool's workers, so a large
        warm cache costs cache_bytes / (pool x crc32c_rate), not a
        single-threaded scan, before the daemon starts serving."""
        from .io_executor import run_io
        stats = {"tasks": 0, "pieces_ok": 0, "pieces_dropped": 0,
                 "tasks_dropped": 0, "pieces_rot": 0}
        if not self.cfg.reload_verify:
            return stats
        pending = [ts for ts in self.tasks() if ts.md.pieces]
        stats["tasks"] = len(pending)
        results = await asyncio.gather(
            *(run_io(self._verify_task, ts) for ts in pending))
        for ok, dropped, gone, rot in results:
            stats["pieces_ok"] += ok
            stats["pieces_dropped"] += dropped
            stats["tasks_dropped"] += 1 if gone else 0
            stats["pieces_rot"] += rot
        if stats["pieces_dropped"] or stats["tasks_dropped"]:
            log.warning("reload verification dropped %d piece(s), "
                        "%d task(s)", stats["pieces_dropped"],
                        stats["tasks_dropped"])
        return stats

    # -- GC ------------------------------------------------------------

    def usage(self) -> tuple[int, int]:
        """(logical_bytes, physical_bytes): per-task sum vs inode-deduped
        disk footprint — digest-shared content counts once in physical."""
        logical = 0
        physical = 0
        seen: set[tuple[int, int]] = set()
        for ts in self.tasks():
            sz = ts.disk_usage()
            logical += sz
            ino = ts.inode()
            if ino is None or ino not in seen:
                physical += sz
                if ino is not None:
                    seen.add(ino)
        _logical_gauge.set(logical)
        _physical_gauge.set(physical)
        if self.castore is not None:
            self.castore.update_shared_gauge(logical, physical)
        return logical, physical

    def _usage(self) -> tuple[int, int]:
        """(physical_used_bytes, capacity_bytes) for the GC watermarks."""
        _logical, physical = self.usage()
        if self.cfg.capacity_bytes:
            return physical, self.cfg.capacity_bytes
        try:
            stat = shutil.disk_usage(self.cfg.data_dir)
            return physical, stat.total
        except OSError:
            return physical, 0

    def try_gc(self) -> int:
        """TTL sweep + usage-driven eviction, least-popular first.

        Not-done tasks are treated as active while their access_time is
        fresh (pieces still landing); once stale past the TTL they are
        abandoned downloads and reclaimed too. Capacity eviction orders by
        download priority, then the CAStore's decayed serve-popularity
        (cold content leaves before the pod's hot model), then oldest
        access. Reclaim accounting is honest about sharing: deleting one
        alias of hardlink-shared content frees ~0 physical bytes, so the
        sweep keeps going until the PHYSICAL watermark is met.
        """
        reclaimed = 0
        logical_freed = 0
        physical_freed = 0
        now = time.time()
        candidates: list[TaskStorage] = []
        for ts in self.tasks():
            if ts.md.task_type != TaskType.STANDARD:
                continue  # persistent cache entries are pinned
            stale = now - ts.md.access_time > self.cfg.task_ttl_s
            if not ts.md.done and not stale:
                continue  # active download
            if stale:
                sz = ts.disk_usage()
                shared = ts.nlink() > 1
                if self.delete_task(ts.md.task_id):
                    reclaimed += 1
                    logical_freed += sz
                    if not shared:
                        physical_freed += sz
            else:
                candidates.append(ts)
        with self._lock:
            dead_subs = [tid for tid, st in self._subtasks.items()
                         if st.parent.md.task_id not in self._tasks
                         or now - st.md.access_time > self.cfg.task_ttl_s]
            for tid in dead_subs:
                del self._subtasks[tid]
        used, cap = self._usage()
        if cap and used / cap > self.cfg.disk_gc_high_ratio:
            target = int(cap * self.cfg.disk_gc_low_ratio)
            mono = time.monotonic()

            def evict_key(t: TaskStorage):
                pop = (self.castore.popularity(t.md.task_id, now=mono)
                       if self.castore is not None else 0.0)
                # class-weighted popularity (QoS): a bulk tenant's content
                # must out-earn critical content 16:1 in observed serves
                # before eviction prefers keeping it — a churning bulk
                # herd cannot launder the pod's hot critical model out of
                # the store just by being recently busy
                pop *= CLASS_EVICT_WEIGHTS.get(t.md.qos_class, 1.0)
                # lowest download priority first (numeric DESC — LEVEL6
                # before LEVEL0), then coldest by class-weighted
                # serve-popularity, then oldest access
                return (-t.md.priority, pop, t.md.access_time)

            candidates.sort(key=evict_key)
            for ts in candidates:
                if used <= target:
                    break
                sz = ts.disk_usage()
                # the last hardlink to an inode frees bytes; an alias of
                # still-referenced content frees only its metadata
                freed = sz if ts.nlink() <= 1 else 0
                if self.delete_task(ts.md.task_id):
                    used -= freed
                    logical_freed += sz
                    physical_freed += freed
                    reclaimed += 1
        self.last_gc_stats = {
            "reclaimed_tasks": reclaimed,
            "logical_bytes_freed": logical_freed,
            "physical_bytes_freed": physical_freed,
        }
        return reclaimed
