"""Per-task persistent metadata.

Role parity: reference ``client/daemon/storage/metadata.go:28-40``
(``persistentMetadata``) — the JSON sidecar that lets a restarted daemon
re-index finished tasks (``storage_manager.go:674 ReloadPersistentTask``).
A task directory holds ``data`` (the content) and ``metadata.json`` (this).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from dataclasses import dataclass, field

from ..idl.messages import PieceInfo, TaskType

METADATA_FILE = "metadata.json"
DATA_FILE = "data"


@dataclass
class PieceMeta:
    num: int
    start: int           # offset in the task file
    size: int
    digest: str = ""     # "crc32c:..." of this piece's bytes
    cost_ms: int = 0     # how long the download took (ML feature)
    source: str = ""     # peer id it came from; "" = back-source

    def to_info(self) -> PieceInfo:
        return PieceInfo(piece_num=self.num, range_start=self.start,
                         range_size=self.size, digest=self.digest,
                         download_cost_ms=self.cost_ms)


@dataclass
class TaskMetadata:
    task_id: str
    task_type: TaskType = TaskType.STANDARD
    url: str = ""
    tag: str = ""
    application: str = ""
    content_length: int = -1
    total_piece_count: int = -1
    piece_size: int = 0
    digest: str = ""                     # whole-content digest if known
    header: dict = field(default_factory=dict)
    pieces: dict[int, PieceMeta] = field(default_factory=dict)
    done: bool = False
    success: bool = False
    # sub-task support: a ranged task stores into its parent's file
    parent_task_id: str = ""
    range_start: int = 0                 # offset of this task's range in parent
    range_length: int = -1
    access_time: float = field(default_factory=time.time)
    create_time: float = field(default_factory=time.time)
    # idl.Priority numeric (0 = highest): disk GC evicts low-priority
    # content first (reference storage GC orders eviction by application
    # priority before recency)
    priority: int = 0
    # QoS service class this task was downloaded under ("" = pre-QoS):
    # capacity eviction weights serve-popularity by class, so a bulk
    # tenant's churn cannot evict the pod's hot critical model (see
    # StorageManager.try_gc)
    qos_class: str = ""

    @property
    def stored_bytes(self) -> int:
        return sum(p.size for p in self.pieces.values())

    def all_pieces_present(self) -> bool:
        if self.total_piece_count < 0:
            return False
        return len(self.pieces) >= self.total_piece_count

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["task_type"] = int(self.task_type)
        d["pieces"] = {str(k): dataclasses.asdict(v) for k, v in self.pieces.items()}
        return json.dumps(d)

    @staticmethod
    def from_json(raw: str) -> "TaskMetadata":
        d = json.loads(raw)
        pieces = {int(k): PieceMeta(**v) for k, v in d.pop("pieces", {}).items()}
        d["task_type"] = TaskType(d.get("task_type", 0))
        md = TaskMetadata(**d)
        md.pieces = pieces
        return md

    def save(self, task_dir: str) -> None:
        """Crash-safe persist: tmp file + fsync + atomic rename + directory
        fsync. A daemon killed mid-persist must never boot with torn
        metadata — the reader sees either the old complete file or the new
        complete file, and the rename itself survives a crash because the
        directory entry is flushed too. Callers run this off-loop
        (mark_done/persist ride the storage executor)."""
        tmp = os.path.join(task_dir, METADATA_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write(self.to_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(task_dir, METADATA_FILE))
        try:
            dfd = os.open(task_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass                    # fs without dir-fsync: best effort

    @staticmethod
    def load(task_dir: str) -> "TaskMetadata":
        with open(os.path.join(task_dir, METADATA_FILE)) as f:
            return TaskMetadata.from_json(f.read())
