"""dragonfly2_tpu — a TPU-pod-native P2P distribution fabric.

A brand-new implementation of the capabilities of Dragonfly2 (CNCF's P2P
file-distribution / image-acceleration system), designed idiomatically for
TPU pods and JAX/XLA rather than ported:

- ``manager``   — global control plane of record (clusters, configs, jobs).
- ``scheduler`` — per-cluster brain: peer/task/host state machines and
  ICI/DCN-topology-aware parent selection.
- ``daemon``    — per-host data plane: piece engine, storage, upload server,
  proxy, object-storage gateway, HBM sink.
- ``trainer``   — JAX bandwidth-predictor (MLP + GNN) trained on TPU and
  served back into scheduling decisions.
- ``tools``     — dfget / dfcache / dfstore CLIs.

Reference surface: aobt/Dragonfly2 (see SURVEY.md for the file:line map).
"""

__version__ = "0.1.0"
