"""Runtime health plane: event-loop watchdog + per-stage SLO engine.

Role parity: none in the reference — Dragonfly2 leans on Go's runtime
(pprof, scheduler preemption) to keep a wedged goroutine from silencing a
peer. asyncio has no such safety net: PRs 1 and 2 each shipped a fix for a
*silent* loop deadlock (a lost-cancellation piece worker, then a
Condition.wait that died holding the dispatcher lock) that wedged the pod
with zero log output. This module turns that failure class into a
first-class, self-reporting event:

* **Loop lag sampler** — a monitor coroutine sleeps a fixed interval and
  measures the overshoot (how long the loop failed to give it the CPU
  back). Exported as the ``df_loop_lag_seconds`` histogram plus a
  high-water gauge; an overshoot past ``stall_threshold_s`` is a *stall*:
  the full await-chain stack dump plus active flight-recorder state goes
  to the log and the ``/debug/health`` ring.

* **Coroutine watchdog** — hot paths register *sections* (``with
  PLANE.watchdog.section("piece.wire", deadline_s=...)``) around awaits
  that own a latency budget. The monitor walks open sections each tick;
  one that overruns its deadline gets its owning task's await chain dumped
  (``Task.get_stack`` only shows the outermost frame — the exact frame
  that hid both earlier hangs — so the walker follows ``cr_await``) and
  counts an SLO breach for its stage.

* **SLO engine** — per-stage latency budgets (schedule→dispatch,
  first-byte, wire, HBM-ingest) evaluated from flight-recorder piece rows
  at task finish and from watchdog overruns, exported as
  ``df_slo_breach_total{stage,rung}`` and annotated onto flight summaries
  so ``dfdiag``'s why-slow verdict can name the blown budget.

Overhead contract: the monitor is ONE coroutine per process ticking at
``sample_interval_s``; registering a section is a dict insert; when the
plane is not running (``PLANE.active`` false) hot paths skip even that.

Exposure: ``GET /debug/health`` on the daemon upload port and on every
launcher's ``--debug-port`` (``?dump=1`` returns the text stack dump).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
import weakref
from collections import deque
from dataclasses import dataclass, field

from .metrics import REGISTRY

log = logging.getLogger("df.health")

# flight-recorder piece-row key -> SLO stage name (the budget vocabulary)
STAGE_KEYS = (("queue_ms", "schedule"), ("ttfb_ms", "first_byte"),
              ("wire_ms", "wire"), ("hbm_ms", "hbm"))

_loop_lag = REGISTRY.histogram(
    "df_loop_lag_seconds", "event-loop scheduling lag sampled by the "
    "health monitor", buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                               1.0, 2.5, 5.0, 10.0, 30.0))
_loop_lag_max = REGISTRY.gauge(
    "df_loop_lag_max_seconds", "high-water event-loop lag since boot")
_loop_stalls = REGISTRY.counter(
    "df_loop_stalls_total", "loop-lag samples past the stall threshold")
_overruns = REGISTRY.counter(
    "df_watchdog_overrun_total", "watchdog sections past their deadline",
    ("section",))
_slo_breaches = REGISTRY.counter(
    "df_slo_breach_total", "per-stage latency budget breaches",
    ("stage", "rung"))
_qos_slo_breaches = REGISTRY.counter(
    "df_qos_slo_breach_total",
    "per-stage latency budget breaches by QoS class (budgets scaled by "
    "CLASS_SLO_MULTIPLIERS: critical answers to tighter budgets, bulk "
    "gets brownout headroom)", ("cls", "stage"))


@dataclass
class HealthConfig:
    """Knobs for the runtime health plane (daemon config ``health``)."""

    enabled: bool = True
    sample_interval_s: float = 0.1     # monitor tick / lag sample period
    stall_threshold_s: float = 1.0     # lag past this = loop stall event
    dump_min_interval_s: float = 10.0  # stack-dump rate limit
    # per-stage SLO budgets (ms) evaluated over flight-recorder piece rows;
    # <= 0 disables that stage's budget
    slo_schedule_ms: float = 1000.0    # scheduled -> dispatched (queue)
    slo_first_byte_ms: float = 2000.0  # dispatched -> first body byte
    slo_wire_ms: float = 5000.0        # first byte -> piece verified
    slo_hbm_ms: float = 1000.0         # wire done -> staged for the sink

    def budgets_ms(self) -> dict[str, float]:
        return {"schedule": self.slo_schedule_ms,
                "first_byte": self.slo_first_byte_ms,
                "wire": self.slo_wire_ms,
                "hbm": self.slo_hbm_ms}


# ---------------------------------------------------------------- stacks

def format_stacks(*, max_depth: int = 16) -> str:
    """Every thread's stack + every asyncio task's FULL await chain.

    ``Task.get_stack`` reports only the outermost coroutine frame, which is
    exactly what hid the PR 1/PR 2 hangs — so walk ``cr_await`` /
    ``gi_yieldfrom`` by hand. Shared by ``/debug/stacks`` (debug_http) and
    the watchdog's auto-dumps.
    """
    import io
    import sys
    import threading
    import traceback

    buf = io.StringIO()
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        buf.write(f"--- thread {names.get(tid, tid)} ---\n")
        traceback.print_stack(frame, file=buf)
    buf.write("--- asyncio tasks ---\n")
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:        # no running loop (called from a thread)
        tasks = set()
    for task in tasks:
        buf.write(f"{task.get_name()}: {task.get_coro()}\n")
        buf.write(format_await_chain(task, max_depth=max_depth))
    return buf.getvalue()


def format_await_chain(task: asyncio.Task, *, max_depth: int = 16) -> str:
    """One task's await chain, innermost frame last (where it is parked)."""
    out: list[str] = []
    coro, depth = task.get_coro(), 0
    while coro is not None and depth < max_depth:
        frame = (getattr(coro, "cr_frame", None)
                 or getattr(coro, "gi_frame", None))
        if frame is not None:
            out.append(f"  {frame.f_code.co_filename}:{frame.f_lineno} "
                       f"{frame.f_code.co_name}\n")
        nxt = (getattr(coro, "cr_await", None)
               or getattr(coro, "gi_yieldfrom", None))
        if nxt is None and frame is None:
            break
        coro = nxt
        depth += 1
    return "".join(out)


# ---------------------------------------------------------------- SLO

# per-class SLO budget multipliers (multi-tenant QoS): a flight summary
# carrying ``qos_class`` is judged against its class's scaled budgets —
# ``critical`` work answers to HALF the configured budgets (it exists to
# hold a tight tail), ``bulk`` gets 4x headroom (being throttled under
# brownout is its contract, not a breach). ``standard`` and classless
# ("" — every pre-QoS caller) stay exactly on the configured budgets.
CLASS_SLO_MULTIPLIERS = {"critical": 0.5, "standard": 1.0, "bulk": 4.0,
                         "": 1.0}


class SLOEngine:
    """Per-stage latency budgets over flight-recorder timestamps.

    Budgets come from ``HealthConfig``; breaches are counted once per task
    (``observe_summary`` at conductor finish) or per watchdog overrun
    (``breach``), labeled by the degradation-ladder rung that was serving
    when the budget blew — "the wire stage breached while on back_source"
    reads very differently from the same breach on p2p.
    """

    def __init__(self, budgets_ms: dict[str, float] | None = None, *,
                 enabled: bool = True):
        self.enabled = enabled
        self.budgets_ms: dict[str, float] = dict(
            budgets_ms or HealthConfig().budgets_ms())
        self._counts: dict[tuple[str, str], int] = {}

    def configure(self, budgets_ms: dict[str, float]) -> None:
        self.budgets_ms.update(budgets_ms)

    def budget_s(self, stage: str) -> float:
        return max(self.budgets_ms.get(stage, 0.0), 0.0) / 1000.0

    def section_deadline_s(self, n_pieces: int = 1) -> float:
        """Watchdog deadline for one parent request: the request window
        covers connection+TTFB plus the wire time of EVERY piece in the
        group — judging it against the single-piece wire budget alone
        would trip the watchdog on healthy multi-piece spans. 0 (section
        disabled) when both budgets are unset."""
        wire = self.budget_s("wire")
        if wire <= 0:
            return 0.0
        return self.budget_s("first_byte") + wire * max(n_pieces, 1)

    def annotate(self, summary: dict) -> dict:
        """Pure annotation (no counters): per-stage breach counts over the
        summary's piece rows, attached as ``summary['slo_breaches']`` so
        every flight surface (HTTP, dfdiag, PeerResult) carries the
        verdict. Idempotent; untouched summary when the engine is off
        (``health.enabled: false`` must really mean off)."""
        if not self.enabled:
            return summary
        mult = CLASS_SLO_MULTIPLIERS.get(
            summary.get("qos_class", ""), 1.0)
        breaches: dict[str, int] = {}
        for row in summary.get("piece_rows") or []:
            for key, stage in STAGE_KEYS:
                budget = self.budgets_ms.get(stage, 0.0) * mult
                if budget > 0 and row.get(key, 0.0) > budget:
                    breaches[stage] = breaches.get(stage, 0) + 1
        summary["slo_breaches"] = breaches
        summary["slo_budgets_ms"] = {
            k: v * mult for k, v in self.budgets_ms.items() if v > 0}
        return summary

    def observe_summary(self, summary: dict) -> dict[str, int]:
        """Count the summary's breaches into ``df_slo_breach_total`` —
        called ONCE per task, at conductor finish."""
        if not self.enabled:
            return {}
        breaches = summary.get("slo_breaches")
        if breaches is None:
            breaches = self.annotate(summary)["slo_breaches"]
        rung = summary.get("served_rung") or "p2p"
        cls = summary.get("qos_class") or "standard"
        for stage, n in breaches.items():
            self._count(stage, rung, n)
            # per-class breach accounting (QoS): the per-class SLO budget
            # verdict operators alert on — a critical-class breach pages,
            # a bulk-class one is the brownout working as designed
            _qos_slo_breaches.labels(cls, stage).inc(n)
        return breaches

    def breach(self, stage: str, rung: str = "p2p", n: int = 1) -> None:
        """A breach observed OUTSIDE a flight summary (watchdog overrun)."""
        if self.enabled:
            self._count(stage, rung, n)

    def _count(self, stage: str, rung: str, n: int) -> None:
        _slo_breaches.labels(stage, rung).inc(n)
        key = (stage, rung)
        self._counts[key] = self._counts.get(key, 0) + n

    def snapshot(self) -> dict:
        return {"budgets_ms": dict(self.budgets_ms),
                "breaches": [{"stage": s, "rung": r, "count": c}
                             for (s, r), c in sorted(self._counts.items())]}


# ---------------------------------------------------------------- watchdog

class _Section:
    __slots__ = ("id", "name", "stage", "rung", "deadline_at", "task",
                 "opened_at", "fired")

    def __init__(self, sid: int, name: str, stage: str, rung: str,
                 deadline_at: float, task: asyncio.Task | None):
        self.id = sid
        self.name = name
        self.stage = stage
        self.rung = rung
        self.deadline_at = deadline_at
        self.task = task
        self.opened_at = time.monotonic()
        self.fired = False


class _SectionCtx:
    __slots__ = ("_wd", "_section")

    def __init__(self, wd: "Watchdog | None", section: _Section | None):
        self._wd = wd
        self._section = section

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if self._wd is not None and self._section is not None:
            self._wd._close(self._section, failed=exc_type is not None)
        return False


_NULL_CTX = _SectionCtx(None, None)


class Watchdog:
    """Deadline sections over awaits; the plane's monitor sweeps them."""

    def __init__(self, plane: "HealthPlane"):
        self._plane = plane
        self._ids = itertools.count(1)
        self._sections: dict[int, _Section] = {}

    def section(self, name: str, deadline_s: float, *, stage: str = "",
                rung: str = "p2p") -> _SectionCtx:
        """Register a deadline around the caller's next await(s). No-op
        (shared null context) while the plane is not running or the
        deadline is unset — the hot path pays one attribute load."""
        if not self._plane.active or deadline_s <= 0:
            return _NULL_CTX
        try:
            task = asyncio.current_task()
        except RuntimeError:
            task = None
        s = _Section(next(self._ids), name, stage, rung,
                     time.monotonic() + deadline_s, task)
        self._sections[s.id] = s
        return _SectionCtx(self, s)

    def _close(self, section: _Section, *, failed: bool = False) -> None:
        self._sections.pop(section.id, None)
        # SLO accounting is exactly-once per piece: a section that overran
        # and then FAILED (deadline cancel, transport error) never lands a
        # flight row, so the breach is counted here; one that completed
        # late is counted by its own flight row at task finish instead
        if section.fired and failed and section.stage:
            self._plane.slo.breach(section.stage, section.rung)

    def check(self, now: float) -> None:
        """Monitor tick: fire each overdue section once (the await-chain
        dump + overrun counter; the SLO breach is settled at close)."""
        for s in list(self._sections.values()):
            if s.fired or now < s.deadline_at:
                continue
            s.fired = True
            age = now - s.opened_at
            _overruns.labels(s.name).inc()
            chain = (format_await_chain(s.task)
                     if s.task is not None and not s.task.done() else "")
            self._plane.record_event(
                "section_overrun",
                f"watchdog: section {s.name} over deadline "
                f"({age:.2f}s held, budget {s.deadline_at - s.opened_at:.2f}s)",
                stacks=chain, section=s.name, stage=s.stage, rung=s.rung)
            self._plane.maybe_dump(
                f"watchdog section {s.name} overran its deadline")

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {"active_sections": [
            {"name": s.name, "stage": s.stage,
             "held_s": round(now - s.opened_at, 3),
             "deadline_in_s": round(s.deadline_at - now, 3),
             "overdue": s.fired}
            for s in self._sections.values()]}


# ---------------------------------------------------------------- plane

class HealthPlane:
    """Process-wide health runtime: one monitor coroutine, refcounted.

    Co-resident services (the test suite runs several daemons per process)
    share the plane the way they share the metrics REGISTRY: ``acquire()``
    at service start, ``release()`` at stop; the monitor runs while any
    holder is alive and is recreated transparently when a fresh event loop
    replaces the one it was started on (sequential ``asyncio.run`` calls).
    """

    MAX_EVENTS = 32

    def __init__(self) -> None:
        self.cfg = HealthConfig()
        self.slo = SLOEngine(self.cfg.budgets_ms())
        self.watchdog = Watchdog(self)
        self.events: deque = deque(maxlen=self.MAX_EVENTS)
        self.started_at = time.time()
        self.last_lag_s = 0.0
        self.max_lag_s = 0.0
        self.samples = 0
        self.stalls = 0
        self._refs = 0
        self._monitor: asyncio.Task | None = None
        self._last_dump = 0.0
        self._recorders: list = []      # weakrefs to FlightRecorders

    # -- lifecycle -----------------------------------------------------

    @property
    def active(self) -> bool:
        return self._monitor is not None and not self._monitor.done()

    def acquire(self, cfg: HealthConfig | None = None) -> None:
        """Adopt config and ensure the monitor runs on the CURRENT loop.
        Requires a running loop. Refcounted against release().

        The plane is process-wide, so config is LAST-CALLER-WINS (the
        same contract as tracing.configure and the shared REGISTRY):
        co-resident services share one set of budgets and one
        enabled/disabled state — in production each process runs one
        service, so the shared knobs only show in multi-daemon tests."""
        if cfg is not None:
            self.cfg = cfg
            self.slo.configure(cfg.budgets_ms())
            # disabling the plane disables the WHOLE plane: no monitor,
            # no sections (watchdog.section short-circuits on active), and
            # no SLO counting/annotation either
            self.slo.enabled = cfg.enabled
        self._refs += 1
        if not self.cfg.enabled:
            # last-caller-wins includes OFF: a disabled acquire stops a
            # monitor an earlier holder started
            if self._monitor is not None:
                self._monitor.cancel()
                self._monitor = None
            return
        if self._monitor is not None and self._monitor.done():
            self._monitor = None        # prior loop gone (sequential runs)
        if self._monitor is None:
            self._monitor = asyncio.get_running_loop().create_task(
                self._run(), name="df-health-monitor")

    def release(self) -> None:
        self._refs = max(0, self._refs - 1)
        if self._refs == 0 and self._monitor is not None:
            self._monitor.cancel()
            self._monitor = None

    def attach_recorder(self, recorder) -> None:
        """Register a FlightRecorder whose active-flight state rides the
        stall dumps (weakly — a stopped daemon must not pin its journal)."""
        self._recorders = [r for r in self._recorders if r() is not None]
        if all(r() is not recorder for r in self._recorders):
            self._recorders.append(weakref.ref(recorder))

    # -- monitor -------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            # re-read each tick: a later acquire() may retune the cadence
            interval = max(self.cfg.sample_interval_s, 0.01)
            t0 = loop.time()
            await asyncio.sleep(interval)
            lag = max(loop.time() - t0 - interval, 0.0)
            self.samples += 1
            self.last_lag_s = lag
            _loop_lag.observe(lag)
            if lag > self.max_lag_s:
                self.max_lag_s = lag
                _loop_lag_max.set(lag)
            if lag >= self.cfg.stall_threshold_s:
                self.stalls += 1
                _loop_stalls.inc()
                self.record_event(
                    "loop_stall",
                    f"event loop stalled {lag:.2f}s (threshold "
                    f"{self.cfg.stall_threshold_s:.2f}s)", lag_s=lag)
                self.maybe_dump(f"loop stalled {lag:.2f}s")
            self.watchdog.check(time.monotonic())

    # -- events + dumps ------------------------------------------------

    def record_event(self, kind: str, message: str, *, stacks: str = "",
                     **extra) -> None:
        log.warning("%s", message)
        self.events.append({"t": time.time(), "kind": kind,
                            "message": message, "stacks": stacks, **extra})

    def flight_state(self) -> list[dict]:
        out = []
        for ref in list(self._recorders):
            rec = ref()
            if rec is None:
                self._recorders.remove(ref)
                continue
            out.append({"tasks": rec.index()})
        return out

    def dump(self) -> str:
        """Full await-chain stacks + active flight-recorder state — the
        first two questions of any hang investigation, answered in one
        read."""
        parts = [format_stacks()]
        flights = self.flight_state()
        if flights:
            parts.append("--- flight recorders ---")
            for i, f in enumerate(flights):
                for t in f["tasks"]:
                    parts.append(f"recorder[{i}] task {t['task_id'][:16]} "
                                 f"state={t['state']} events={t['events']}")
        return "\n".join(parts)

    def maybe_dump(self, why: str) -> None:
        """Rate-limited full dump to the log: a wedged pod self-reports
        once per window instead of log-flooding (or, pre-PR3, saying
        nothing at all)."""
        now = time.monotonic()
        if now - self._last_dump < self.cfg.dump_min_interval_s:
            return
        self._last_dump = now
        log.warning("health dump (%s):\n%s", why, self.dump())

    # -- exposure ------------------------------------------------------

    def snapshot(self) -> dict:
        stalled = (self.events and self.events[-1]["kind"] == "loop_stall"
                   and time.time() - self.events[-1]["t"] < 60.0)
        overdue = any(s["overdue"]
                      for s in self.watchdog.snapshot()["active_sections"])
        return {
            "status": ("stalled" if stalled or overdue else "ok"),
            "active": self.active,
            "loop": {"last_lag_s": round(self.last_lag_s, 6),
                     "max_lag_s": round(self.max_lag_s, 6),
                     "samples": self.samples,
                     "stalls": self.stalls,
                     "sample_interval_s": self.cfg.sample_interval_s,
                     "stall_threshold_s": self.cfg.stall_threshold_s},
            "watchdog": self.watchdog.snapshot(),
            "slo": self.slo.snapshot(),
            "events": list(self.events),
            "flight_recorders": self.flight_state(),
        }


PLANE = HealthPlane()


def add_health_routes(router) -> None:
    """``GET /debug/health`` — machine-readable health snapshot
    (``?dump=1`` returns the text stack dump instead). Mounted on the
    daemon upload server next to /debug/flight and on every launcher's
    ``--debug-port`` — read-only and cheap, so not gated behind the
    profiling flag."""
    from aiohttp import web

    async def health(request: web.Request) -> web.Response:
        if request.query.get("dump"):
            return web.Response(text=PLANE.dump())
        return web.json_response(PLANE.snapshot())

    router.add_get("/debug/health", health)
