"""Object-storage backend clients behind one interface.

Role parity: reference ``pkg/objectstorage/{objectstorage,s3,oss,obs}.go``.
One S3-COMPATIBLE client covers the real-world backends (AWS S3, GCS's XML
API, MinIO, Ceph RGW — OSS/OBS are S3-compatible too) with stdlib AWS
Signature V4 signing; ``file://`` serves tests and single-host setups. The
daemon's object gateway uses these for the PUT write-back path
(``daemon/objectstorage.py``), and the ``s3://`` origin scheme
(``source/s3_client.py``) shares the signer.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import logging
import os
import urllib.parse
from dataclasses import dataclass, field
from typing import AsyncIterator

import aiohttp

from .errors import Code, DFError

log = logging.getLogger("df.objstore")


# ------------------------------------------------------------------ sigv4

def _sha256_hex(data: bytes) -> str:
    # dflint: disable=DF001 — async callers hash ≤KB canonical-request strings here; whole-payload hashes hop through the executor at the call site
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


@dataclass
class S3Credentials:
    access_key: str = ""
    secret_key: str = ""
    region: str = "us-east-1"
    session_token: str = ""

    @classmethod
    def from_env(cls) -> "S3Credentials":
        return cls(
            access_key=os.environ.get("AWS_ACCESS_KEY_ID", ""),
            secret_key=os.environ.get("AWS_SECRET_ACCESS_KEY", ""),
            region=os.environ.get("AWS_REGION",
                                  os.environ.get("AWS_DEFAULT_REGION",
                                                 "us-east-1")),
            session_token=os.environ.get("AWS_SESSION_TOKEN", ""))


def sign_v4(creds: S3Credentials, method: str, url: str,
            headers: dict[str, str], payload_hash: str,
            *, service: str = "s3",
            now: datetime.datetime | None = None) -> dict[str, str]:
    """AWS Signature Version 4 (stdlib-only). Returns the headers to send
    (input headers + x-amz-date/content-sha256/Authorization)."""
    parts = urllib.parse.urlsplit(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    # lower-case ALL keys first: a caller-supplied "Host"/"Range" colliding
    # case-insensitively with the injected names would otherwise appear
    # twice in SignedHeaders — guaranteed SignatureDoesNotMatch
    out = {k.lower(): v for k, v in headers.items()}
    out["host"] = parts.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    if creds.session_token:
        out["x-amz-security-token"] = creds.session_token

    # the URL's path is already percent-encoded by the caller (_url /
    # quote); re-quoting would turn %20 into %2520 and real S3 answers
    # SignatureDoesNotMatch for any key that needed encoding
    canonical_uri = parts.path or "/"
    query_pairs = sorted(urllib.parse.parse_qsl(parts.query,
                                                keep_blank_values=True))
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}" for k, v in query_pairs)
    signed_names = sorted(out)
    canonical_headers = "".join(
        f"{k}:{out[k].strip()}\n" for k in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method.upper(), canonical_uri, canonical_query, canonical_headers,
        signed_headers, payload_hash])
    scope = f"{date}/{creds.region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256_hex(canonical_request.encode())])
    k = _hmac(("AWS4" + creds.secret_key).encode(), date)
    k = _hmac(k, creds.region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return out


# ------------------------------------------------------------------ clients

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"


@dataclass
class ObjectMeta:
    key: str = ""
    size: int = -1
    etag: str = ""


class S3CompatClient:
    """Path-style S3-compatible backend (AWS, GCS XML, MinIO, OSS, OBS).

    ``endpoint``: e.g. https://s3.amazonaws.com or http://minio:9000.
    Streaming PUTs use UNSIGNED-PAYLOAD (TLS protects integrity in real
    deployments; signing a multi-GB body would require buffering it).
    """

    def __init__(self, endpoint: str,
                 creds: S3Credentials | None = None):
        self.endpoint = endpoint.rstrip("/")
        self.creds = creds or S3Credentials.from_env()
        self._sessions: dict[int, aiohttp.ClientSession] = {}

    async def _session(self) -> aiohttp.ClientSession:
        import asyncio
        loop = asyncio.get_running_loop()
        s = self._sessions.get(id(loop))
        if s is None or s.closed:
            s = aiohttp.ClientSession()
            self._sessions[id(loop)] = s
            self._sessions = {k: v for k, v in self._sessions.items()
                              if not v.closed}
        return s

    async def close(self) -> None:
        import asyncio
        s = self._sessions.pop(id(asyncio.get_running_loop()), None)
        if s is not None and not s.closed:
            await s.close()

    def _url(self, bucket: str, key: str = "") -> str:
        path = f"/{urllib.parse.quote(bucket)}"
        if key:
            path += f"/{urllib.parse.quote(key, safe='/-_.~')}"
        return self.endpoint + path

    def _signed(self, method: str, url: str,
                headers: dict[str, str] | None = None,
                payload_hash: str = _sha256_hex(b"")) -> dict[str, str]:
        if not self.creds.access_key:
            return dict(headers or {})      # anonymous / public buckets
        return sign_v4(self.creds, method, url, headers or {}, payload_hash)

    async def put_object(self, bucket: str, key: str,
                         data: bytes | AsyncIterator[bytes],
                         *, content_length: int = -1) -> None:
        url = self._url(bucket, key)
        headers: dict[str, str] = {}
        if isinstance(data, (bytes, bytearray)):
            # sigv4 needs the whole-payload hash; a multi-MiB object
            # hashed (or even copied) on the loop is the PR 5 stall
            # class (DF001) — hashlib takes the buffer as-is off-loop
            payload_hash = await asyncio.get_running_loop().run_in_executor(
                None, _sha256_hex, data)
            headers["content-length"] = str(len(data))
        else:
            payload_hash = UNSIGNED_PAYLOAD
            if content_length >= 0:
                headers["content-length"] = str(content_length)
        headers = self._signed("PUT", url, headers, payload_hash)
        s = await self._session()
        async with s.put(url, data=data, headers=headers) as resp:
            if resp.status >= 300:
                raise DFError(Code.SOURCE_ERROR,
                              f"s3 put {bucket}/{key}: HTTP {resp.status} "
                              f"{(await resp.text())[:200]}")

    async def get_object(self, bucket: str, key: str, *,
                         range_header: str = "") -> tuple[bytes, int]:
        url = self._url(bucket, key)
        headers: dict[str, str] = {}
        if range_header:
            headers["range"] = range_header
        headers = self._signed("GET", url, headers)
        s = await self._session()
        async with s.get(url, headers=headers) as resp:
            if resp.status == 404:
                raise DFError(Code.SOURCE_NOT_FOUND, f"{bucket}/{key}")
            if resp.status >= 300:
                raise DFError(Code.SOURCE_ERROR,
                              f"s3 get {bucket}/{key}: HTTP {resp.status}")
            return await resp.read(), resp.status

    async def head_object(self, bucket: str, key: str) -> ObjectMeta:
        url = self._url(bucket, key)
        headers = self._signed("HEAD", url)
        s = await self._session()
        async with s.head(url, headers=headers) as resp:
            if resp.status == 404:
                raise DFError(Code.SOURCE_NOT_FOUND, f"{bucket}/{key}")
            if resp.status >= 300:
                raise DFError(Code.SOURCE_ERROR,
                              f"s3 head {bucket}/{key}: HTTP {resp.status}")
            return ObjectMeta(
                key=key,
                size=int(resp.headers.get("Content-Length", "-1")),
                etag=resp.headers.get("ETag", "").strip('"'))

    async def delete_object(self, bucket: str, key: str) -> None:
        url = self._url(bucket, key)
        headers = self._signed("DELETE", url)
        s = await self._session()
        async with s.delete(url, headers=headers) as resp:
            if resp.status >= 300 and resp.status != 404:
                raise DFError(Code.SOURCE_ERROR,
                              f"s3 delete {bucket}/{key}: "
                              f"HTTP {resp.status}")


@dataclass
class BackendConfig:
    """One gateway bucket's backend (daemon config)."""

    kind: str = "file"              # file | s3
    base: str = ""                  # file: dir path; s3: endpoint URL
    bucket: str = ""                # backend-side bucket name (s3)
    access_key: str = ""
    secret_key: str = ""
    region: str = "us-east-1"


def make_backend(cfg: BackendConfig):
    if cfg.kind == "s3":
        creds = (S3Credentials(cfg.access_key, cfg.secret_key, cfg.region)
                 if cfg.access_key else S3Credentials.from_env())
        client = S3CompatClient(cfg.base, creds)
    elif cfg.kind == "file":
        # "." backend-bucket keeps the legacy flat file layout (base/key)
        client = FileBackend(cfg.base)
        cfg = BackendConfig(**{**cfg.__dict__, "bucket": cfg.bucket or "."})
    else:
        raise DFError(Code.INVALID_ARGUMENT,
                      f"unknown backend kind {cfg.kind!r}")
    client.bucket = cfg.bucket          # gateway passes this to put_object
    return client


class FileBackend:
    """file:// backend: same interface, local directory storage."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def _path(self, bucket: str, key: str) -> str:
        # dflint: disable=DF001 — two lstat walks for sandbox containment, µs-scale
        path = os.path.realpath(os.path.join(self.base_dir, bucket, key))
        # dflint: disable=DF001 — two lstat walks for sandbox containment, µs-scale
        root = os.path.realpath(self.base_dir)
        if not path.startswith(root + os.sep):
            raise DFError(Code.INVALID_ARGUMENT, "path escapes backend root")
        return path

    async def put_object(self, bucket: str, key: str, data, *,
                         content_length: int = -1) -> None:
        import tempfile
        path = self._path(bucket, key)
        loop = asyncio.get_running_loop()
        # whole-object body writes hop through the default executor
        # (DF001); the surrounding mkstemp/replace/unlink are µs-scale
        # metadata syscalls on a local fs
        # dflint: disable=DF001 — mkstemp/makedirs are metadata syscalls, not buffer traversals
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            f = os.fdopen(fd, "wb")
            try:
                if isinstance(data, (bytes, bytearray)):
                    await loop.run_in_executor(None, f.write, data)
                else:
                    async for chunk in data:
                        await loop.run_in_executor(None, f.write, chunk)
            finally:
                f.close()
            # dflint: disable=DF001 — atomic rename, metadata syscall
            os.replace(tmp, path)
        except BaseException:
            try:
                # dflint: disable=DF001 — unlink of a just-made temp file
                os.unlink(tmp)
            except OSError:
                pass
            raise

    async def get_object(self, bucket: str, key: str, *,
                         range_header: str = "") -> tuple[bytes, int]:
        path = self._path(bucket, key)

        def _read() -> bytes | None:
            try:
                with open(path, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                return None

        body = await asyncio.get_running_loop().run_in_executor(None, _read)
        if body is None:
            raise DFError(Code.SOURCE_NOT_FOUND, f"{bucket}/{key}")
        return body, 200

    async def head_object(self, bucket: str, key: str) -> ObjectMeta:
        path = self._path(bucket, key)
        # dflint: disable=DF001 — one stat on a local fs, µs-scale
        if not os.path.exists(path):
            raise DFError(Code.SOURCE_NOT_FOUND, f"{bucket}/{key}")
        # dflint: disable=DF001 — one stat on a local fs, µs-scale
        return ObjectMeta(key=key, size=os.path.getsize(path))

    async def delete_object(self, bucket: str, key: str) -> None:
        path = self._path(bucket, key)
        try:
            # dflint: disable=DF001 — one unlink on a local fs, µs-scale
            os.unlink(path)
        except FileNotFoundError:
            pass

    async def close(self) -> None:
        pass
