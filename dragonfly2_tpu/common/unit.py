"""Byte-size units: parse "4MiB"-style strings, format counts.

Role parity: reference ``pkg/unit``.
"""

from __future__ import annotations

import re

KiB = 1024
MiB = 1024 * KiB
GiB = 1024 * MiB
TiB = 1024 * GiB

_SUFFIX = {
    "": 1, "b": 1,
    "k": KiB, "kb": KiB, "kib": KiB,
    "m": MiB, "mb": MiB, "mib": MiB,
    "g": GiB, "gb": GiB, "gib": GiB,
    "t": TiB, "tb": TiB, "tib": TiB,
}

_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([a-zA-Z]*)\s*$")


def parse_bytes(s: str | int | float) -> int:
    """Parse a human byte size ("4MiB", "1.5g", 4096) into an int byte count."""
    if isinstance(s, (int, float)):
        return int(s)
    m = _RE.match(s)
    if not m:
        raise ValueError(f"invalid byte size: {s!r}")
    num, suffix = m.groups()
    mult = _SUFFIX.get(suffix.lower())
    if mult is None:
        raise ValueError(f"invalid byte-size suffix: {suffix!r}")
    return int(float(num) * mult)


def format_bytes(n: int | float) -> str:
    """Human-format a byte count: 4194304 -> "4.0MiB"."""
    n = float(n)
    for name, mult in (("TiB", TiB), ("GiB", GiB), ("MiB", MiB), ("KiB", KiB)):
        if abs(n) >= mult:
            return f"{n / mult:.1f}{name}"
    return f"{int(n)}B"
