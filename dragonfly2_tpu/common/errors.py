"""Coded errors carried across RPC boundaries.

Role parity: the reference's ``internal/dferrors`` (coded errors wrapping
``commonv1.Code``) and the code constants its services switch on
(e.g. NeedBackSource / SchedulerBusy decisions in the daemon's conductor).
"""

from __future__ import annotations

import enum


class Code(enum.IntEnum):
    """Wire error codes. Stable values — part of the IDL."""

    OK = 0

    # generic
    UNKNOWN = 1000
    INVALID_ARGUMENT = 1001
    NOT_FOUND = 1002
    ALREADY_EXISTS = 1003
    PERMISSION_DENIED = 1004
    UNAVAILABLE = 1005
    DEADLINE_EXCEEDED = 1006
    RESOURCE_EXHAUSTED = 1007
    INTERNAL = 1008

    # scheduler → peer control verbs
    SCHED_NEED_BACK_SOURCE = 2000   # peer must fetch from origin itself
    SCHED_PEER_GONE = 2001          # peer was evicted; re-register
    SCHED_TASK_STATUS_ERROR = 2002  # task failed upstream
    SCHED_FORBIDDEN = 2003          # blocklisted / over limits
    SCHED_REREGISTER = 2004         # scheduler lost state; register again

    # data-plane
    CLIENT_PEER_BUSY = 2999         # parent at upload concurrency limit; not a failure
    CLIENT_PIECE_DOWNLOAD_FAIL = 3000
    CLIENT_PIECE_NOT_FOUND = 3001
    CLIENT_BACK_SOURCE_ERROR = 3002
    CLIENT_CONTEXT_CANCELED = 3003
    CLIENT_DIGEST_MISMATCH = 3004
    CLIENT_STORAGE_ERROR = 3005

    # origin
    SOURCE_ERROR = 4000
    SOURCE_NOT_FOUND = 4004
    SOURCE_RANGE_UNSUPPORTED = 4005
    SOURCE_AUTH_ERROR = 4006

    # manager / control plane
    MANAGER_STORE_ERROR = 5000
    MANAGER_KEEPALIVE_EXPIRED = 5001


class DFError(Exception):
    """An error with a wire ``Code``; survives RPC round-trips intact."""

    def __init__(self, code: Code, message: str = ""):
        super().__init__(message or code.name)
        self.code = Code(code)
        self.message = message or code.name

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"DFError({self.code.name}, {self.message!r})"

    @staticmethod
    def wrap(exc: BaseException, default: Code = Code.UNKNOWN) -> "DFError":
        if isinstance(exc, DFError):
            return exc
        return DFError(default, f"{type(exc).__name__}: {exc}")


def is_back_source(exc: BaseException) -> bool:
    return isinstance(exc, DFError) and exc.code == Code.SCHED_NEED_BACK_SOURCE
