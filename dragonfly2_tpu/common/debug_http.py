"""pprof-analog debug surface ANY service can serve.

Role parity: reference ``cmd/dependency/dependency.go:95-117`` gives every
service (daemon, scheduler, manager, trainer) a net/pprof listener. Here:

- ``/debug/stacks``  — every thread's stack + every asyncio task (the
  goroutine-dump analog; first question in any hang investigation)
- ``/debug/profile`` — cProfile the event-loop thread for ?seconds=N
  (the pprof 'profile' analog)
- ``/metrics``       — the process's Prometheus registry

The daemon embeds these routes in its upload server; the scheduler,
manager, and trainer launchers serve them on a dedicated ``--debug-port``.
"""

from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from .metrics import REGISTRY

log = logging.getLogger("df.debug")


async def debug_stacks(_r: web.Request) -> web.Response:
    """Every thread's stack + every asyncio task's full await chain
    (health.format_stacks — shared with the watchdog's auto-dumps)."""
    from .health import format_stacks

    return web.Response(text=format_stacks())


_profile_lock = asyncio.Lock()


async def debug_profile(request: web.Request) -> web.Response:
    """cProfile the event-loop thread for ?seconds=N (default 5, max 60).
    Serialized: two concurrent profilers on one thread corrupt each
    other."""
    import cProfile
    import io
    import pstats

    try:
        seconds = min(max(float(request.query.get("seconds", "5")), 0.0),
                      60.0)
    except ValueError:
        return web.Response(status=400, text="seconds must be a number")
    if _profile_lock.locked():
        return web.Response(status=409, text="a profile is already running")
    async with _profile_lock:
        prof = cProfile.Profile()
        try:
            prof.enable()
            # dflint: disable=DF005 — the sleep IS the profiling window; the lock exists precisely to serialize profilers
            await asyncio.sleep(seconds)
        finally:
            prof.disable()
        out = io.StringIO()
        pstats.Stats(prof, stream=out).sort_stats(
            "cumulative").print_stats(60)
        return web.Response(text=out.getvalue())


async def _metrics(_r: web.Request) -> web.Response:
    return web.Response(text=REGISTRY.expose(),
                        content_type="text/plain")


def add_debug_routes(router) -> None:
    router.add_get("/debug/stacks", debug_stacks)
    router.add_get("/debug/profile", debug_profile)


def add_debug_arg(parser) -> None:
    """The shared --debug-port flag for service launchers."""
    parser.add_argument("--debug-port", type=int, default=0,
                        help="serve /debug/{stacks,profile} + /metrics "
                        "(pprof analog, reference cmd/dependency "
                        "InitMonitor); 0 off, -1 ephemeral")


async def maybe_start_debug(debug_port: int, extra_routes=None):
    """Launcher wiring: start (and announce) the debug server when the
    flag is set; returns the runner (or None) for cleanup at shutdown.
    ``extra_routes``: callable(router) adding service-specific surfaces
    (the scheduler mounts /debug/cluster this way)."""
    if not debug_port:
        return None
    runner, port = await start_debug_server("127.0.0.1", max(debug_port, 0),
                                            extra_routes=extra_routes)
    print(f"debug on :{port}", flush=True)
    return runner


async def start_debug_server(host: str, port: int, extra_routes=None):
    """Serve /debug/{stacks,profile} + /metrics; returns (runner, port).
    ``port`` 0 binds ephemeral. Bind failures raise — a requested debug
    surface that silently isn't there wastes the hang investigation it
    exists for."""
    app = web.Application()
    add_debug_routes(app.router)
    from .health import add_health_routes
    add_health_routes(app.router)
    app.router.add_get("/metrics", _metrics)
    if extra_routes is not None:
        extra_routes(app.router)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    bound = site._server.sockets[0].getsockname()[1]
    log.info("debug endpoints on %s:%d", host, bound)
    return runner, bound
