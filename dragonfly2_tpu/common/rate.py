"""Token-bucket rate limiting (async), the primitive under per-peer and
total-rate limits and the traffic shaper.

Role parity: reference ``client/util`` RateLimiter + golang.org/x/time/rate
usages in ``piece_manager.go`` / ``traffic_shaper.go``.
"""

from __future__ import annotations

import asyncio
import time


class TokenBucket:
    """Classic token bucket. ``rate`` tokens/second, ``burst`` capacity.

    ``rate <= 0`` means unlimited. Thread-compatible for reads; writers are
    expected to be on one event loop (the daemon's).
    """

    def __init__(self, rate: float, burst: float | None = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(self.rate, 1.0)
        self._tokens = self.burst
        self._last = time.monotonic()

    def set_rate(self, rate: float, burst: float | None = None) -> None:
        self._refill()
        self.rate = float(rate)
        if burst is not None:
            self.burst = float(burst)
        elif self.rate > 0:
            self.burst = max(self.rate, 1.0)
        self._tokens = min(self._tokens, self.burst)

    def _refill(self) -> None:
        now = time.monotonic()
        if self.rate > 0:
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_acquire(self, n: float) -> bool:
        if self.rate <= 0:
            return True
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def reserve(self, n: float) -> float:
        """Take ``n`` tokens (going negative if needed); return seconds to wait."""
        if self.rate <= 0:
            return 0.0
        self._refill()
        self._tokens -= n
        if self._tokens >= 0:
            return 0.0
        return -self._tokens / self.rate

    def _unreserve(self, n: float) -> None:
        self._refill()
        self._tokens = min(self.burst, self._tokens + n)

    def refund(self, n: float) -> None:
        """Hand back ``n`` reserved tokens whose bytes were never moved
        (cancelled transfer, 404 after an optimistic acquire)."""
        self._unreserve(n)

    async def acquire(self, n: float) -> None:
        # Oversized requests (a 16 MiB piece against a small burst) are allowed
        # through one at a time by paying the full wait instead of deadlocking.
        delay = self.reserve(n)
        if delay > 0:
            try:
                await asyncio.sleep(delay)
            except asyncio.CancelledError:
                # the bytes were never moved: hand the tokens back
                self._unreserve(n)
                raise


def class_shares(total: float, weights: dict[str, float],
                 demand: dict[str, float]) -> dict[str, float]:
    """Split ``total`` across service classes by weight, counting only
    classes with live demand — idle classes' capacity is borrowed by the
    active ones, so a lone ``bulk`` task gets the full pipe and loses it
    the moment ``critical`` traffic appears. Pure function: the shaper's
    retune and the dfbench QoS model both call exactly this math, which is
    what makes the bench's contended numbers a claim about the shipped
    code rather than a parallel reimplementation. Returns bytes/s per
    class (every class gets a row; idle ones get 0.0)."""
    active = {c: w for c, w in weights.items() if demand.get(c, 0.0) > 0}
    out = {c: 0.0 for c in weights}
    if total <= 0 or not active:
        return out
    wsum = sum(active.values())
    for c, w in active.items():
        out[c] = total * w / wsum
    return out
