"""A small directed-acyclic-graph container.

Role parity: reference ``pkg/graph/dag`` (``dag.go:50``) — backs the per-task
peer tree in the scheduler's resource model: vertices are peers, an edge
parent→child means the child streams pieces from the parent. Cycle-refusing
edge insertion is what keeps the download topology a forest/DAG.
"""

from __future__ import annotations

import random
from typing import Generic, Iterator, TypeVar

V = TypeVar("V")


class DAGError(Exception):
    pass


class DAG(Generic[V]):
    def __init__(self) -> None:
        self._values: dict[str, V] = {}
        self._children: dict[str, set[str]] = {}
        self._parents: dict[str, set[str]] = {}

    def __len__(self) -> int:
        return len(self._values)

    def __contains__(self, vid: str) -> bool:
        return vid in self._values

    def add_vertex(self, vid: str, value: V) -> None:
        if vid in self._values:
            raise DAGError(f"vertex exists: {vid}")
        self._values[vid] = value
        self._children[vid] = set()
        self._parents[vid] = set()

    def get(self, vid: str) -> V:
        try:
            return self._values[vid]
        except KeyError:
            raise DAGError(f"vertex not found: {vid}") from None

    def try_get(self, vid: str) -> V | None:
        return self._values.get(vid)

    def delete_vertex(self, vid: str) -> None:
        if vid not in self._values:
            return
        for p in self._parents.pop(vid):
            self._children[p].discard(vid)
        for c in self._children.pop(vid):
            self._parents[c].discard(vid)
        del self._values[vid]

    def add_edge(self, frm: str, to: str) -> None:
        if frm == to:
            raise DAGError("self edge")
        if frm not in self._values or to not in self._values:
            raise DAGError("vertex not found")
        if to in self._children[frm]:
            raise DAGError("edge exists")
        if self.can_reach(to, frm):
            raise DAGError(f"edge {frm}->{to} would create a cycle")
        self._children[frm].add(to)
        self._parents[to].add(frm)

    def delete_edge(self, frm: str, to: str) -> None:
        self._children.get(frm, set()).discard(to)
        self._parents.get(to, set()).discard(frm)

    def delete_in_edges(self, vid: str) -> None:
        for p in list(self._parents.get(vid, ())):
            self.delete_edge(p, vid)

    def can_reach(self, frm: str, to: str) -> bool:
        """True if ``to`` is reachable from ``frm`` along child edges."""
        seen = set()
        stack = [frm]
        while stack:
            v = stack.pop()
            if v == to:
                return True
            if v in seen:
                continue
            seen.add(v)
            stack.extend(self._children.get(v, ()))
        return False

    def children(self, vid: str) -> set[str]:
        return set(self._children.get(vid, ()))

    def parents(self, vid: str) -> set[str]:
        return set(self._parents.get(vid, ()))

    def in_degree(self, vid: str) -> int:
        return len(self._parents.get(vid, ()))

    def out_degree(self, vid: str) -> int:
        return len(self._children.get(vid, ()))

    def vertex_ids(self) -> list[str]:
        return list(self._values.keys())

    def values(self) -> Iterator[V]:
        return iter(self._values.values())

    def random_vertex_ids(self, n: int) -> list[str]:
        ids = self.vertex_ids()
        if n >= len(ids):
            random.shuffle(ids)
            return ids
        return random.sample(ids, n)

    def descendants(self, vid: str) -> set[str]:
        out: set[str] = set()
        stack = list(self._children.get(vid, ()))
        while stack:
            v = stack.pop()
            if v in out:
                continue
            out.add(v)
            stack.extend(self._children.get(v, ()))
        return out
