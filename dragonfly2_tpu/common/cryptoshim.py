"""OpenSSL-CLI-backed stand-in for the ``cryptography`` wheel.

Role parity: none in the reference (Go links its crypto statically).
The container images this repo targets carry the ``openssl`` binary but
not the ``cryptography`` Python wheel, and installing wheels is off the
table — so every TLS surface (proxy MITM minting, fleet cert issuance,
the OCI mirror e2e) used to skip its tests and ship unexercised.

``install()`` registers a minimal, subprocess-backed implementation of
the exact ``cryptography`` subset this package uses (EC P-256 keys,
X.509 build/sign/parse, PEM serialization) under the real module names
in ``sys.modules`` — a NO-OP whenever the real wheel is importable, so
environments that have it see zero behavior change. The certs produced
are real certs (OpenSSL makes them); ``ssl.SSLContext`` handshakes
against them exactly as with wheel-minted ones.

Deliberate non-goals: anything the package does not call. This is not a
general reimplementation — unknown API surface raises instead of
guessing, so a future consumer of a missing feature fails loudly at the
call site rather than subtly at the handshake.
"""

from __future__ import annotations

import ipaddress
import os
import secrets
import subprocess
import sys
import tempfile
import types

OPENSSL = "openssl"


def _run(args: list[str], data: bytes | None = None) -> bytes:
    proc = subprocess.run([OPENSSL] + args, input=data,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if proc.returncode != 0:
        raise RuntimeError(
            f"openssl {' '.join(args[:3])}... failed: "
            f"{proc.stderr.decode(errors='replace').strip()}")
    return proc.stdout


# -- names ---------------------------------------------------------------

class _OID:
    def __init__(self, name: str):
        self._name = name

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<OID {self._name}>"


class NameOID:
    COMMON_NAME = _OID("commonName")


class NameAttribute:
    def __init__(self, oid, value: str):
        self.oid = oid
        self.value = value


class Name:
    """Held as an RFC2253 string (what ``openssl -nameopt RFC2253``
    prints), which makes equality between a parsed issuer and a parsed
    subject exact. Optionally carries a backref to the certificate PEM
    it was read from — the builder needs the CA *certificate* to sign a
    leaf via the CLI, and ``issuer_name(ca_cert.subject)`` is the only
    way the package ever names a non-self issuer."""

    def __init__(self, attributes=(), *, rfc2253: str = "",
                 cert_pem: bytes = b""):
        self._attrs = list(attributes)
        if rfc2253:
            self._rfc2253 = rfc2253
        else:
            # only CN is ever used by this package
            self._rfc2253 = ",".join(
                f"CN={a.value}" for a in self._attrs)
        self._cert_pem = cert_pem

    def __eq__(self, other) -> bool:
        return isinstance(other, Name) and self._rfc2253 == other._rfc2253

    def __hash__(self) -> int:
        return hash(self._rfc2253)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Name({self._rfc2253})>"

    def _subj(self) -> str:
        """openssl -subj form. CN values are the only attributes the
        package writes; escape the two characters -subj treats
        specially."""
        parts = []
        for a in self._attrs:
            v = str(a.value).replace("\\", "\\\\").replace("/", "\\/")
            parts.append(f"CN={v}")
        if not parts and self._rfc2253.startswith("CN="):
            parts = [self._rfc2253]
        return "/" + "/".join(parts)


# -- keys ----------------------------------------------------------------

class SECP256R1:
    name = "secp256r1"
    _openssl = "prime256v1"


class _Encoding:
    PEM = "PEM"


class _PrivateFormat:
    PKCS8 = "PKCS8"


class _PublicFormat:
    SubjectPublicKeyInfo = "SubjectPublicKeyInfo"


class NoEncryption:
    pass


class _ECPublicKey:
    def __init__(self, pem: bytes):
        self._pem = pem

    def public_bytes(self, encoding, fmt) -> bytes:
        return self._pem


class _ECPrivateKey:
    def __init__(self, pkcs8_pem: bytes):
        self._pem = pkcs8_pem

    def public_key(self) -> _ECPublicKey:
        with tempfile.TemporaryDirectory(prefix="dfshim-") as d:
            kp = os.path.join(d, "k.pem")
            with open(kp, "wb") as f:
                f.write(self._pem)
            pub = _run(["pkey", "-in", kp, "-pubout"])
        return _ECPublicKey(pub)

    def private_bytes(self, encoding, fmt, encryption) -> bytes:
        return self._pem


def generate_private_key(curve) -> _ECPrivateKey:
    raw = _run(["ecparam", "-name", getattr(curve, "_openssl", "prime256v1"),
                "-genkey", "-noout"])
    pkcs8 = _run(["pkcs8", "-topk8", "-nocrypt"], raw)
    return _ECPrivateKey(pkcs8)


def load_pem_private_key(data: bytes, password=None,
                         backend=None) -> _ECPrivateKey:
    if password is not None:
        raise NotImplementedError("cryptoshim: encrypted keys unsupported")
    pkcs8 = _run(["pkcs8", "-topk8", "-nocrypt"], data)
    return _ECPrivateKey(pkcs8)


def load_pem_public_key(data: bytes, backend=None) -> _ECPublicKey:
    # normalize through openssl so malformed input fails HERE, not at sign
    return _ECPublicKey(_run(["pkey", "-pubin", "-pubout"], data))


# -- hashes --------------------------------------------------------------

class SHA256:
    name = "sha256"


# -- x509 extensions -----------------------------------------------------

class BasicConstraints:
    def __init__(self, ca: bool, path_length: int | None):
        self.ca = ca
        self.path_length = path_length

    def _conf(self) -> str:
        v = f"CA:{'TRUE' if self.ca else 'FALSE'}"
        if self.ca and self.path_length is not None:
            v += f",pathlen:{self.path_length}"
        return f"basicConstraints={v}"


_KEY_USAGE_FLAGS = (
    ("digital_signature", "digitalSignature"),
    ("content_commitment", "nonRepudiation"),
    ("key_encipherment", "keyEncipherment"),
    ("data_encipherment", "dataEncipherment"),
    ("key_agreement", "keyAgreement"),
    ("key_cert_sign", "keyCertSign"),
    ("crl_sign", "cRLSign"),
    ("encipher_only", "encipherOnly"),
    ("decipher_only", "decipherOnly"),
)


class KeyUsage:
    def __init__(self, **flags: bool):
        self._flags = flags

    def _conf(self) -> str:
        names = [ossl for attr, ossl in _KEY_USAGE_FLAGS
                 if self._flags.get(attr)]
        return "keyUsage=" + ",".join(names)


class GeneralName:
    pass


class DNSName(GeneralName):
    def __init__(self, value: str):
        self.value = value

    def _conf(self) -> str:
        return f"DNS:{self.value}"


class IPAddress(GeneralName):
    def __init__(self, value):
        self.value = value

    def _conf(self) -> str:
        return f"IP:{self.value}"


class SubjectAlternativeName:
    def __init__(self, general_names):
        self._names = list(general_names)

    def _conf(self) -> str:
        return "subjectAltName=" + ",".join(n._conf() for n in self._names)

    def get_values_for_type(self, type_) -> list:
        return [n.value for n in self._names if isinstance(n, type_)]


class ExtensionNotFound(Exception):
    pass


class _Extension:
    def __init__(self, value):
        self.value = value


class _Extensions:
    def __init__(self, cert: "Certificate"):
        self._cert = cert

    def get_extension_for_class(self, cls) -> _Extension:
        if cls is SubjectAlternativeName:
            return _Extension(self._cert._san())
        raise ExtensionNotFound(
            f"cryptoshim: only SubjectAlternativeName is parseable "
            f"(asked for {cls.__name__})")


def random_serial_number() -> int:
    # the wheel's contract: positive, < 2^159
    return secrets.randbits(158) | 1


# -- certificates --------------------------------------------------------

class Certificate:
    def __init__(self, pem: bytes):
        self._pem = pem
        self._subject: Name | None = None
        self._issuer: Name | None = None

    def public_bytes(self, encoding) -> bytes:
        return self._pem

    def _parse_names(self) -> None:
        out = _run(["x509", "-noout", "-subject", "-issuer",
                    "-nameopt", "RFC2253"], self._pem).decode()
        subj = issr = ""
        for line in out.splitlines():
            if line.startswith("subject="):
                subj = line[len("subject="):].strip()
            elif line.startswith("issuer="):
                issr = line[len("issuer="):].strip()
        self._subject = Name(rfc2253=subj, cert_pem=self._pem)
        self._issuer = Name(rfc2253=issr)

    @property
    def subject(self) -> Name:
        if self._subject is None:
            self._parse_names()
        return self._subject

    @property
    def issuer(self) -> Name:
        if self._issuer is None:
            self._parse_names()
        return self._issuer

    @property
    def extensions(self) -> _Extensions:
        return _Extensions(self)

    def _san(self) -> SubjectAlternativeName:
        out = _run(["x509", "-noout", "-ext", "subjectAltName"],
                   self._pem).decode()
        names: list[GeneralName] = []
        for line in out.splitlines():
            line = line.strip()
            if ":" not in line or line.endswith(":"):
                continue
            for part in line.split(","):
                part = part.strip()
                if part.startswith("DNS:"):
                    names.append(DNSName(part[4:]))
                elif part.startswith("IP Address:"):
                    names.append(IPAddress(
                        ipaddress.ip_address(part[len("IP Address:"):])))
        if not names:
            raise ExtensionNotFound("no subjectAltName")
        return SubjectAlternativeName(names)


def load_pem_x509_certificate(data: bytes, backend=None) -> Certificate:
    # round-trip through openssl: verifies the PEM parses AND normalizes
    # trailing garbage away (the wheel is equally strict)
    return Certificate(_run(["x509"], data))


class CertificateBuilder:
    """Collects the same chained state as the wheel's builder; ``sign``
    drives the OpenSSL CLI. Self-signed when the builder's public key
    matches the signing key; otherwise the issuer Name must have been
    read off a Certificate (it carries the CA PEM backref) — which is
    the only non-self pattern this package uses."""

    def __init__(self):
        self._subject: Name | None = None
        self._issuer: Name | None = None
        self._pub: _ECPublicKey | None = None
        self._serial: int | None = None
        self._not_before = None
        self._not_after = None
        self._extensions: list = []

    def subject_name(self, name: Name) -> "CertificateBuilder":
        self._subject = name
        return self

    def issuer_name(self, name: Name) -> "CertificateBuilder":
        self._issuer = name
        return self

    def public_key(self, key) -> "CertificateBuilder":
        self._pub = key if isinstance(key, _ECPublicKey) \
            else _ECPublicKey(key.public_bytes(_Encoding.PEM,
                                               _PublicFormat
                                               .SubjectPublicKeyInfo))
        return self

    def serial_number(self, serial: int) -> "CertificateBuilder":
        self._serial = serial
        return self

    def not_valid_before(self, dt) -> "CertificateBuilder":
        self._not_before = dt
        return self

    def not_valid_after(self, dt) -> "CertificateBuilder":
        self._not_after = dt
        return self

    def add_extension(self, ext, critical: bool) -> "CertificateBuilder":
        self._extensions.append((ext, critical))
        return self

    def _days(self) -> int:
        import datetime
        if self._not_after is None:
            return 1
        now = datetime.datetime.now(datetime.timezone.utc)
        secs = (self._not_after - now).total_seconds()
        return max(1, int(secs // 86400) + 1)

    def _ext_conf(self) -> str:
        lines = ["[v3_shim]"]
        for ext, critical in self._extensions:
            conf = ext._conf()
            if critical:
                key, _, val = conf.partition("=")
                conf = f"{key}=critical,{val}"
            lines.append(conf)
        return "\n".join(lines) + "\n"

    def sign(self, private_key: _ECPrivateKey, algorithm,
             backend=None) -> Certificate:
        if self._subject is None or self._pub is None:
            raise ValueError("cryptoshim: subject and public key required")
        with tempfile.TemporaryDirectory(prefix="dfshim-") as d:
            key_p = os.path.join(d, "sign.key")
            pub_p = os.path.join(d, "pub.pem")
            ext_p = os.path.join(d, "ext.cnf")
            with open(key_p, "wb") as f:
                f.write(private_key._pem)
            with open(pub_p, "wb") as f:
                f.write(self._pub._pem)
            with open(ext_p, "w", encoding="utf-8") as f:
                # req -x509 wants a full config; x509 -req only the section
                f.write("[req]\ndistinguished_name=dn\nprompt=no\n[dn]\n"
                        "CN=placeholder\n" + self._ext_conf())
            self_signed = (self._issuer is None
                           or self._issuer == self._subject)
            if self_signed:
                signer_pub = private_key.public_key()._pem
                if signer_pub != self._pub._pem:
                    raise NotImplementedError(
                        "cryptoshim: self-named issuer with a foreign "
                        "public key")
                pem = _run(["req", "-new", "-x509", "-key", key_p,
                            "-subj", self._subject._subj(),
                            "-days", str(self._days()), "-sha256",
                            "-config", ext_p, "-extensions", "v3_shim",
                            "-set_serial", str(self._serial
                                               or random_serial_number())])
                return Certificate(pem)
            ca_pem = getattr(self._issuer, "_cert_pem", b"")
            if not ca_pem:
                raise NotImplementedError(
                    "cryptoshim: issuer Name must come from a parsed "
                    "Certificate (ca_cert.subject) to locate the CA")
            ca_p = os.path.join(d, "ca.pem")
            with open(ca_p, "wb") as f:
                f.write(ca_pem)
            # CSR exists only to carry the subject; -force_pubkey swaps
            # in the real leaf key, so the CSR's own key (the CA key,
            # already on disk) never shows in the result
            csr = _run(["req", "-new", "-key", key_p,
                        "-subj", self._subject._subj()])
            pem = _run(["x509", "-req", "-CA", ca_p, "-CAkey", key_p,
                        "-set_serial", str(self._serial
                                           or random_serial_number()),
                        "-days", str(self._days()), "-sha256",
                        "-extfile", ext_p, "-extensions", "v3_shim",
                        "-force_pubkey", pub_p], csr)
            return Certificate(pem)


# -- module assembly -----------------------------------------------------

def _available() -> bool:
    """Is the CLI there? Cached: one probe per process."""
    global _PROBE
    if _PROBE is None:
        try:
            _run(["version"])
            _PROBE = True
        except (OSError, RuntimeError):
            _PROBE = False
    return _PROBE


_PROBE: bool | None = None


def install() -> bool:
    """Register the shim under the ``cryptography`` module names.

    No-op (returns True) when the real wheel imports; returns False when
    neither the wheel nor the ``openssl`` binary is available — callers
    (the TLS test prologues) turn that into a skip, which then means
    "this machine genuinely cannot do TLS", not "a wheel is missing".
    """
    import importlib.util
    if "cryptography" in sys.modules:
        return True        # real wheel already imported, or shim installed
    if importlib.util.find_spec("cryptography") is not None:
        return True
    if not _available():
        return False

    root = types.ModuleType("cryptography")
    root.__df_shim__ = True

    x509 = types.ModuleType("cryptography.x509")
    for name in ("Name", "NameAttribute", "CertificateBuilder",
                 "Certificate", "BasicConstraints", "KeyUsage",
                 "GeneralName", "DNSName", "IPAddress",
                 "SubjectAlternativeName", "ExtensionNotFound",
                 "load_pem_x509_certificate", "random_serial_number"):
        setattr(x509, name, globals()[name])
    oid = types.ModuleType("cryptography.x509.oid")
    oid.NameOID = NameOID
    x509.oid = oid

    hazmat = types.ModuleType("cryptography.hazmat")
    primitives = types.ModuleType("cryptography.hazmat.primitives")
    hashes_m = types.ModuleType("cryptography.hazmat.primitives.hashes")
    hashes_m.SHA256 = SHA256
    serialization = types.ModuleType(
        "cryptography.hazmat.primitives.serialization")
    serialization.Encoding = _Encoding
    serialization.PrivateFormat = _PrivateFormat
    serialization.PublicFormat = _PublicFormat
    serialization.NoEncryption = NoEncryption
    serialization.load_pem_private_key = load_pem_private_key
    serialization.load_pem_public_key = load_pem_public_key
    asymmetric = types.ModuleType(
        "cryptography.hazmat.primitives.asymmetric")
    ec_m = types.ModuleType("cryptography.hazmat.primitives.asymmetric.ec")
    ec_m.SECP256R1 = SECP256R1
    ec_m.generate_private_key = generate_private_key
    ec_m.EllipticCurvePrivateKey = _ECPrivateKey
    ec_m.EllipticCurvePublicKey = _ECPublicKey

    primitives.hashes = hashes_m
    primitives.serialization = serialization
    primitives.asymmetric = asymmetric
    asymmetric.ec = ec_m
    hazmat.primitives = primitives
    root.x509 = x509
    root.hazmat = hazmat

    sys.modules["cryptography"] = root
    sys.modules["cryptography.x509"] = x509
    sys.modules["cryptography.x509.oid"] = oid
    sys.modules["cryptography.hazmat"] = hazmat
    sys.modules["cryptography.hazmat.primitives"] = primitives
    sys.modules["cryptography.hazmat.primitives.hashes"] = hashes_m
    sys.modules["cryptography.hazmat.primitives.serialization"] = \
        serialization
    sys.modules["cryptography.hazmat.primitives.asymmetric"] = asymmetric
    sys.modules["cryptography.hazmat.primitives.asymmetric.ec"] = ec_m
    return True
