"""Shared primitives: IDs, piece math, errors, units, rate limiting, DAG,
TTL cache, GC runner, logging, metrics, dynconfig."""
