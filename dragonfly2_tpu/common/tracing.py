"""Distributed tracing: W3C-traceparent spans through every layer.

Role parity: reference OpenTelemetry bootstrap
(``cmd/dependency/dependency.go:95-137`` --jaeger) with spans created in
the conductor (``peertask_conductor.go:183,255,669,1064``), trace context
carried inside the piece HTTP request (``piece_downloader.go:227-228``),
and gin middleware on the upload server. The OTel SDK isn't in this image,
so the implementation is stdlib: contextvar-propagated spans, W3C
``traceparent`` headers on the wire (interoperable with any W3C-compliant
system the fleet talks to), a JSONL file exporter for post-mortems, and an
OTLP/HTTP-JSON exporter for live collectors (Jaeger, Tempo, vendor
backends all ingest OTLP).

Usage:
    configure(service="dfdaemon", jsonl_path=".../traces.jsonl")
    with span("piece.download", task_id=tid) as sp:
        headers["traceparent"] = traceparent()
    # server side:
    with span("upload.serve", parent=from_traceparent(hdr)):
        ...

Debugging a v5p-256 fan-out without trace ids does not work — every piece
request carries the child's trace so a slow transfer is attributable
end-to-end (the round-3 bench regression is the kind of incident these
explain).
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import json
import logging
import os
import queue
import secrets
import threading
import time
from dataclasses import dataclass, field

log = logging.getLogger("df.tracing")

_current: contextvars.ContextVar["SpanContext | None"] = \
    contextvars.ContextVar("df_span", default=None)


@dataclass
class SpanContext:
    trace_id: str                  # 32 hex chars
    span_id: str                   # 16 hex chars
    sampled: bool = True


@dataclass
class Span:
    name: str
    ctx: SpanContext
    parent_span_id: str = ""
    start_ns: int = 0
    end_ns: int = 0
    attributes: dict = field(default_factory=dict)
    status: str = "ok"

    def set(self, **attrs) -> None:
        self.attributes.update(attrs)

    def error(self, message: str) -> None:
        self.status = "error"
        self.attributes["error.message"] = message


class Tracer:
    """Process-wide tracer: sampling + bounded buffer + exporters."""

    MAX_BUFFER = 8192

    def __init__(self) -> None:
        self.service = "dragonfly2-tpu"
        self.sample_ratio = 1.0
        self.enabled = False
        self._jsonl_path = ""
        self._jsonl_file = None
        self._otlp_endpoint = ""
        self._lock = threading.Lock()
        self._buffer: list[Span] = []
        self._last_flush = time.monotonic()   # monotonic: NTP steps must
        # not suppress (or force) the age-based flush
        self._atexit_registered = False
        self._export_q: "queue.Queue[list[Span] | None]" = queue.Queue(64)
        self._exporter: threading.Thread | None = None
        self._flusher: threading.Thread | None = None

    def configure(self, *, service: str = "", jsonl_path: str = "",
                  otlp_endpoint: str = "",
                  sample_ratio: float = 1.0) -> None:
        with self._lock:
            if service:
                self.service = service
            self.sample_ratio = sample_ratio
            self._otlp_endpoint = otlp_endpoint
            if jsonl_path and jsonl_path != self._jsonl_path:
                os.makedirs(os.path.dirname(jsonl_path) or ".",
                            exist_ok=True)
                if self._jsonl_file:
                    self._jsonl_file.close()
                self._jsonl_file = open(jsonl_path, "a", encoding="utf-8")
                self._jsonl_path = jsonl_path
            self.enabled = bool(self._jsonl_file or self._otlp_endpoint)
            if self.enabled and not self._atexit_registered:
                # short-lived runs (the post-mortem case this module exists
                # for) rarely hit the 64-span flush threshold
                atexit.register(self._shutdown_flush)
                self._atexit_registered = True
            if self.enabled and self._flusher is None:
                # timer-driven flush: the finish()-time age check alone
                # cannot drain a burst followed by silence — a live tail of
                # the trace file would show nothing until the next span
                self._flusher = threading.Thread(
                    target=self._flush_loop, name="df-trace-flush",
                    daemon=True)
                self._flusher.start()

    def _sampled(self) -> bool:
        if self.sample_ratio >= 1.0:
            return True
        return secrets.randbelow(10_000) < self.sample_ratio * 10_000

    def start_span(self, name: str, *,
                   parent: SpanContext | None = None, **attrs) -> Span:
        if parent is None:
            parent = _current.get()
        if parent is not None:
            ctx = SpanContext(parent.trace_id, secrets.token_hex(8),
                              parent.sampled)
            parent_id = parent.span_id
        else:
            ctx = SpanContext(secrets.token_hex(16), secrets.token_hex(8),
                              self._sampled())
            parent_id = ""
        return Span(name=name, ctx=ctx, parent_span_id=parent_id,
                    start_ns=time.time_ns(), attributes=dict(attrs))

    def finish(self, sp: Span) -> None:
        sp.end_ns = time.time_ns()
        if not self.enabled or not sp.ctx.sampled:
            return
        with self._lock:
            if len(self._buffer) >= self.MAX_BUFFER:
                self._buffer.pop(0)        # bounded: drop-oldest
            self._buffer.append(sp)
            # size, notable-span, or AGE: a long-lived daemon emitting a
            # trickle must not hold its spans in memory until shutdown
            # (a live `tail -f traces.jsonl` is the point of the file)
            if (len(self._buffer) >= 64
                    or sp.end_ns - sp.start_ns > 1_000_000_000
                    or time.monotonic() - self._last_flush > 5.0):
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_loop(self) -> None:
        while True:
            time.sleep(5.0)
            if self._buffer:
                self.flush()

    def _shutdown_flush(self) -> None:
        """Final flush + export drain; the atexit target AND the explicit
        launcher-shutdown path (module-level ``shutdown``) — one logic
        home so the two exits cannot drift."""
        self.flush()
        if self._otlp_endpoint:
            self.drain_exports()

    def _flush_locked(self) -> None:
        batch, self._buffer = self._buffer, []
        self._last_flush = time.monotonic()
        if not batch:
            return
        if self._jsonl_file is not None:
            for sp in batch:
                self._jsonl_file.write(json.dumps({
                    "name": sp.name, "trace_id": sp.ctx.trace_id,
                    "span_id": sp.ctx.span_id,
                    "parent_span_id": sp.parent_span_id,
                    "start_ns": sp.start_ns, "end_ns": sp.end_ns,
                    "duration_ms": (sp.end_ns - sp.start_ns) / 1e6,
                    "status": sp.status, "service": self.service,
                    "attributes": sp.attributes}) + "\n")
            self._jsonl_file.flush()
        if self._otlp_endpoint:
            # single long-lived exporter thread draining a queue: a thread
            # per batch piles up against a slow collector, and a daemon
            # thread spawned from the atexit flush dies before sending
            self._ensure_exporter()
            try:
                self._export_q.put_nowait(batch)
            except queue.Full:
                log.debug("otlp export queue full; batch dropped")

    def _ensure_exporter(self) -> None:
        if self._exporter is None or not self._exporter.is_alive():
            self._exporter = threading.Thread(target=self._export_loop,
                                              name="otlp-export",
                                              daemon=True)
            self._exporter.start()

    def _export_loop(self) -> None:
        while True:
            batch = self._export_q.get()
            if batch is None:
                return
            self._export_otlp(batch)

    def drain_exports(self, timeout: float = 5.0) -> None:
        """Best-effort: wait for queued OTLP batches to leave (shutdown)."""
        deadline = time.monotonic() + timeout
        while not self._export_q.empty() and time.monotonic() < deadline:
            time.sleep(0.05)

    def _export_otlp(self, batch: list[Span]) -> None:
        """OTLP/HTTP JSON — the lingua franca every collector ingests."""
        import urllib.request
        payload = {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": self.service}}]},
            "scopeSpans": [{"scope": {"name": "dragonfly2-tpu"},
                            "spans": [self._otlp_span(sp)
                                      for sp in batch]}]}]}
        req = urllib.request.Request(
            self._otlp_endpoint.rstrip("/") + "/v1/traces",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10).read()
        except Exception as exc:  # noqa: BLE001 - collector may be away
            log.debug("otlp export failed: %s", exc)

    @staticmethod
    def _otlp_span(sp: Span) -> dict:
        return {
            "traceId": sp.ctx.trace_id, "spanId": sp.ctx.span_id,
            "parentSpanId": sp.parent_span_id, "name": sp.name,
            "startTimeUnixNano": str(sp.start_ns),
            "endTimeUnixNano": str(sp.end_ns),
            "kind": 1,
            "status": {"code": 2 if sp.status == "error" else 1},
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in sp.attributes.items()]}


TRACER = Tracer()
configure = TRACER.configure


def shutdown() -> None:
    """Launcher tail: flush the span buffer and give queued OTLP batches a
    bounded window to leave, deterministically BEFORE the launcher's own
    process-exit path (the atexit registration covers interpreter exit,
    but only once configure() ran; launchers call this unconditionally)."""
    TRACER._shutdown_flush()


_NOOP = Span(name="noop", ctx=SpanContext("0" * 32, "0" * 16,
                                          sampled=False))


@contextlib.contextmanager
def span(name: str, *, parent: SpanContext | None = None, **attrs):
    """Context manager: a span that is `current` inside the block (child
    spans and traceparent() pick it up via contextvars — async-safe).

    Fully free when tracing is disabled (the default): no ids are
    generated, no context is set, traceparent() stays empty — a v5p
    fan-out pushes thousands of pieces/second through this path."""
    if not TRACER.enabled and parent is None and _current.get() is None:
        yield _NOOP
        return
    sp = TRACER.start_span(name, parent=parent, **attrs)
    token = _current.set(sp.ctx)
    try:
        yield sp
    except BaseException as exc:
        sp.error(f"{type(exc).__name__}: {exc}")
        raise
    finally:
        _current.reset(token)
        TRACER.finish(sp)


def current() -> SpanContext | None:
    return _current.get()


def traceparent() -> str:
    """W3C traceparent header for the current span ('' when none)."""
    ctx = _current.get()
    if ctx is None:
        return ""
    flags = "01" if ctx.sampled else "00"
    return f"00-{ctx.trace_id}-{ctx.span_id}-{flags}"


def from_traceparent(header: str) -> SpanContext | None:
    """Parse a W3C traceparent header; None when absent/malformed."""
    if not header:
        return None
    parts = header.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    try:
        flags = int(parts[3], 16)
    except ValueError:
        return None
    return SpanContext(parts[1], parts[2], sampled=bool(flags & 1))
