"""Certificate authority + per-host leaf issuance for HTTPS interception.

Role parity: reference ``client/daemon/proxy/cert.go:37 genLeafCert`` — the
proxy MITMs CONNECT/SNI traffic by minting a short-lived leaf certificate
for the requested host, signed by a CA the fleet's clients trust (containerd
is pointed at the CA file). Differences from the reference, on purpose:

- EC P-256 keys instead of reusing the CA's key material for leaves: leaf
  minting is on the connection path, and EC keygen is ~1ms vs ~100ms RSA.
- The CA auto-generates into the daemon workdir on first use (the reference
  requires an operator-supplied cert; a TPU-pod deployment wants zero-touch
  bootstrap — the same CA file is then mounted into containerd's trust dir).

Leaves live 24h (reference parity) and are cached per host.
"""

from __future__ import annotations

import datetime
import ipaddress
import logging
import os
import re
import ssl
import threading

from . import cryptoshim

cryptoshim.install()   # no-op when the real wheel is importable

from cryptography import x509  # noqa: E402 - shim must land first
from cryptography.hazmat.primitives import hashes, serialization  # noqa: E402
from cryptography.hazmat.primitives.asymmetric import ec  # noqa: E402
from cryptography.x509.oid import NameOID  # noqa: E402

log = logging.getLogger("df.proxy.certs")

LEAF_TTL = datetime.timedelta(hours=24)
CA_TTL = datetime.timedelta(days=3650)


def _name(common_name: str) -> x509.Name:
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])


def generate_ca(common_name: str = "dragonfly2-tpu proxy CA"
                ) -> tuple[bytes, bytes]:
    """Self-signed CA; returns (cert_pem, key_pem)."""
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name))
        .issuer_name(_name(common_name))
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(hours=1))
        .not_valid_after(now + CA_TTL)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_cert_sign=True, crl_sign=True,
            content_commitment=False, key_encipherment=False,
            data_encipherment=False, key_agreement=False,
            encipher_only=False, decipher_only=False), critical=True)
        .sign(key, hashes.SHA256())
    )
    return (cert.public_bytes(serialization.Encoding.PEM),
            key.private_bytes(serialization.Encoding.PEM,
                              serialization.PrivateFormat.PKCS8,
                              serialization.NoEncryption()))


class CertIssuer:
    """CA-backed leaf minting with a per-host cache.

    ``ca_cert_path``/``ca_key_path`` empty -> auto-generate the CA under
    ``workdir`` (``proxy-ca.crt`` / ``proxy-ca.key``) so operators can point
    clients at the .crt.
    """

    def __init__(self, workdir: str, *, ca_cert_path: str = "",
                 ca_key_path: str = ""):
        self.workdir = workdir
        if not ca_cert_path:
            ca_cert_path = os.path.join(workdir, "proxy-ca.crt")
            ca_key_path = os.path.join(workdir, "proxy-ca.key")
            if not os.path.exists(ca_cert_path):
                os.makedirs(workdir, exist_ok=True)
                cert_pem, key_pem = generate_ca()
                with open(ca_cert_path, "wb") as f:
                    f.write(cert_pem)
                with open(ca_key_path, "wb") as f:
                    f.write(key_pem)
                os.chmod(ca_key_path, 0o600)
                log.info("generated proxy CA at %s", ca_cert_path)
        self.ca_cert_path = ca_cert_path
        self.ca_key_path = ca_key_path or ca_cert_path
        with open(ca_cert_path, "rb") as f:
            self.ca_cert = x509.load_pem_x509_certificate(f.read())
        with open(self.ca_key_path, "rb") as f:
            self.ca_key = serialization.load_pem_private_key(f.read(), None)
        self._lock = threading.Lock()
        # host -> (ssl_ctx, not_after); insertion-ordered for LRU eviction
        self._cache: dict[str, tuple[ssl.SSLContext, datetime.datetime]] = {}
        # per-host single-flight: minting host B must not block a cache HIT
        # for host A (the SNI callback runs server_context synchronously on
        # the event loop; a global mint lock would head-of-line block it)
        self._mint_locks: dict[str, threading.Lock] = {}

    # client-controlled names (CONNECT targets, raw SNI bytes) feed the
    # cache: bound it, or a client looping random names grows memory and
    # CPU without limit
    CACHE_MAX = 512

    @staticmethod
    def _sans(hosts: list[str]) -> list[x509.GeneralName]:
        out: list[x509.GeneralName] = []
        for h in hosts:
            try:
                out.append(x509.IPAddress(ipaddress.ip_address(h)))
            except ValueError:
                out.append(x509.DNSName(h))
        return out

    def sign_public_key(self, public_key, hosts: list[str],
                        *, ttl: datetime.timedelta = LEAF_TTL) -> bytes:
        """Sign a leaf for a key whose PRIVATE half the caller keeps
        (manager-issued fleet certs: reference
        ``manager/rpcserver/security_server_v1.go`` + ``pkg/issuer`` — the
        private key never crosses the wire)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(hosts[0] if hosts else "peer"))
            .issuer_name(self.ca_cert.subject)
            .public_key(public_key)
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(hours=1))
            .not_valid_after(now + ttl)
            .add_extension(x509.SubjectAlternativeName(self._sans(hosts)),
                           critical=False)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_encipherment=True,
                data_encipherment=True, key_agreement=True,
                content_commitment=False, key_cert_sign=False,
                crl_sign=False, encipher_only=False, decipher_only=False),
                critical=True)
            .sign(self.ca_key, hashes.SHA256())
        )
        return cert.public_bytes(serialization.Encoding.PEM)

    def _mint(self, host: str) -> tuple[bytes, bytes, datetime.datetime]:
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        not_after = now + LEAF_TTL
        cert_pem = self.sign_public_key(key.public_key(), [host])
        return (cert_pem,
                key.private_bytes(serialization.Encoding.PEM,
                                  serialization.PrivateFormat.PKCS8,
                                  serialization.NoEncryption()),
                not_after)

    def server_context(self, host: str) -> ssl.SSLContext:
        """TLS server context presenting a CA-signed leaf for ``host``.

        Single-flight: mint + file write + load all happen under the lock —
        concurrent cache misses for one host (containerd opening parallel
        layer pulls) otherwise interleave their writes to shared paths and
        load mismatched cert/key pairs (KEY_VALUES_MISMATCH at handshake).
        """
        now = datetime.datetime.now(datetime.timezone.utc)
        with self._lock:
            hit = self._cache.get(host)
            if hit is not None and now < hit[1]:
                self._cache[host] = self._cache.pop(host)   # LRU touch
                return hit[0]
            mint_lock = self._mint_locks.setdefault(host, threading.Lock())
        with mint_lock:
            # double-check: the racer that held the mint lock first filled it
            with self._lock:
                hit = self._cache.get(host)
                if hit is not None and now < hit[1]:
                    return hit[0]
            cert_pem, key_pem, not_after = self._mint(host)
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            # load_cert_chain wants files; they are TRANSIENT (deleted the
            # moment the chain is loaded) so client-controlled names cost no
            # disk. The filename is sanitized (a name like '../proxy-ca'
            # must never escape the leaves dir) and unique per thread so
            # same-sanitization hosts cannot interleave writes.
            leaf_dir = os.path.join(self.workdir, "leaves")
            os.makedirs(leaf_dir, exist_ok=True)
            safe = re.sub(r"[^A-Za-z0-9._-]", "_", host).strip(".")[:64]
            base = os.path.join(
                leaf_dir,
                f"leaf-{safe or 'host'}-{os.getpid()}-"
                f"{threading.get_ident()}")
            try:
                with open(base + ".crt", "wb") as f:
                    f.write(cert_pem + self._ca_pem())
                fd = os.open(base + ".key",
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "wb") as f:
                    f.write(key_pem)
                ctx.load_cert_chain(base + ".crt", base + ".key")
            finally:
                for suffix in (".crt", ".key"):
                    try:
                        os.unlink(base + suffix)
                    except OSError:
                        pass
            with self._lock:
                # expired + LRU eviction keeps the cache bounded
                for key in [k for k, v in self._cache.items()
                            if now >= v[1]]:
                    del self._cache[key]
                    self._mint_locks.pop(key, None)
                while len(self._cache) >= self.CACHE_MAX:
                    evicted = next(iter(self._cache))
                    del self._cache[evicted]
                    self._mint_locks.pop(evicted, None)
                self._cache[host] = (ctx, not_after)
        log.debug("minted leaf cert for %s", host)
        return ctx

    def _ca_pem(self) -> bytes:
        return self.ca_cert.public_bytes(serialization.Encoding.PEM)
