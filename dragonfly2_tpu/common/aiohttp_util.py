"""aiohttp server helpers shared across HTTP surfaces."""

from __future__ import annotations

from aiohttp import web


def resolve_port(runner: web.AppRunner) -> int:
    """The actual bound port of an ephemeral (`:0`) TCPSite.

    aiohttp doesn't expose this publicly; keep the one reach into
    ``site._server`` here so every HTTP surface resolves ports the same way
    and a future aiohttp change breaks exactly one function.
    """
    for site in runner.sites:
        server = getattr(site, "_server", None)
        if server and server.sockets:
            return server.sockets[0].getsockname()[1]
    raise RuntimeError("no bound socket on runner (site not started?)")
