"""Plugin loading: operator-supplied extensions from a plugin directory.

Role parity: reference ``internal/dfplugin/dfplugin.go:43-80`` — Go ``.so``
plugins named ``d7y-<type>-plugin-<name>.so`` exposing
``DragonflyPluginInit(option) -> (plugin, meta)`` with type/name echoed in
the metadata. Python-shaped: a plugin is a module file
``df_plugin_<type>_<name>.py`` in the plugin dir exposing

    def dragonfly_plugin_init(option: dict) -> tuple[object, dict]:
        return impl, {"type": "<type>", "name": "<name>"}

The same contract checks apply (init symbol present, metadata echoes the
requested type and name). Known types: ``evaluator`` (object with an
``evaluate(child, parent, total_piece_count)`` method, consumed by
``scheduler.evaluator.make_evaluator``), ``source`` (a source client
registered for the schemes in ``meta["schemes"]``), and ``searcher``
(object with ``find_scheduler_cluster(clusters, req)``, consumed by
``manager.searcher.load_searcher_plugin``).
"""

from __future__ import annotations

import importlib.util
import logging
import os
from typing import Any

log = logging.getLogger("df.plugins")

INIT_FUNC = "dragonfly_plugin_init"
FILE_FORMAT = "df_plugin_{type}_{name}.py"


class PluginError(Exception):
    pass


def load(plugin_dir: str, type_: str, name: str,
         option: dict | None = None) -> tuple[Any, dict]:
    """Load one plugin; returns (impl, meta). Raises PluginError on any
    contract violation (missing file/symbol, metadata mismatch)."""
    path = os.path.join(plugin_dir, FILE_FORMAT.format(type=type_, name=name))
    # dflint: disable=DF001 — one manifest stat at service start, before traffic
    if not os.path.exists(path):
        raise PluginError(f"plugin not found: {path}")
    spec = importlib.util.spec_from_file_location(
        f"df_plugin_{type_}_{name}", path)
    if spec is None or spec.loader is None:
        raise PluginError(f"cannot load plugin module: {path}")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    init = getattr(module, INIT_FUNC, None)
    if init is None:
        raise PluginError(f"{path}: missing {INIT_FUNC}()")
    impl, meta = init(dict(option or {}))
    if not isinstance(meta, dict) or not meta:
        raise PluginError(f"{path}: empty plugin metadata")
    if meta.get("type") != type_:
        raise PluginError(f"{path}: plugin type {meta.get('type')!r} != "
                          f"requested {type_!r}")
    if meta.get("name") != name:
        raise PluginError(f"{path}: plugin name {meta.get('name')!r} != "
                          f"requested {name!r}")
    log.info("loaded plugin %s/%s from %s", type_, name, path)
    return impl, meta


def discover(plugin_dir: str, type_: str) -> list[str]:
    """Names of available plugins of one type in the dir."""
    # dflint: disable=DF001 — plugin-dir scan at service start, before traffic
    if not os.path.isdir(plugin_dir):
        return []
    prefix = f"df_plugin_{type_}_"
    out = []
    # dflint: disable=DF001 — plugin-dir scan at service start, before traffic
    for fn in sorted(os.listdir(plugin_dir)):
        if fn.startswith(prefix) and fn.endswith(".py"):
            out.append(fn[len(prefix):-3])
    return out


def load_source_plugins(plugin_dir: str) -> int:
    """Load every ``source`` plugin and register its schemes in the origin
    client registry (reference ``pkg/source/plugin.go``). Returns the
    number registered; bad plugins are skipped loudly — a broken optional
    extension must never take the daemon down with it."""
    from ..source.client import client_for, register_client

    n = 0
    for name in discover(plugin_dir, "source"):
        try:
            impl, meta = load(plugin_dir, "source", name)
            schemes = list(meta.get("schemes") or [name])
            for scheme in schemes:
                # a plugin must not silently hijack a built-in scheme
                # (typo'd {'schemes': ['http']} would reroute ALL origin
                # traffic through it)
                try:
                    client_for(f"{scheme}://probe/x")
                except Exception:  # noqa: BLE001 - unknown scheme: free
                    continue
                raise PluginError(
                    f"scheme {scheme!r} already registered — refusing to "
                    f"override a built-in client")
            register_client(schemes, impl)
            n += 1
        except Exception as exc:  # noqa: BLE001 - isolate bad plugins
            log.error("source plugin %s skipped: %s", name, exc)
    return n
