"""Deep ``sys.getsizeof`` walk for bytes-of-state accounting.

The control-plane observatory (/debug/ctrl, dfbench --ctrl) reports how
many bytes of scheduler state each registered peer costs — the number
that decides whether a 10k-daemon fleet fits one asyncio brain. Each
control-plane component (Resource, DecisionLedger, PodFederation,
QuarantineRegistry, ShardAffinity) exposes ``state_bytes()`` built on
this walker.

The walk is O(objects) and therefore EXPENSIVE on a big fleet (~1M
nodes at 10k peers): callers compute it only at snapshot points behind
the /debug/ctrl TTL cache, never on a ruling path.

Accounting rules: containers recurse (dict/list/tuple/set/frozenset/
deque), instances recurse through ``__dict__`` and ``__slots__``; a
shared object is charged once (visited-id set), so cross-references —
every Peer holding its Task, every Task holding its peers — cannot
double-count; modules, classes, and functions are skipped (they are
code, not per-peer state)."""

from __future__ import annotations

import sys
from collections import deque

# code, not state: classes, modules, functions (python + builtin), and
# bound methods reached through instance attributes
_SKIP = (type, type(sys), type(lambda: 0), type(len), type([].append))


def deep_sizeof(obj, seen: set | None = None) -> int:
    """Total ``sys.getsizeof`` over ``obj`` and everything (transitively)
    reachable from it, each distinct object charged once."""
    if seen is None:
        seen = set()
    stack = [obj]
    total = 0
    while stack:
        o = stack.pop()
        oid = id(o)
        if oid in seen:
            continue
        seen.add(oid)
        if isinstance(o, _SKIP):
            continue
        try:
            total += sys.getsizeof(o)
        except TypeError:
            continue
        if isinstance(o, dict):
            stack.extend(o.keys())
            stack.extend(o.values())
        elif isinstance(o, (list, tuple, set, frozenset, deque)):
            stack.extend(o)
        else:
            d = getattr(o, "__dict__", None)
            if d is not None:
                stack.append(d)
            slots = getattr(type(o), "__slots__", ())
            if isinstance(slots, str):
                slots = (slots,)
            for name in slots:
                try:
                    stack.append(getattr(o, name))
                except AttributeError:
                    continue
    return total
