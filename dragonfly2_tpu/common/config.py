"""Typed config base: dataclass configs loadable from YAML/JSON + env overlay.

Role parity: the reference's cobra+viper config plumbing
(``cmd/dependency/dependency.go`` initConfig; per-service option structs with
``Validate()``). Each service defines nested dataclasses; ``load_config``
merges file -> dict -> dataclass with unknown-key errors, then calls
``validate()`` hooks bottom-up.
"""

from __future__ import annotations

import dataclasses
import json
import os
import types
import typing
from typing import Any, Type, TypeVar

_UNION_TYPES = (typing.Union, types.UnionType)

T = TypeVar("T")


class ConfigError(ValueError):
    pass


def _build(cls: Type[T], data: dict[str, Any], path: str) -> T:
    if not dataclasses.is_dataclass(cls):
        raise ConfigError(f"{path}: {cls} is not a dataclass")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for key, value in data.items():
        if key not in fields:
            raise ConfigError(f"{path}: unknown key {key!r} for {cls.__name__}")
        kwargs[key] = _coerce(hints.get(key, fields[key].type), value, f"{path}.{key}")
    return cls(**kwargs)


def _coerce(ftype: Any, value: Any, path: str) -> Any:
    origin = typing.get_origin(ftype)
    if origin in _UNION_TYPES:  # Optional[X]
        args = [a for a in typing.get_args(ftype) if a is not type(None)]
        if value is None:
            return None
        if len(args) == 1:
            return _coerce(args[0], value, path)
        return value
    if dataclasses.is_dataclass(ftype) and isinstance(value, dict):
        return _build(ftype, value, path)
    if origin in (list, tuple) and isinstance(value, (list, tuple)):
        elem = (typing.get_args(ftype) or (Any,))[0]
        seq = [_coerce(elem, v, f"{path}[{i}]") for i, v in enumerate(value)]
        return tuple(seq) if origin is tuple else seq
    if origin is dict and isinstance(value, dict):
        return value
    if ftype is float and isinstance(value, int):
        return float(value)
    return value


def from_dict(cls: Type[T], data: dict[str, Any]) -> T:
    cfg = _build(cls, data, cls.__name__)
    _validate_tree(cfg)
    return cfg


def _validate_tree(obj: Any) -> None:
    if not dataclasses.is_dataclass(obj):
        return
    for f in dataclasses.fields(obj):
        _validate_tree(getattr(obj, f.name))
    validate = getattr(obj, "validate", None)
    if callable(validate):
        validate()


def load_config(cls: Type[T], config_path: str | None = None,
                overrides: dict[str, Any] | None = None) -> T:
    data: dict[str, Any] = {}
    if config_path:
        with open(config_path) as f:
            text = f.read()
        if config_path.endswith((".yaml", ".yml")):
            data = _parse_yaml(text)
        else:
            data = json.loads(text)
    if overrides:
        data = _deep_merge(data, overrides)
    return from_dict(cls, data)


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _parse_yaml(text: str) -> dict[str, Any]:
    """Parse YAML, via PyYAML if present, else a small indentation-based subset
    (maps, lists, scalars) sufficient for our config files."""
    try:
        import yaml  # type: ignore

        return yaml.safe_load(text) or {}
    except ImportError:
        pass
    return _mini_yaml(text)


def _mini_yaml(text: str) -> dict[str, Any]:
    lines = [ln for ln in text.splitlines()
             if ln.strip() and not ln.lstrip().startswith("#")]

    def walk(i: int, indent: int, container: Any) -> int:
        while i < len(lines):
            ln = lines[i]
            ind = len(ln) - len(ln.lstrip())
            if ind <= indent:
                return i
            content = ln.strip()
            if content.startswith("- "):
                if not isinstance(container, list):
                    raise ConfigError(f"list item outside list: {ln!r}")
                container.append(_scalar(content[2:].strip()))
                i += 1
                continue
            key, sep, rest = content.partition(":")
            if not sep:
                raise ConfigError(f"cannot parse line: {ln!r}")
            key, rest = key.strip(), rest.strip()
            if rest == "":
                # block value: list if the first child line is "- ", else map
                sub: Any = {}
                if i + 1 < len(lines):
                    nxt = lines[i + 1]
                    nind = len(nxt) - len(nxt.lstrip())
                    if nind > ind and nxt.strip().startswith("- "):
                        sub = []
                container[key] = sub
                i = walk(i + 1, ind, sub)
                continue
            container[key] = _scalar(rest)
            i += 1
        return i

    root: dict[str, Any] = {}
    walk(0, -1, root)
    return root


def _scalar(s: str) -> Any:
    if s.startswith(("'", '"')) and s.endswith(s[0]) and len(s) >= 2:
        return s[1:-1]
    low = s.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    if low in ("null", "~", ""):
        return None
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


# DF_* vars that are NOT config-field overrides (consumed elsewhere:
# dfpath default, tpu.topology injection + probe timeout). Missing an
# entry here is fatal at boot — the launcher folds every other DF_* var
# into the config tree and unknown keys are errors by design.
_ENV_NON_CONFIG = {"DF_WORKDIR", "DF_ZONE", "DF_DEFAULT_ZONE",
                   "DF_ICI_COORDS", "DF_POD_ID",
                   "DF_TOPOLOGY_PROBE_TIMEOUT_S"}


def env_overrides(prefix: str = "DF_") -> dict[str, Any]:
    """DF_A__B=2 -> {"a": {"b": 2}} (double underscore nests)."""
    out: dict[str, Any] = {}
    for key, val in os.environ.items():
        if not key.startswith(prefix) or key in _ENV_NON_CONFIG:
            continue
        path = key[len(prefix):].lower().split("__")
        node = out
        for part in path[:-1]:
            node = node.setdefault(part, {})
        node[path[-1]] = _scalar(val)
    return out
