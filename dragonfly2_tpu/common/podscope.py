"""Podscope: pod-wide distribution-tree aggregation over daemon snapshots.

Role parity: none in the reference — the paper's fabric is judged at pod
scope (1 seed fanning out to N daemons over ICI/DCN), but every per-daemon
surface (`/debug/flight`, `/debug/health`) sees one end of each transfer
and the scheduler's `/debug/cluster` is blind to the scheduler-less `pex`
rung. Podscope ingests the debug snapshots of a daemon SET and
reconstructs, per task, the distribution tree the pod actually used:

  * **edges** — who served whom, with bytes, wire ms, and estimated
    bandwidth. Each edge is seen from the child side (flight piece rows)
    and, when the parent journaled the serve (`TaskFlight.serve`,
    `upload` rows), confirmed from the parent side with serve/limiter
    timings attached.
  * **tree + depth** — each daemon's tree parent is the peer that
    delivered most of its bytes; depth is measured from the origin
    (origin = 0, a back-sourcing or pre-seeded root holder = 1).
  * **pod makespan** — first download activity to last daemon complete,
    on the daemons' wall clocks (an NTP-synced pod; ms-level skew is in
    the noise at fan-out timescales).
  * **origin amplification** — origin bytes ÷ content size. A healthy
    mesh fetches the content across the origin uplink exactly once
    (≈ 1.0); N means the mesh carried nothing. A pod serving content
    seeded before the observation window (origin bytes 0) reports 1.0
    with a note — the content still crossed that uplink exactly once.
  * **seed-uplink utilization** — the heaviest-serving node, its share
    of all mesh bytes, and its estimated serve bandwidth.
  * **a bottleneck-edge verdict** — the slowest substantial edge; named
    as a *breach* only when it runs under ``BOTTLENECK_FACTOR`` of the
    median edge bandwidth (the dfdiag straggler rule, pod-scoped).

Everything below ``collect_pod`` is a pure function over dict snapshots,
so dfbench feeds it simulated flights and the tests feed it synthetic
ones; ``collect_pod`` is the thin HTTP half ``dfdiag --pod`` and
``stress --pod-report`` share. ``edges_from_summary`` is the
``kind=edge`` row source for ``scheduler/records.py`` — the per-edge
bandwidth observations the trainer's parent-quality model learns from.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

ORIGIN = "origin"                # node label for back-source fetches
BOTTLENECK_FACTOR = 3.0          # edge slower than median/3 = breach
SUBSTANTIAL_EDGE_SHARE = 0.05    # edges carrying <5% of content are noise
AMPLIFICATION_BREACH = 1.5       # origin pulled >1.5x the content = breach


def _pctl(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return round(s[min(len(s) - 1, int(q * len(s)))], 3)


# ---------------------------------------------------------------- collect

def _get_json(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def collect_daemon(addr: str, *, timeout_s: float = 10.0,
                   max_flights: int = 16) -> dict:
    """One daemon's podscope snapshot over HTTP: the flight index + the
    ``max_flights`` most recent full flights, plus /debug/health and
    /debug/pex (each optional — absence is recorded, never raised)."""
    base = f"http://{addr}"
    snap: dict = {"addr": addr, "flights": {}, "health": None, "pex": None}
    index = _get_json(f"{base}/debug/flight", timeout_s)   # raises: caller
    snap["flight_index"] = {k: index.get(k) for k in
                            ("enabled", "max_tasks", "occupancy",
                             "evicted_total")}
    tasks = index.get("tasks") or []
    for row in tasks[-max_flights:]:
        tid = row.get("task_id", "")
        try:
            snap["flights"][tid] = _get_json(
                f"{base}/debug/flight/{tid}", timeout_s)
        except (OSError, ValueError):
            continue            # flight evicted between index and fetch
    for key, path in (("health", "/debug/health"), ("pex", "/debug/pex"),
                      ("verdicts", "/debug/verdicts")):
        try:
            snap[key] = _get_json(f"{base}{path}", timeout_s)
        except (OSError, ValueError):
            snap[key] = None    # older daemon / surface disabled
    return snap


def collect_pod(addrs: list[str], *, timeout_s: float = 10.0,
                max_flights: int = 16) -> list[dict]:
    """Snapshot every daemon; an unreachable one yields
    ``{"addr": ..., "error": ...}`` instead of failing the sweep — a pod
    diagnosis that dies on the first wedged daemon diagnoses nothing.
    Daemons are fetched CONCURRENTLY: one half-stalled daemon answering
    at the timeout edge (the exact condition this tool exists to catch)
    must cost the sweep one daemon's worth of wall time, not the pod's."""
    from concurrent.futures import ThreadPoolExecutor

    def one(addr: str) -> dict:
        try:
            return collect_daemon(addr, timeout_s=timeout_s,
                                  max_flights=max_flights)
        except (OSError, ValueError) as exc:
            return {"addr": addr, "error": str(exc) or type(exc).__name__}

    if not addrs:
        return []
    with ThreadPoolExecutor(max_workers=min(16, len(addrs))) as pool:
        return list(pool.map(one, addrs))


# -------------------------------------------------------------- aggregate

def _flight_summary(flight: dict) -> dict:
    return flight.get("summary") or flight


def _flight_times(flight: dict, summary: dict) -> tuple[float, float]:
    """(abs_start_s, abs_end_s) of a flight on its daemon's wall clock."""
    start = float(flight.get("started_at") or 0.0)
    events = flight.get("events") or []
    if events:
        end_ms = max(e.get("t_ms", 0.0) for e in events)
    else:
        end_ms = max((r.get("start_ms", 0.0) + r.get("total_ms", 0.0)
                      for r in summary.get("piece_rows") or []),
                     default=0.0)
    return start, start + end_ms / 1000.0


def _aggregate_task(task_id: str, holders: list[tuple[str, dict]],
                    pods: dict[str, str] | None = None) -> dict:
    """One task's tree/edge/makespan report from [(addr, flight), ...].
    ``pods`` (addr -> pod id, from each daemon's /debug/pex host block or
    a bench snapshot's ``pod`` label) marks pod-CROSSING edges: the DCN
    tier the federation plane rations, rendered as ``[dcn]`` by
    render_pod and summed into ``cross_pod_bytes``."""
    pods = pods or {}
    peer_to_addr: dict[str, str] = {}
    for addr, flight in holders:
        pid = flight.get("peer_id") or ""
        if pid:
            peer_to_addr[pid] = addr

    def label(peer_id: str) -> str:
        if peer_id == "":
            return ORIGIN
        return peer_to_addr.get(peer_id, peer_id)

    # child-side edges from piece rows; key on resolved (src, dst) labels
    edges: dict[tuple[str, str], dict] = {}
    serve_by_peers: dict[tuple[str, str], dict] = {}
    content = 0
    origin_bytes = 0
    placed_bytes = 0
    starts: list[float] = []
    ends: list[float] = []
    complete = 0
    downloaders = 0
    slo: dict[str, int] = {}
    rungs: dict[str, int] = {}
    # sharded-task readiness across the pod: (host, shard) ready/total
    # tallies + tree-vs-swap byte split from the summaries' shards block
    shards_ready = shards_total = 0
    shard_tree_bytes = shard_swap_bytes = shard_fallbacks = 0
    for addr, flight in holders:
        summary = _flight_summary(flight)
        sh = summary.get("shards")
        if sh:
            shards_ready += sh.get("ready", 0)
            shards_total += sh.get("total", 0)
            shard_tree_bytes += sh.get("tree_bytes", 0)
            shard_swap_bytes += sh.get("swap_bytes", 0)
            shard_fallbacks += sh.get("fallbacks", 0)
        rows = summary.get("piece_rows") or []
        dl_bytes = (summary.get("bytes_p2p", 0)
                    + summary.get("bytes_source", 0)
                    + summary.get("bytes_placed", 0))
        content = max(content, dl_bytes)
        origin_bytes += summary.get("bytes_source", 0)
        placed_bytes += summary.get("bytes_placed", 0)
        for stage, n in (summary.get("slo_breaches") or {}).items():
            slo[stage] = slo.get(stage, 0) + n
        served_rung = summary.get("served_rung") or ""
        if served_rung:
            rungs[served_rung] = rungs.get(served_rung, 0) + 1
        if rows or summary.get("placed_pieces"):
            # placement-only flights (whole-content adoption, full warm
            # restart) have no wire rows but ARE download activity — not
            # counting them would read the healthiest pod as incomplete
            downloaders += 1
            t0, t1 = _flight_times(flight, summary)
            starts.append(t0)
            if flight.get("state") == "success":
                complete += 1
                ends.append(t1)
        for r in rows:
            key = (label(r.get("parent") or ""), addr)
            e = edges.setdefault(key, {
                "src": key[0], "dst": key[1],
                "src_peer": r.get("parent") or "",
                "dst_peer": flight.get("peer_id") or "",
                "bytes": 0, "pieces": 0, "wire_ms": 0.0,
                "ttfb_ms": 0.0, "confirmed": False})
            e["bytes"] += r.get("bytes", 0)
            e["pieces"] += 1
            e["wire_ms"] += r.get("wire_ms", 0.0)
            e["ttfb_ms"] += r.get("ttfb_ms", 0.0)
        # parent-side serve rows (the upload journal): keyed by peer ids —
        # resolved against the child edges below
        my_peer = flight.get("peer_id") or ""
        for srv in flight.get("serves") or []:
            skey = (my_peer, srv.get("peer") or srv.get("addr") or "")
            s = serve_by_peers.setdefault(skey, {
                "bytes": 0, "pieces": 0, "serve_ms": 0.0, "wait_ms": 0.0,
                "relayed_pieces": 0, "src": addr})
            s["bytes"] += srv.get("bytes", 0)
            s["pieces"] += srv.get("pieces", 1)
            s["serve_ms"] += srv.get("serve_ms", 0.0)
            s["wait_ms"] += srv.get("wait_ms", 0.0)
            if srv.get("relayed"):
                s["relayed_pieces"] += srv.get("pieces", 1)

    # stitch: a child edge (src_peer -> dst_peer) confirmed by the
    # parent's serve journal carries the parent-side timings too
    def _attach(e: dict, s: dict) -> None:
        e["confirmed"] = True
        e["serve_ms"] = round(s["serve_ms"], 3)
        e["wait_ms"] = round(s["wait_ms"], 3)
        e["serve_bps"] = (round(s["bytes"] / (s["serve_ms"] / 1e3))
                          if s["serve_ms"] > 0 else 0)
        if s.get("relayed_pieces"):
            # the parent streamed (part of) this edge against its landing
            # watermark: a cut-through edge of the distribution tree
            e["relayed"] = True
            e["relayed_pieces"] = s["relayed_pieces"]

    used_serves: set[tuple[str, str]] = set()
    for e in edges.values():
        # origin edges (src_peer "") must never match an ANONYMOUS serve
        # key ("" is also the peer id of a serve-only flight) — origin
        # bytes by definition did not come off a daemon's upload port
        s = (serve_by_peers.get((e["src_peer"], e["dst_peer"]))
             if e["src_peer"] else None)
        if s is not None:
            used_serves.add((e["src_peer"], e["dst_peer"]))
            _attach(e, s)
        e["wire_ms"] = round(e["wire_ms"], 3)
        e["ttfb_ms"] = round(e["ttfb_ms"], 3)
        e["bandwidth_bps"] = (round(e["bytes"] / (e["wire_ms"] / 1e3))
                              if e["wire_ms"] > 0 else 0)
        # pod-tier mark: both endpoints' pods known and different = a
        # DCN-crossing edge of the two-level federation tree
        sp, dp = pods.get(e["src"], ""), pods.get(e["dst"], "")
        if sp and dp and sp != dp:
            e["cross_pod"] = True
    # fallback stitch: a parent that never downloaded the task here (a
    # restarted seed re-seeded from disk) journals serves on a flight
    # with NO peer id, so the exact key can't match. When a child edge's
    # src peer resolved to no known daemon and exactly ONE daemon holds
    # otherwise-unmatched serve rows for that child, that daemon is the
    # parent: confirm the edge and relabel it to the daemon's address.
    for e in edges.values():
        if e["confirmed"] or not e["src_peer"] or e["src"] == ORIGIN:
            continue               # origin edges never stitch to a daemon
        if e["src"] != e["src_peer"]:
            continue               # src resolved to a daemon; exact only
        cands = [(key, s) for key, s in serve_by_peers.items()
                 if key not in used_serves and key[1] == e["dst_peer"]]
        if len({s["src"] for _k, s in cands}) == 1:
            key, s = cands[0]
            used_serves.add(key)
            e["src"] = s["src"]
            _attach(e, s)

    # the distribution TREE: each node hangs off the src that delivered
    # most of its bytes (the DAG stays in `edges`; the tree is the story)
    nodes = ({e["src"] for e in edges.values()}
             | {e["dst"] for e in edges.values()})
    tree: dict[str, str] = {}
    for dst in {e["dst"] for e in edges.values()}:
        best = max((e for e in edges.values() if e["dst"] == dst),
                   key=lambda e: e["bytes"])
        tree[dst] = best["src"]

    depth_memo: dict[str, int] = {ORIGIN: 0}

    def depth_of(node: str, seen: frozenset = frozenset()) -> int:
        if node in depth_memo:
            return depth_memo[node]
        if node in seen:        # swarm cross-serve cycle: cut here
            return 1
        parent = tree.get(node)
        # a node that only serves (pre-seeded / restarted seed) is a
        # root holder: depth 1, same as a back-sourcing daemon
        d = 1 if parent is None else depth_of(parent, seen | {node}) + 1
        depth_memo[node] = d
        return d

    depth = max((depth_of(n) for n in nodes), default=0)

    # relay view: the cut-through sub-tree — how deep the pipelined
    # chains ran and what each hop added in first-byte latency (the
    # per-hop tax a relay chain pays instead of a full store-and-forward
    # piece time)
    relay = None
    relay_edges = [e for e in edges.values() if e.get("relayed")]
    if relay_edges:
        ekey = {(e["src"], e["dst"]): e for e in edges.values()}
        rdepth_memo: dict[str, int] = {}

        def relay_depth_of(node: str, seen: frozenset = frozenset()) -> int:
            """Consecutive relayed tree edges above ``node``."""
            if node in rdepth_memo:
                return rdepth_memo[node]
            if node in seen:
                return 0
            parent = tree.get(node)
            e = ekey.get((parent, node)) if parent is not None else None
            d = (relay_depth_of(parent, seen | {node}) + 1
                 if e is not None and e.get("relayed") else 0)
            rdepth_memo[node] = d
            return d

        relay = {
            "edges": len(relay_edges),
            "pieces": sum(e.get("relayed_pieces", 0) for e in relay_edges),
            "depth": max((relay_depth_of(n) for n in nodes), default=0),
            "per_hop_added_ms": _pctl(
                [e["ttfb_ms"] / max(e["pieces"], 1)
                 for e in relay_edges], 0.5),
        }

    # seed uplink: the heaviest server and what it sustained. The serve
    # journal's rate is preferred, but only over the bytes it actually
    # covered — a node with one confirmed and one unconfirmed edge must
    # not have ALL its bytes divided by the confirmed edge's serve time
    served: dict[str, dict] = {}
    for e in edges.values():
        if e["src"] == ORIGIN:
            continue
        sv = served.setdefault(e["src"], {"bytes": 0, "wire_ms": 0.0,
                                          "serve_ms": 0.0,
                                          "serve_bytes": 0})
        sv["bytes"] += e["bytes"]
        sv["wire_ms"] += e["wire_ms"]
        if e.get("serve_ms"):
            sv["serve_ms"] += e["serve_ms"]
            sv["serve_bytes"] += e["bytes"]
    p2p_bytes = sum(sv["bytes"] for sv in served.values())
    seed_uplink = None
    if served:
        top = max(served, key=lambda n: served[n]["bytes"])
        sv = served[top]
        if sv["serve_ms"] > 0:
            rate = sv["serve_bytes"] / (sv["serve_ms"] / 1e3)
        elif sv["wire_ms"] > 0:
            rate = sv["bytes"] / (sv["wire_ms"] / 1e3)
        else:
            rate = 0.0
        seed_uplink = {
            "node": top, "bytes": sv["bytes"],
            "share": round(sv["bytes"] / p2p_bytes, 4) if p2p_bytes else 0.0,
            "est_bandwidth_bps": round(rate)}

    # bottleneck: slowest edge that carried a substantial share
    bottleneck = None
    floor = max(1, int(content * SUBSTANTIAL_EDGE_SHARE))
    substantial = [e for e in edges.values()
                   if e["bytes"] >= floor and e["bandwidth_bps"] > 0]
    if substantial:
        worst = min(substantial, key=lambda e: e["bandwidth_bps"])
        med = _pctl([e["bandwidth_bps"] for e in substantial], 0.5)
        bottleneck = {
            "src": worst["src"], "dst": worst["dst"],
            "bytes": worst["bytes"],
            "bandwidth_bps": worst["bandwidth_bps"],
            "median_bps": med,
            "straggler": (len(substantial) >= 3 and med > 0
                          and worst["bandwidth_bps"]
                          * BOTTLENECK_FACTOR < med)}

    if origin_bytes == 0 and placed_bytes > 0:
        # dedupe-served: the pod moved nothing across the origin uplink
        # because the bytes were already held (content store placements /
        # warm restart) — 0.0 with this note is the HEALTHY reading, not
        # a blind observation window
        amplification, amp_note = 0.0, "healthy-warm: dedupe-served " \
            "from the content store"
    elif origin_bytes == 0 and content > 0:
        amplification, amp_note = 1.0, "seeded before observation"
    else:
        amplification = (round(origin_bytes / content, 4) if content
                         else 0.0)
        amp_note = ""
    makespan_ms = (round((max(ends) - min(starts)) * 1000.0, 3)
                   if starts and ends else 0.0)
    cross_pod_bytes = sum(e["bytes"] for e in edges.values()
                          if e.get("cross_pod"))
    return {
        "task_id": task_id,
        "content_length": content,
        "daemons": downloaders,
        "complete": complete,
        "makespan_ms": makespan_ms,
        "depth": depth,
        "origin_bytes": origin_bytes,
        "placed_bytes": placed_bytes,
        "cross_pod_bytes": cross_pod_bytes,
        "amplification": amplification,
        "amplification_note": amp_note,
        "edges": sorted(edges.values(),
                        key=lambda e: (e["src"], e["dst"])),
        "tree": tree,
        "relay": relay,
        "bottleneck": bottleneck,
        "seed_uplink": seed_uplink,
        "slo_breaches": slo,
        "rungs": rungs,
        "shards": ({"ready": shards_ready, "total": shards_total,
                    "tree_bytes": shard_tree_bytes,
                    "swap_bytes": shard_swap_bytes,
                    "fallbacks": shard_fallbacks}
                   if shards_total else None),
    }


def aggregate(snapshots: list[dict]) -> dict:
    """The pod report: per-task tree/edge/makespan aggregation plus a
    pod-level breach list (the CI-gate surface — `dfdiag --pod` exits
    non-zero when it is non-empty) and a one-paragraph verdict."""
    unreachable = {s["addr"]: s["error"] for s in snapshots if "error" in s}
    by_task: dict[str, list[tuple[str, dict]]] = {}
    daemons_detail: dict[str, dict] = {}
    # addr -> pod id: from a bench snapshot's own label, else the
    # daemon's /debug/pex host block — the per-tier edge marks' source
    pods: dict[str, str] = {}
    for s in snapshots:
        pod = (s.get("pod")
               or ((s.get("pex") or {}).get("host") or {}).get("pod") or "")
        if pod:
            pods[s["addr"]] = pod
        for tid, flight in (s.get("flights") or {}).items():
            by_task.setdefault(tid, []).append((s["addr"], flight))
        if "error" in s:
            continue
        # the per-daemon health/pex/verdict halves of the snapshot,
        # compacted: a stalled loop, empty gossip view, or shunned
        # parent explains a bad tree
        health = s.get("health") or {}
        pex = s.get("pex") or {}
        verdicts = s.get("verdicts") or {}
        vparents = verdicts.get("parents") or {}
        daemons_detail[s["addr"]] = {
            "pod": pods.get(s["addr"], ""),
            "health_status": health.get("status", ""),
            "loop_max_lag_s": (health.get("loop") or {}).get(
                "max_lag_s", 0.0),
            "pex_peers": len(pex.get("peers") or []),
            "flight_index": s.get("flight_index") or {},
            "self_quarantined": bool(verdicts.get("self_quarantined")),
            "shunned": sorted(a for a, row in vparents.items()
                              if row.get("shunned")),
        }
    tasks = {tid: _aggregate_task(tid, holders, pods=pods)
             for tid, holders in sorted(by_task.items())}

    # quarantine view: who the pod's local verdicts condemn, and whether
    # a condemned address is STILL being offered (present as a holder in
    # some daemon's swarm index — the exact re-poisoning loop the immune
    # system exists to break)
    shunned_by: dict[str, list[str]] = {}
    selfq: list[str] = []
    for addr, d in daemons_detail.items():
        if d["self_quarantined"]:
            selfq.append(addr)
        for bad in d["shunned"]:
            shunned_by.setdefault(bad, []).append(addr)
    still_offered: dict[str, list[str]] = {}
    for s in snapshots:
        if "error" in s:
            continue
        swarm = ((s.get("pex") or {}).get("swarm") or {}).get("tasks") or {}
        holder_addrs = {e.get("addr", "") for entries in swarm.values()
                        for e in entries}
        for bad in shunned_by:
            if bad in holder_addrs:
                still_offered.setdefault(bad, []).append(s["addr"])
    quarantine = {
        "self_quarantined": sorted(selfq),
        "shunned": {bad: sorted(who) for bad, who in
                    sorted(shunned_by.items())},
        "still_offered": {bad: sorted(who) for bad, who in
                          sorted(still_offered.items())},
    }

    breaches: list[str] = []
    for bad, where in sorted(still_offered.items()):
        breaches.append(
            f"poisoner_offered: {bad} is shunned by "
            f"{'/'.join(shunned_by[bad])} on local corrupt verdicts but "
            f"still indexed as a holder on {'/'.join(sorted(where))} — "
            "the pod can be steered back at it")
    for addr, err in sorted(unreachable.items()):
        breaches.append(f"unreachable: {addr} ({err})")
    for addr, d in sorted(daemons_detail.items()):
        if d["health_status"] == "stalled":
            breaches.append(
                f"health: {addr} reports a stalled event loop "
                f"(max lag {d['loop_max_lag_s']:.3f}s)")
    for tid, t in tasks.items():
        short = tid[:12]
        if t["slo_breaches"]:
            blown = ", ".join(f"{stage}x{n}" for stage, n in
                              sorted(t["slo_breaches"].items()))
            breaches.append(f"slo: task {short} blew budgets ({blown})")
        if (t["amplification"] > AMPLIFICATION_BREACH
                and t["origin_bytes"] > 0):
            breaches.append(
                f"amplification: task {short} pulled "
                f"{t['amplification']:.2f}x its content from origin — "
                "the mesh is not carrying the bytes")
        b = t["bottleneck"]
        if b and b.get("straggler"):
            breaches.append(
                f"bottleneck: task {short} edge {b['src']} -> {b['dst']} "
                f"ran at {_fmt_bps(b['bandwidth_bps'])} vs median "
                f"{_fmt_bps(b['median_bps'])} — a straggler edge")
        if t["daemons"] and t["complete"] < t["daemons"]:
            breaches.append(
                f"incomplete: task {short} finished on {t['complete']}/"
                f"{t['daemons']} daemons")

    report = {
        "daemons": [s["addr"] for s in snapshots],
        "daemons_detail": daemons_detail,
        "unreachable": unreachable,
        "tasks": tasks,
        "quarantine": quarantine,
        "breaches": breaches,
    }
    report["verdict"] = pod_verdict(report)
    return report


def bench_summary(task_report: dict) -> dict:
    """The compact per-scenario form dfbench stamps into BENCH_pr6.json:
    the headline pod numbers + per-edge distribution percentiles."""
    bws = [e["bandwidth_bps"] for e in task_report["edges"]
           if e["src"] != ORIGIN and e["bandwidth_bps"] > 0]
    wires = [e["wire_ms"] for e in task_report["edges"]
             if e["src"] != ORIGIN]
    return {
        "makespan_ms": task_report["makespan_ms"],
        "depth": task_report["depth"],
        "amplification": task_report["amplification"],
        "origin_bytes": task_report["origin_bytes"],
        "placed_bytes": task_report.get("placed_bytes", 0),
        "cross_pod_bytes": task_report.get("cross_pod_bytes", 0),
        "edges": len(task_report["edges"]),
        "edge_bandwidth_bps": {"p5": _pctl(bws, 0.05),
                               "p50": _pctl(bws, 0.50),
                               "p95": _pctl(bws, 0.95)},
        "edge_wire_ms": {"p50": _pctl(wires, 0.50),
                         "p95": _pctl(wires, 0.95)},
        "seed_uplink": task_report["seed_uplink"],
        "bottleneck": task_report["bottleneck"],
        "relay": task_report.get("relay"),
    }


# ------------------------------------------------------- records (edges)

def edges_from_summary(task_id: str, dst_peer_id: str, dst_host_id: str,
                       summary: dict) -> list[dict]:
    """``kind=edge`` rows for the trainer's record stream: one per parent
    that served this flight, carrying the observed per-edge bandwidth —
    the label source for a learned parent-quality model (ROADMAP item 1).
    Pure; ``scheduler/records.py`` stamps ``created_at``."""
    rows = []
    for parent, pp in (summary.get("per_parent") or {}).items():
        rows.append({
            "kind": "edge",
            "task_id": task_id,
            "src_peer_id": parent or ORIGIN,
            "dst_peer_id": dst_peer_id,
            "dst_host_id": dst_host_id,
            "bytes": pp.get("bytes", 0),
            "pieces": pp.get("pieces", 0),
            "wire_ms": pp.get("wire_ms", 0.0),
            "bandwidth_bps": pp.get("throughput_bps", 0),
        })
    return rows


# ----------------------------------------------------------------- render

def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def _fmt_bps(n: float) -> str:
    return f"{_fmt_bytes(n)}/s"


def render_pod(report: dict, *, max_edges_per_node: int = 8) -> str:
    """ASCII distribution tree per task, one line per NODE under its
    tree parent with the delivering edge's bytes / estimated bandwidth /
    both-ends confirmation, bottleneck flagged. The walk follows
    ``tree`` (each node rendered exactly once), not the full edge DAG —
    a dense pex swarm where every daemon serves every later joiner has
    combinatorially many DAG paths, and rendering each one would flood
    the terminal at exactly the pod sizes the tool exists for. Cross
    edges beyond the tree are counted per task; ``--json`` carries the
    full DAG. Pure function over an aggregate() report (or a saved
    copy)."""
    out: list[str] = []
    for addr, err in sorted((report.get("unreachable") or {}).items()):
        out.append(f"UNREACHABLE {addr}: {err}")
    for tid, t in (report.get("tasks") or {}).items():
        note = t["amplification_note"]
        amp = (f"{t['amplification']:.2f}"
               + (" (warm)" if note.startswith("healthy-warm")
                  else " (seeded)" if note else ""))
        out.append(
            f"task {tid[:24]}  content={_fmt_bytes(t['content_length'])}  "
            f"daemons={t['complete']}/{t['daemons']} complete  "
            f"makespan={t['makespan_ms']:.0f}ms  depth={t['depth']}  "
            f"amplification={amp}")
        tree = t.get("tree") or {}
        edge_by_key = {(e["src"], e["dst"]): e for e in t["edges"]}
        kids_of: dict[str, list[str]] = {}
        for child, parent in tree.items():
            kids_of.setdefault(parent, []).append(child)
        b = t.get("bottleneck") or {}
        rendered: set[str] = set()

        def walk(node: str, prefix: str) -> None:
            kids = sorted(kids_of.get(node, []),
                          key=lambda d: -edge_by_key[(node, d)]["bytes"])
            shown = kids[:max_edges_per_node]
            for i, dst in enumerate(shown):
                e = edge_by_key[(node, dst)]
                last = i == len(shown) - 1
                tick = "└─ " if last else "├─ "
                mark = ""
                if e.get("cross_pod"):
                    # a pod-crossing (DCN-tier) edge of the two-level
                    # federation tree — healthy only on seed edges
                    mark += "  [dcn]"
                if e.get("relayed"):
                    mark += "  [relay]"
                if e.get("confirmed"):
                    mark += "  [confirmed]"
                if (b and e["src"] == b.get("src")
                        and e["dst"] == b.get("dst")):
                    mark += "  <- bottleneck"
                bw = (f"  {_fmt_bps(e['bandwidth_bps'])}"
                      if e["bandwidth_bps"] else "")
                out.append(
                    f"{prefix}{tick}{dst}  "
                    f"{_fmt_bytes(e['bytes'])}/{e['pieces']}pc{bw}{mark}")
                if dst not in rendered:     # tree-parent cycle guard
                    rendered.add(dst)
                    walk(dst, prefix + ("   " if last else "│  "))
            if len(kids) > len(shown):
                out.append(f"{prefix}└… +{len(kids) - len(shown)} more")
                # the "+N more" line accounts for the truncated children
                # AND their subtrees — without this they would fall into
                # the rootless sweep below and print as phantom cycles
                stack = list(kids[len(shown):])
                while stack:
                    n = stack.pop()
                    if n in rendered:
                        continue
                    rendered.add(n)
                    stack.extend(kids_of.get(n, []))

        all_nodes = set(tree) | set(tree.values())
        roots = [n for n in all_nodes if n not in tree]
        for root in sorted(roots, key=lambda n: (n != ORIGIN, n)):
            out.append(f"  {root}")
            rendered.add(root)
            walk(root, "  ")
        for n in sorted(all_nodes - rendered):
            # a mutual-heaviest-source pair forms a rootless tree cycle:
            # surface the node flat rather than dropping it silently
            out.append(f"  {n}  (in a cross-serve cycle; see --json)")
        cross = len(t["edges"]) - len(tree)
        if cross > 0:
            out.append(f"  (+{cross} cross edge(s) beyond the tree — "
                       "full DAG in --json)")
        rl = t.get("relay")
        if rl:
            out.append(
                f"  relay: {rl['edges']} cut-through edge(s), "
                f"{rl['pieces']}pc streamed mid-landing, chain depth "
                f"{rl['depth']}, ~{rl['per_hop_added_ms']:.1f}ms added "
                "per hop")
        if t.get("cross_pod_bytes"):
            out.append(
                f"  federation: {_fmt_bytes(t['cross_pod_bytes'])} "
                "crossed a pod boundary ([dcn] edges) — healthy when "
                "only pod-seed edges carry it")
        shd = t.get("shards")
        if shd:
            fb = (f", {shd['fallbacks']} tree fallback(s)"
                  if shd.get("fallbacks") else "")
            out.append(
                f"  shards: {shd['ready']}/{shd['total']} ready "
                f"pod-wide ({_fmt_bytes(shd['tree_bytes'])} tree, "
                f"{_fmt_bytes(shd['swap_bytes'])} swapped over ICI{fb})")
        su = t.get("seed_uplink")
        if su:
            out.append(
                f"  seed uplink: {su['node']} served "
                f"{_fmt_bytes(su['bytes'])} at "
                f"~{_fmt_bps(su['est_bandwidth_bps'])} "
                f"({100 * su['share']:.0f}% of p2p bytes)")
    out.append(report.get("verdict") or pod_verdict(report))
    return "\n".join(out)


def pod_verdict(report: dict) -> str:
    """One-paragraph pod attribution: what limited this pod, or 'healthy'."""
    parts: list[str] = []
    tasks = report.get("tasks") or {}
    for tid, t in tasks.items():
        b = t.get("bottleneck")
        if b:
            parts.append(
                f"task {tid[:12]}: bottleneck edge {b['src']} -> "
                f"{b['dst']} at {_fmt_bps(b['bandwidth_bps'])}"
                + (" — a straggler vs the "
                   f"{_fmt_bps(b['median_bps'])} median"
                   if b.get("straggler") else
                   f" (median {_fmt_bps(b['median_bps'])})"))
        if t.get("rungs"):
            trail = ", ".join(f"{r}x{n}" for r, n in
                              sorted(t["rungs"].items()))
            parts.append(f"task {tid[:12]}: served by rungs {trail}")
        if t.get("placed_bytes"):
            # name the dedupe explicitly so "no origin bytes at all"
            # reads as a warm content store, not a blind window
            parts.append(
                f"task {tid[:12]}: {_fmt_bytes(t['placed_bytes'])} "
                "dedupe-served from the content store (healthy-warm)")
    q = report.get("quarantine") or {}
    for addr in q.get("self_quarantined") or []:
        parts.append(f"{addr} has SELF-QUARANTINED (its own storage "
                     "failed re-verification): not advertising, flagged "
                     "to the scheduler")
    for bad, who in (q.get("shunned") or {}).items():
        parts.append(f"{bad} is locally quarantined by {'/'.join(who)} "
                     "on verified corrupt pieces"
                     + (" — AND STILL OFFERED (see breaches)"
                        if bad in (q.get("still_offered") or {}) else ""))
    breaches = report.get("breaches") or []
    if breaches:
        parts.append("BREACH " + "; BREACH ".join(breaches))
    if not parts:
        return "pod verdict: healthy — nothing to attribute."
    return "pod verdict: " + ";\n  ".join(parts) + "."
