"""Well-known directories for a service instance.

Role parity: reference ``pkg/dfpath`` (workdir/cache/log/data/plugins).
Everything is rooted under one workdir so tests can point at a tempdir.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def _default_workdir() -> str:
    return os.environ.get("DF_WORKDIR", os.path.expanduser("~/.dragonfly2-tpu"))


@dataclass
class DFPath:
    workdir: str = field(default_factory=_default_workdir)

    @property
    def data_dir(self) -> str:
        return os.path.join(self.workdir, "data")

    @property
    def cache_dir(self) -> str:
        return os.path.join(self.workdir, "cache")

    @property
    def log_dir(self) -> str:
        return os.path.join(self.workdir, "logs")

    @property
    def run_dir(self) -> str:
        return os.path.join(self.workdir, "run")

    @property
    def plugin_dir(self) -> str:
        return os.path.join(self.workdir, "plugins")

    def ensure(self) -> "DFPath":
        for d in (self.data_dir, self.cache_dir, self.log_dir, self.run_dir, self.plugin_dir):
            os.makedirs(d, exist_ok=True)
        return self

    def daemon_sock(self) -> str:
        return os.path.join(self.run_dir, "daemon.sock")
