"""Piece math: how a content length is cut into pieces.

Behavior parity with the reference's adaptive sizing
(``internal/util/util.go:24-40``): 4 MiB base; for content beyond 200 MiB the
piece size grows ~1 MiB per extra 100 MiB, capped at 15 MiB. Sizes here are
additionally rounded to a 4 MiB multiple when grown so pieces stay aligned for
device transfer (TPU HBM ingest likes large aligned chunks).
"""

from __future__ import annotations

from dataclasses import dataclass

from .unit import MiB

DEFAULT_PIECE_SIZE = 4 * MiB
MAX_PIECE_SIZE = 16 * MiB          # reference caps at 15 MiB; we keep a pow2 cap

# One host->HBM DMA unit. Shared by the DeviceIngest auto-sizer (daemon) and
# the back-source group sizer (piece_manager): ingest shards complete
# progressively — and their transfers overlap the download — only while a
# back-source work-queue group is no larger than one ingest shard, so the two
# sizes must move together.
INGEST_DMA_UNIT_BYTES = 32 * MiB
_GROWTH_STEP_BYTES = 100 * MiB     # grow 1 MiB per 100 MiB beyond the threshold
_GROWTH_THRESHOLD = 200 * MiB


def compute_piece_size(content_length: int) -> int:
    """Adaptive piece size for a task of ``content_length`` bytes."""
    if content_length <= _GROWTH_THRESHOLD:
        return DEFAULT_PIECE_SIZE
    grown = DEFAULT_PIECE_SIZE + ((content_length - _GROWTH_THRESHOLD) // _GROWTH_STEP_BYTES) * MiB
    # round up to 4 MiB multiples: aligned pieces coalesce into clean device shards
    aligned = ((grown + 4 * MiB - 1) // (4 * MiB)) * (4 * MiB)
    return min(aligned, MAX_PIECE_SIZE)


def piece_count(content_length: int, piece_size: int) -> int:
    if content_length <= 0:
        return 0
    return (content_length + piece_size - 1) // piece_size


def piece_range(piece_num: int, piece_size: int, content_length: int) -> tuple[int, int]:
    """(offset, length) of piece ``piece_num``; final piece may be short."""
    off = piece_num * piece_size
    if off >= content_length:
        raise ValueError(f"piece {piece_num} out of range for length {content_length}")
    return off, min(piece_size, content_length - off)


@dataclass(frozen=True)
class Range:
    """A half-open byte range [start, start+length) of a task's content."""

    start: int
    length: int

    @property
    def end(self) -> int:  # exclusive
        return self.start + self.length

    def http_header(self) -> str:
        return f"bytes={self.start}-{self.start + self.length - 1}"


def parse_http_range(header: str, total: int) -> Range:
    """Parse an HTTP Range header value against a known total length.

    Supports "bytes=a-b", "bytes=a-", "bytes=-n" (suffix). Single range only.
    """
    if not header.startswith("bytes="):
        raise ValueError(f"unsupported range unit: {header!r}")
    spec = header[len("bytes="):]
    if "," in spec:
        raise ValueError("multi-range not supported")
    first, _, last = spec.partition("-")
    if first == "":                      # suffix: last N bytes
        if not last.isdigit():
            raise ValueError(f"invalid suffix range: {header!r}")
        n = min(int(last), total)
        if n == 0:
            raise ValueError("zero-length suffix range")
        return Range(total - n, n)
    if not first.isdigit() or (last and not last.isdigit()):
        raise ValueError(f"invalid range: {header!r}")
    start = int(first)
    if start >= total:
        raise ValueError(f"range start {start} beyond total {total}")
    if last == "":
        return Range(start, total - start)
    end = int(last)                      # inclusive per HTTP
    if end < start:
        raise ValueError(f"range end {end} before start {start}")
    return Range(start, min(end + 1, total) - start)
