"""Dynamic config: a cached remote-config fetcher with disk-snapshot fallback
and observer notification.

Role parity: reference ``internal/dynconfig`` (``dynconfig.go:45-136``) plus
the per-service wrappers (``client/config/dynconfig_manager.go``,
``scheduler/config/dynconfig.go``). Services use this to pull cluster config,
scheduler lists, and seed-peer lists from the manager on an interval, keep
working from the last good snapshot when the manager is down, and notify
observers (e.g. the scheduler-address resolver) when data changes.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Any, Awaitable, Callable

log = logging.getLogger("df.core.dynconfig")

Fetcher = Callable[[], Awaitable[dict[str, Any]]]
Observer = Callable[[dict[str, Any]], None]


class Dynconfig:
    def __init__(self, fetch: Fetcher, *, refresh_interval: float = 30.0,
                 snapshot_path: str | None = None):
        self._fetch = fetch
        self._interval = refresh_interval
        self._snapshot_path = snapshot_path
        self._data: dict[str, Any] | None = None
        self._observers: list[Observer] = []
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()

    def register(self, observer: Observer) -> None:
        self._observers.append(observer)
        if self._data is not None:
            observer(self._data)

    async def get(self) -> dict[str, Any]:
        if self._data is None:
            await self.refresh()
        if self._data is None:
            raise RuntimeError("dynconfig: no data and no snapshot")
        return self._data

    async def refresh(self) -> None:
        try:
            data = await self._fetch()
        except Exception as exc:
            if self._data is None:
                loaded = self._load_snapshot()
                if loaded is not None:
                    log.warning("dynconfig fetch failed (%s); using disk snapshot", exc)
                    self._set(loaded, persist=False)
                    return
            log.warning("dynconfig fetch failed: %s (keeping cached data)", exc)
            return
        if data != self._data:
            self._set(data, persist=True)

    def _set(self, data: dict[str, Any], persist: bool) -> None:
        self._data = data
        if persist and self._snapshot_path:
            try:
                tmp = self._snapshot_path + ".tmp"
                # dflint: disable=DF001 — KB-scale config snapshot on the minutes-cadence refresh tick
                with open(tmp, "w") as f:
                    json.dump(data, f)
                # dflint: disable=DF001 — atomic rename, metadata syscall
                os.replace(tmp, self._snapshot_path)
            except OSError as exc:  # snapshot is best-effort
                log.warning("dynconfig snapshot write failed: %s", exc)
        for ob in self._observers:
            try:
                ob(data)
            except Exception:
                log.exception("dynconfig observer failed")

    def _load_snapshot(self) -> dict[str, Any] | None:
        # dflint: disable=DF001 — one stat on the manager-unreachable fallback path
        if not self._snapshot_path or not os.path.exists(self._snapshot_path):
            return None
        try:
            # dflint: disable=DF001 — KB-scale config snapshot, read only when the manager is away
            with open(self._snapshot_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    async def serve(self) -> None:
        self._stopped.clear()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                await asyncio.wait_for(self._stopped.wait(), timeout=self._interval)
                return
            except asyncio.TimeoutError:
                await self.refresh()

    async def stop(self) -> None:
        self._stopped.set()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
