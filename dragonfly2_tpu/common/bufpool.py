"""Piece-buffer pool: recycles the 4-16 MiB download buffers.

Role parity: the reference's Go client leans on the runtime allocator +
``sync.Pool``; CPython's allocator hands multi-MiB bytearrays straight to
mmap/munmap, so a saturated fan-out paid a page-fault storm per piece:
every downloaded piece/span allocated a fresh bytearray
(piece_downloader._read_body), used it once, and dropped it. At 4 workers
x 4-16 MiB that is hundreds of MB/s of allocate-touch-free churn on the
one core the daemon owns.

Contract (the reuse-safety rules the pool's consumers live by):

* ``acquire(size)`` returns a bytearray of EXACTLY ``size`` bytes, possibly
  dirty — callers must overwrite every byte they later read (the
  downloader's short/long-read checks already guarantee a full fill).
* ``release(buf)`` parks the buffer for reuse. The caller promises that no
  consumer still references its memory: storage writes have returned and
  the HBM sink's staging memcpy (``DeviceIngest.write``) has completed —
  both are synchronous-before-release in the landing path by construction.
* A buffer released while a ``memoryview`` over it is still exported is
  NOT recycled: release probes with a resize (append+pop), which raises
  ``BufferError`` iff exports exist, and such buffers are discarded
  (counted ``df_bufpool_discards_total{reason="exported"}``) — a leaked
  view can therefore never observe another download's bytes.

Buffers are keyed by exact size (piece geometry is uniform per task, so
exact-size buckets hit ~always); the pool is bounded by total parked bytes
and per-size depth, and is thread-safe (release may run from executor
threads).
"""

from __future__ import annotations

import threading

from .metrics import REGISTRY

_acquires = REGISTRY.counter(
    "df_bufpool_acquires_total", "piece-buffer pool acquires", ("result",))
_discards = REGISTRY.counter(
    "df_bufpool_discards_total",
    "piece buffers dropped at release instead of pooled", ("reason",))
_pooled = REGISTRY.gauge(
    "df_bufpool_bytes", "bytes currently parked in the piece-buffer pool")


class BufferPool:
    def __init__(self, *, max_bytes: int = 256 << 20,
                 max_per_size: int = 16):
        self.max_bytes = max_bytes
        self.max_per_size = max_per_size
        self._lock = threading.Lock()
        self._free: dict[int, list[bytearray]] = {}
        self._bytes = 0

    def acquire(self, size: int) -> bytearray:
        """A buffer of exactly ``size`` bytes; contents undefined."""
        if size <= 0:
            return bytearray(0)
        with self._lock:
            bucket = self._free.get(size)
            if bucket:
                buf = bucket.pop()
                self._bytes -= size
                _pooled.set(self._bytes)
                _acquires.labels("hit").inc()
                return buf
        _acquires.labels("miss").inc()
        return bytearray(size)

    def release(self, buf) -> None:
        """Park ``buf`` for reuse (see the module contract). Anything that
        is not a recyclable bytearray — wrong type, zero-size, still
        exported to a memoryview — is silently dropped."""
        if not isinstance(buf, bytearray) or len(buf) == 0:
            return
        try:
            # export probe: resizing a bytearray with live memoryview
            # exports raises BufferError — exactly the case where pooling
            # would let a stale view read the NEXT download's bytes
            buf.append(0)
            buf.pop()
        except BufferError:
            _discards.labels("exported").inc()
            return
        size = len(buf)
        with self._lock:
            bucket = self._free.setdefault(size, [])
            if (self._bytes + size > self.max_bytes
                    or len(bucket) >= self.max_per_size):
                _discards.labels("full").inc()
                return
            bucket.append(buf)
            self._bytes += size
            _pooled.set(self._bytes)

    def clear(self) -> None:
        with self._lock:
            self._free.clear()
            self._bytes = 0
            _pooled.set(0)

    def pooled_bytes(self) -> int:
        with self._lock:
            return self._bytes


# process-wide pool, shared by every downloader the way REGISTRY is shared
POOL = BufferPool()
