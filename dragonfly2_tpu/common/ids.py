"""Content-addressed identifiers for tasks, peers, and hosts.

Role parity: reference ``pkg/idgen`` (``task_id.go:37-93``, ``peer_id.go``,
``host_id.go``). A *task* is identified by what it fetches — sha256 over the
normalized URL plus the download-relevant metadata (filtered query params,
digest, tag, application, range) — so any peer asking for the same bytes maps
to the same task id and can join the same P2P swarm.
"""

from __future__ import annotations

import hashlib
import time
import uuid
from urllib.parse import urlsplit, urlunsplit, parse_qsl, urlencode


def _filtered_url(url: str, filtered_query_params: list[str] | None) -> str:
    """Normalize a URL, dropping query params that don't change the content
    (e.g. signatures, expiry timestamps on presigned URLs)."""
    parts = urlsplit(url)
    query = parse_qsl(parts.query, keep_blank_values=True)
    if filtered_query_params:
        drop = {p.lower() for p in filtered_query_params}
        query = [(k, v) for k, v in query if k.lower() not in drop]
    query.sort()
    return urlunsplit((parts.scheme.lower(), parts.netloc, parts.path,
                       urlencode(query), ""))


def task_id(url: str, *, tag: str = "", application: str = "",
            digest: str = "", piece_range: str = "",
            filtered_query_params: list[str] | None = None) -> str:
    """Content-addressed task id (hex sha256)."""
    h = hashlib.sha256()
    # dflint: disable=DF001 — id hashing covers URL-scale strings (≤KB); an executor hop per task_id would cost more than the digest
    h.update(_filtered_url(url, filtered_query_params).encode())
    for part in (tag, application, digest, piece_range):
        # dflint: disable=DF001 — see above: URL-scale id strings
        h.update(b"\x00")
        # dflint: disable=DF001 — see above: URL-scale id strings
        h.update(part.encode())
    return h.hexdigest()


def parent_task_id(url: str, *, tag: str = "", application: str = "",
                   digest: str = "",
                   filtered_query_params: list[str] | None = None) -> str:
    """Task id of the whole-file parent of a ranged sub-task (range dropped).

    Ranged requests store into a sub-task that shares the parent task's file
    (reference ``storage/local_storage_subtask.go``): the parent id is the key
    both sides agree on.
    """
    return task_id(url, tag=tag, application=application, digest=digest,
                   filtered_query_params=filtered_query_params)


def peer_id(hostname: str, ip: str, *, seed: bool = False) -> str:
    """Unique-per-process peer id: host identity + random suffix."""
    kind = "seed" if seed else "peer"
    return f"{ip}-{hostname}-{uuid.uuid4().hex[:16]}-{kind}"


def host_id(hostname: str, ip: str, port: int = 0) -> str:
    """Stable host id. One daemon process == one host."""
    if port:
        return f"{hostname}-{ip}-{port}"
    return f"{hostname}-{ip}"


def must_new_id() -> str:
    """Opaque unique id (jobs, streams)."""
    return f"{int(time.time() * 1000):x}-{uuid.uuid4().hex[:12]}"
