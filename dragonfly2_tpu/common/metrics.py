"""Minimal prometheus-style metrics registry with text exposition.

Role parity: the reference's prometheus counters/gauges/histograms in
``client/daemon/metrics``, ``scheduler/metrics``, ``manager/metrics``,
``trainer/metrics``. Exposition format is Prometheus text 0.0.4 so a real
scraper can be pointed at the daemon/scheduler metrics ports.
"""

from __future__ import annotations

import threading
from typing import Iterable


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple[str, ...], float] = {}

    def labels(self, *labels: str) -> "_CounterChild":
        if len(labels) != len(self.label_names):
            raise ValueError(f"{self.name}: want {len(self.label_names)} labels")
        return _CounterChild(self, tuple(labels))

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def _samples(self) -> Iterable[tuple[tuple[str, ...], str, float]]:
        for k, v in list(self._values.items()):
            yield k, "", v


class _CounterChild:
    def __init__(self, parent: Counter, labels: tuple[str, ...]):
        self._p, self._l = parent, labels

    def inc(self, amount: float = 1.0) -> None:
        with self._p._lock:
            self._p._values[self._l] = self._p._values.get(self._l, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple[str, ...], float] = {}

    def labels(self, *labels: str) -> "_GaugeChild":
        if len(labels) != len(self.label_names):
            raise ValueError(f"{self.name}: want {len(self.label_names)} labels")
        return _GaugeChild(self, tuple(labels))

    def set(self, v: float) -> None:
        self.labels().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().inc(-amount)

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def _samples(self) -> Iterable[tuple[tuple[str, ...], str, float]]:
        for k, v in list(self._values.items()):
            yield k, "", v


class _GaugeChild:
    def __init__(self, parent: Gauge, labels: tuple[str, ...]):
        self._p, self._l = parent, labels

    def set(self, v: float) -> None:
        with self._p._lock:
            self._p._values[self._l] = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._p._lock:
            self._p._values[self._l] = self._p._values.get(self._l, 0.0) + amount


_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Size-shaped preset for piece/transfer byte histograms: the latency
# default above tops out at 60 — useless for values in the MiB range.
# Spans a 4 KiB ranged read to a 1 GiB whole-file span, log-spaced around
# the 4-16 MiB piece sizes the fabric actually moves.
BYTES_BUCKETS = (4096.0, 65536.0, 262144.0, float(1 << 20), float(4 << 20),
                 float(8 << 20), float(16 << 20), float(64 << 20),
                 float(256 << 20), float(1 << 30))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        # labels -> (bucket_counts, sum, count)
        self._values: dict[tuple[str, ...], tuple[list[int], float, int]] = {}

    def labels(self, *labels: str) -> "_HistChild":
        if len(labels) != len(self.label_names):
            raise ValueError(f"{self.name}: want {len(self.label_names)} labels")
        return _HistChild(self, tuple(labels))

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def snapshot(self, *labels: str) -> tuple[list[int], float, int]:
        return self._values.get(tuple(labels), ([0] * len(self.buckets), 0.0, 0))

    def _samples(self) -> Iterable[tuple[tuple[str, ...], str, float]]:
        for k, (counts, total, n) in list(self._values.items()):
            acc = 0
            for b, c in zip(self.buckets, counts):
                acc += c
                yield k + (str(b),), "_bucket", float(acc)
            yield k + ("+Inf",), "_bucket", float(n)
            yield k, "_sum", total
            yield k, "_count", float(n)


class _HistChild:
    def __init__(self, parent: Histogram, labels: tuple[str, ...]):
        self._p, self._l = parent, labels

    def observe(self, v: float) -> None:
        p = self._p
        with p._lock:
            counts, total, n = p._values.get(self._l, ([0] * len(p.buckets), 0.0, 0))
            for i, b in enumerate(p.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            p._values[self._l] = (counts, total + v, n + 1)


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Counter:
        return self._get_or_make(
            Counter, name, labels, lambda: Counter(name, help_, tuple(labels)))

    def gauge(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Gauge:
        return self._get_or_make(
            Gauge, name, labels, lambda: Gauge(name, help_, tuple(labels)))

    def histogram(self, name: str, help_: str = "", labels: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = _DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(
            Histogram, name, labels, lambda: Histogram(name, help_, tuple(labels), buckets))

    def _get_or_make(self, cls, name, labels, factory=None):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory() if factory else cls(name, "", tuple(labels))
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {m.kind}")
            elif m.label_names != tuple(labels):
                raise TypeError(f"metric {name} re-registered with labels "
                                f"{tuple(labels)} != {m.label_names}")
            return m

    def expose(self) -> str:
        """Prometheus text exposition (label values escaped per the format)."""

        def esc(val: str) -> str:
            return val.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

        with self._lock:
            metrics = list(self._metrics.values())
        out: list[str] = []
        for m in metrics:
            out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            extra = ("le",) if isinstance(m, Histogram) else ()
            for label_vals, suffix, v in m._samples():
                names = m.label_names + extra if suffix == "_bucket" else m.label_names
                if names and label_vals:
                    pairs = ",".join(f'{k}="{esc(str(val))}"'
                                     for k, val in zip(names, label_vals))
                    out.append(f"{m.name}{suffix}{{{pairs}}} {v}")
                else:
                    out.append(f"{m.name}{suffix} {v}")
        return "\n".join(out) + "\n"


REGISTRY = Registry()
