"""Control-plane ruling profiler: per-phase timing for scheduler rulings.

Role parity: none in the reference — Dragonfly2 ships no control-plane
profile at all. Every perf headline so far (BENCH_pr5/pr9/pr10/pr13/pr14)
measures the data plane; the scheduler — the single asyncio brain that
will serve a cold herd of 16 pods x 256 daemons — had never been profiled
end to end, and PR 13 found an O(candidates x DAG) walk only by accident.
This module is the measuring instrument that makes the control plane the
benchmarked hot path (ROADMAP item 3): every ``Scheduling`` ruling
(``find``/``refresh``/``preempt``/``shard``) is timed and decomposed into
the pinned PHASES vocabulary, aggregated into per-phase latency
histograms (``df_sched_ruling_seconds{phase}``), rulings/sec, and a
queue-wait vs compute split — read live at ``GET /debug/ctrl``
(scheduler/ctrl_debug.py), rendered by ``dfdiag --ctrl``, and driven at
fleet scale by ``dfbench --ctrl`` (the BENCH_pr16 trajectory point).

Overhead contract (the faultgate idiom): ``ARMED`` is a module-level
boolean and ``phase()``/``ruling()`` return the shared no-op ``_NULL``
context manager when it is down — one attribute load, a falsy test, and
one no-op ``with`` per call site, measured in tier-1 by the
disarmed-overhead microbenchmark (tests/test_phasetimer.py). Hot loops
that cannot afford even that (the per-candidate exclusion checks) hoist
``armed = phasetimer.ARMED`` once per ruling, accumulate a local
``perf_counter`` delta, and hand it in with ``record()``.

Purity contract: the profiler OBSERVES rulings, it never participates in
one — no code path here touches the rng, the candidate ordering, or any
scheduler state, so the armed run's ``schedule_digest`` is byte-identical
to the disarmed one (gated by tests/test_dfbench.py ``TestPr16Ctrl``
against the committed BENCH_pr3 baseline).

Attribution model: phases nest (``dag-walk`` and ``exclusion`` run inside
``filter``, every phase runs inside a ``ruling``). Each frame records its
SELF time — wall elapsed minus the elapsed of its nested children — so
the per-phase histogram columns sum to ~the ruling total instead of
double-counting, and the remainder (``unattributed_ms`` in the snapshot)
is the profiler's own visible overhead plus un-phased ruling code. A
phase that RAISES still closes and attributes its time (the
exception-path test): ``__exit__`` records unconditionally. Concurrent
rulings (one per report stream's asyncio task) each get their own frame
stack via a ``contextvars.ContextVar``, so interleaved awaits can never
cross-charge phases; the aggregate tables are mutated under one lock so
threaded harnesses stay consistent too.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque

from .metrics import REGISTRY

# The pinned phase vocabulary. Every ``phase(...)``/``record(...)`` call
# site must name a member, every member must be fired somewhere in the
# package, and every member must be backticked in docs/OBSERVABILITY.md
# (dflint DF006 phase-vocabulary) — an unregistered phase is an invisible
# histogram label, and an undocumented one is a /debug/ctrl surface
# operators cannot read.
PHASES = (
    "filter",       # filter_candidates: the whole legality pass
    "dag-walk",     # the one descendant sweep feeding the cycle check
    "exclusion",    # quarantine + federation lookups inside the filter
    "score",        # evaluator evaluate()/explain() + the sort
    "relay",        # relay-tree fan-out shaping (_relay_shape)
    "emit",         # decision-ledger row construction + sink call
)

# The ruling kinds ``ruling(...)`` wraps — the control plane's unit of
# work, matching the decision ledger's find/refresh/preempt/shard
# decision kinds. Same closed-vocabulary contract as PHASES.
RULING_KINDS = ("find", "refresh", "preempt", "shard")

# Ruling phases live at us..ms scale — the default request buckets
# (5ms floor) would put every sample in the first bucket.
_CTRL_BUCKETS = (0.000005, 0.00002, 0.00005, 0.0001, 0.00025, 0.0005,
                 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)

_phase_seconds = REGISTRY.histogram(
    "df_sched_ruling_seconds",
    "per-phase self time inside scheduler rulings (the PHASES "
    "vocabulary; self time = wall minus nested phases, so the phases "
    "sum to ~the ruling total)", ("phase",), buckets=_CTRL_BUCKETS)
_ruling_seconds = REGISTRY.histogram(
    "df_ctrl_ruling_seconds",
    "end-to-end scheduler ruling wall time, by ruling kind "
    "(find/refresh/preempt/shard)", ("kind",), buckets=_CTRL_BUCKETS)
_rulings_total = REGISTRY.counter(
    "df_ctrl_rulings_total",
    "scheduler rulings profiled, by ruling kind", ("kind",))
_queue_wait_seconds = REGISTRY.histogram(
    "df_ctrl_queue_wait_seconds",
    "time a ruling request waited before its ruling ran (cold-herd "
    "arrival-to-service in dfbench --ctrl; patience-loop wait in the "
    "live scheduler)", buckets=_CTRL_BUCKETS + (2.5, 10.0))

ARMED = False

_RECENT = 2048          # per-name self-time samples kept for p50/p99
_ENDS = 8192            # ruling end stamps kept for the rulings/sec window
_RATE_WINDOW_S = 60.0

_lock = threading.Lock()
_armed_at = 0.0

# name -> _Agg; rulings keyed by kind, phases by PHASES member
_phases: dict[str, "_Agg"] = {}
_rulings: dict[str, "_Agg"] = {}
_queue_wait: "_Agg | None" = None
_ruling_ends: deque = deque(maxlen=_ENDS)

# per-asyncio-task (and per-thread) frame stack; each frame is a one-slot
# list holding the child-elapsed accumulator, so nested phases charge
# their wall time to the enclosing frame without any global state
_stack: contextvars.ContextVar[list | None] = contextvars.ContextVar(
    "df_phase_stack", default=None)


class _Agg:
    __slots__ = ("count", "total_s", "self_s", "max_s", "recent")

    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0      # wall elapsed (children included)
        self.self_s = 0.0       # wall minus nested children
        self.max_s = 0.0
        self.recent: deque = deque(maxlen=_RECENT)

    def add(self, elapsed: float, self_s: float) -> None:
        self.count += 1
        self.total_s += elapsed
        self.self_s += self_s
        if self_s > self.max_s:
            self.max_s = self_s
        self.recent.append(self_s)

    def row(self) -> dict:
        vals = sorted(self.recent)
        return {
            "count": self.count,
            "total_ms": round(self.total_s * 1000, 4),
            "self_ms": round(self.self_s * 1000, 4),
            "mean_ms": round(self.self_s / self.count * 1000, 4)
            if self.count else 0.0,
            "p50_ms": round(_pctl(vals, 0.50) * 1000, 4),
            "p99_ms": round(_pctl(vals, 0.99) * 1000, 4),
            "max_ms": round(self.max_s * 1000, 4),
        }


def _pctl(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over pre-sorted values (the repo-wide
    rule; kept local so common/ stays free of daemon imports)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class _NullCtx:
    """The disarmed path: one shared instance, no-op enter/exit."""
    __slots__ = ()

    def __enter__(self) -> "_NullCtx":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL = _NullCtx()


class _Frame:
    """One armed phase/ruling context. Exception-safe by construction:
    ``__exit__`` records whether or not the body raised, so a phase that
    blows up still closes and attributes its time."""
    __slots__ = ("name", "table", "t0", "children")

    def __init__(self, name: str, table: dict) -> None:
        self.name = name
        self.table = table
        self.t0 = 0.0
        self.children = [0.0]

    def __enter__(self) -> "_Frame":
        stack = _stack.get()
        if stack is None:
            stack = []
            _stack.set(stack)
        stack.append(self.children)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        elapsed = time.perf_counter() - self.t0
        stack = _stack.get()
        if stack and stack[-1] is self.children:
            stack.pop()
        if stack:
            stack[-1][0] += elapsed
        self_s = max(elapsed - self.children[0], 0.0)
        with _lock:
            agg = self.table.get(self.name)
            if agg is None:
                agg = self.table[self.name] = _Agg()
            agg.add(elapsed, self_s)
            if self.table is _rulings:
                _ruling_ends.append(time.perf_counter())
                _rulings_total.labels(self.name).inc()
                # a ruling's headline number is its WALL time; phases
                # below it report self time
                _ruling_seconds.labels(self.name).observe(elapsed)
            else:
                _phase_seconds.labels(self.name).observe(self_s)
        return False


def phase(name: str):
    """Time one named phase of a ruling. Disarmed: returns the shared
    no-op context. Armed: validates the name against PHASES (a typo'd
    phase must fail loudly, not mint a new histogram label)."""
    if not ARMED:
        return _NULL
    if name not in PHASES:
        raise ValueError(f"unknown phase {name!r} (PHASES={PHASES})")
    return _Frame(name, _phases)


def ruling(kind: str, queue_wait_s: float | None = None):
    """Time one whole ruling (the outermost frame; phases nest inside).
    ``queue_wait_s`` — how long the request waited before this ruling
    ran — feeds the queue-wait vs compute split when the caller knows
    it (dfbench's cold-herd arrival delta, the service's patience
    wait)."""
    if not ARMED:
        return _NULL
    if kind not in RULING_KINDS:
        raise ValueError(
            f"unknown ruling kind {kind!r} (RULING_KINDS={RULING_KINDS})")
    if queue_wait_s is not None:
        note_queue_wait(queue_wait_s)
    return _Frame(kind, _rulings)


def record(name: str, seconds: float) -> None:
    """Hand in a pre-measured phase duration (the hot-loop accumulation
    path: the filter's per-candidate exclusion checks sum a local
    perf_counter delta and record once per ruling). Charges the open
    enclosing frame like a nested phase would."""
    if not ARMED:
        return
    if name not in PHASES:
        raise ValueError(f"unknown phase {name!r} (PHASES={PHASES})")
    stack = _stack.get()
    if stack:
        stack[-1][0] += seconds
    with _lock:
        agg = _phases.get(name)
        if agg is None:
            agg = _phases[name] = _Agg()
        agg.add(seconds, seconds)
        _phase_seconds.labels(name).observe(seconds)


def note_queue_wait(seconds: float) -> None:
    """Record how long a ruling request sat waiting for the scheduler's
    attention before its ruling started (no-op disarmed)."""
    global _queue_wait
    if not ARMED:
        return
    seconds = max(seconds, 0.0)
    with _lock:
        if _queue_wait is None:
            _queue_wait = _Agg()
        _queue_wait.add(seconds, seconds)
        _queue_wait_seconds.observe(seconds)


def arm() -> None:
    """Arm the profiler (aggregates start empty; re-arming resets)."""
    global ARMED, _armed_at
    with _lock:
        _clear_locked()
        _armed_at = time.time()
    ARMED = True


def disarm() -> None:
    """Stop timing; aggregates stay readable (snapshot/ /debug/ctrl)."""
    global ARMED
    ARMED = False


def reset() -> None:
    """Disarm and drop every aggregate (test isolation)."""
    global ARMED
    ARMED = False
    with _lock:
        _clear_locked()


def _clear_locked() -> None:
    global _queue_wait, _armed_at
    _phases.clear()
    _rulings.clear()
    _ruling_ends.clear()
    _queue_wait = None
    _armed_at = 0.0


def snapshot() -> dict:
    """The live profile: rulings/sec, per-kind and per-phase latency,
    queue-wait vs compute. Pure read — /debug/ctrl serves this."""
    with _lock:
        now = time.perf_counter()
        ends = [t for t in _ruling_ends if now - t <= _RATE_WINDOW_S]
        total = sum(a.count for a in _rulings.values())
        compute_s = sum(a.total_s for a in _rulings.values())
        lifetime_s = (time.time() - _armed_at) if _armed_at else 0.0
        phase_rows = {n: _phases[n].row() for n in sorted(_phases)}
        ruling_rows = {k: _rulings[k].row() for k in sorted(_rulings)}
        qw = _queue_wait.row() if _queue_wait is not None else None
        phase_self_s = sum(a.self_s for a in _phases.values())
    return {
        "armed": ARMED,
        "since": _armed_at,
        "rulings": {
            "total": total,
            # two rates: the recent window (what the fleet is doing NOW)
            # and busy-rate (rulings per second of actual ruling compute
            # — the single-brain capacity number dfbench reports)
            "per_sec_60s": round(len(ends) / min(
                max(lifetime_s, 1e-9), _RATE_WINDOW_S), 3)
            if ends else 0.0,
            "per_sec_busy": round(total / compute_s, 1)
            if compute_s > 0 else 0.0,
            "by_kind": ruling_rows,
        },
        "phases": phase_rows,
        "compute_ms": round(compute_s * 1000, 3),
        # ruling wall time not attributed to any phase: profiler
        # overhead + un-phased ruling code; a growing share here means
        # the phase vocabulary no longer covers the hot path
        "unattributed_ms": round(
            max(compute_s - phase_self_s, 0.0) * 1000, 3),
        "queue_wait_ms": qw,
    }
