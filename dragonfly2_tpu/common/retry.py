"""One retry/backoff policy for every control-plane ladder.

Role parity: reference ``pkg/retry`` + the per-client backoff interceptors
(``pkg/rpc/*/client``); before this module the repo smeared the same math
ad-hoc across the rpc client, the piece dispatcher's busy backoff, and the
scheduler's seed retry gate. Everything that retries now shares ONE
jittered-exponential policy object that is:

  * budget-aware   — ``budget_s`` caps total wall-clock across attempts;
  * deadline-aware — a per-call ``deadline_s`` does the same per run, and a
    sleep that would overshoot either is not taken (fail fast instead of
    sleeping into a deadline);
  * hint-honoring  — a ``retry_after_ms`` attribute on the raised error (the
    piece 503 backpressure hint, a faultgate 'error' script) or an HTTP
    ``Retry-After`` header floor the computed backoff.

Deterministic by construction: the clock, sleep, and rng are injectable so
tests drive the whole ladder with a fake clock (tests/test_faults.py).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

from .errors import Code

log = logging.getLogger("df.retry")


def retry_after_s(exc: BaseException) -> float:
    """The error's own backoff hint in seconds: ``retry_after_ms`` (wire
    convention for the upload-slot 503 and faultgate errors) or an HTTP
    ``Retry-After`` header (seconds form) on a ``headers`` mapping."""
    ms = getattr(exc, "retry_after_ms", 0)
    if ms:
        return float(ms) / 1000.0
    headers = getattr(exc, "headers", None)
    if headers:
        try:
            value = headers.get("Retry-After", "")
        except AttributeError:
            return 0.0
        if isinstance(value, str) and value.strip().isdigit():
            return float(value.strip())
    return 0.0


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff with an attempt cap and a time budget."""

    max_attempts: int = 3        # total tries, including the first
    base_s: float = 0.1          # first backoff
    max_s: float = 2.0           # per-sleep cap
    multiplier: float = 2.0
    jitter: float = 0.5          # sleep *= uniform(1-jitter, 1+jitter)
    budget_s: float = 0.0        # total wall budget across attempts; 0 = none

    def backoff_s(self, failures: int,
                  rng: Callable[[], float] = random.random) -> float:
        """Sleep before attempt ``failures + 1`` (failures >= 1)."""
        raw = min(self.max_s,
                  self.base_s * self.multiplier ** max(failures - 1, 0))
        if self.jitter <= 0:
            return raw
        return raw * (1.0 - self.jitter + 2.0 * self.jitter * rng())


# transient-by-default classifier: coded errors whose code says "try again"
_TRANSIENT_CODES = frozenset({int(Code.UNAVAILABLE),
                              int(Code.DEADLINE_EXCEEDED)})


def transient(exc: BaseException) -> bool:
    """Default retryable test: DFError UNAVAILABLE/DEADLINE_EXCEEDED, plain
    transport failures (OSError/TimeoutError), or anything carrying a
    retry-after hint."""
    code = getattr(exc, "code", None)
    try:
        if code is not None and int(code) in _TRANSIENT_CODES:
            return True
    except (TypeError, ValueError):
        pass       # grpc StatusCode and friends aren't int()-able
    if isinstance(exc, (OSError, asyncio.TimeoutError)):
        return True
    return retry_after_s(exc) > 0


class Retrier:
    """Runs an async callable under a RetryPolicy.

    ``clock``/``sleep``/``rng`` are injectable for deterministic tests; the
    defaults are the real monotonic clock, ``asyncio.sleep``, and
    ``random.random``.
    """

    def __init__(self, policy: RetryPolicy, *,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], Awaitable] = asyncio.sleep,
                 rng: Callable[[], float] = random.random):
        self.policy = policy
        self.clock = clock
        self.sleep = sleep
        self.rng = rng

    async def run(self, fn: Callable[[], Awaitable[Any]], *,
                  retryable: Callable[[BaseException], bool] = transient,
                  deadline_s: float | None = None,
                  on_retry: Callable[[int, BaseException, float], None]
                  | None = None) -> Any:
        """Call ``fn`` until it succeeds, attempts run out, or the time
        budget/deadline would be overshot by the next sleep. Raises the
        last exception. ``on_retry(failures, exc, sleep_s)`` fires before
        each sleep."""
        p = self.policy
        start = self.clock()
        budget = p.budget_s or 0.0
        if deadline_s is not None:
            budget = min(budget, deadline_s) if budget else deadline_s
        failures = 0
        while True:
            try:
                return await fn()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - classified below
                failures += 1
                if failures >= p.max_attempts or not retryable(exc):
                    raise
                pause = max(self.policy.backoff_s(failures, self.rng),
                            retry_after_s(exc))
                if budget and (self.clock() - start) + pause > budget:
                    # sleeping would eat the caller's deadline: surface the
                    # failure now so the next ladder rung gets the time
                    raise
                if on_retry is not None:
                    on_retry(failures, exc, pause)
                log.debug("retry %d/%d in %.3fs after %s", failures,
                          p.max_attempts, pause, exc)
                await self.sleep(pause)
