"""Digest parsing and verification.

Role parity: reference ``pkg/digest`` — "algo:hex" strings, verifying readers,
and per-piece hash checks. The hot path (hashing 4-16 MiB pieces) dispatches
to the C++ native library when built (``native/libdfnative.so``), falling back
to hashlib.
"""

from __future__ import annotations

import hashlib
from typing import Iterator

SUPPORTED = ("sha256", "sha512", "sha1", "md5", "crc32c", "blake2b")


_HEX_LEN = {"sha256": 64, "sha512": 128, "sha1": 40, "md5": 32, "crc32c": 8,
            "blake2b": 64}
_HEX_CHARS = set("0123456789abcdef")


def parse(digest: str) -> tuple[str, str]:
    """Split "sha256:abcd..." into (algo, hexvalue); validates algo + hex + length."""
    algo, sep, value = digest.partition(":")
    if not sep or not value:
        raise ValueError(f"invalid digest {digest!r}; want 'algo:hex'")
    algo = algo.lower()
    if algo not in SUPPORTED:
        raise ValueError(f"unsupported digest algorithm {algo!r}")
    value = value.lower()
    if len(value) != _HEX_LEN[algo] or not set(value) <= _HEX_CHARS:
        raise ValueError(f"invalid {algo} digest value {value!r}")
    return algo, value


def hash_bytes(algo: str, data: bytes | memoryview) -> str:
    """Hex digest of ``data`` under ``algo``.

    crc32c (the per-piece default) dispatches to the native library's
    hardware-accelerated path (~4.5 GB/s measured vs ~10 MB/s pure Python);
    sha/md5 stay on hashlib, whose OpenSSL backend outruns portable C++.
    """
    if algo == "crc32c":
        from ..storage import native  # local import: avoid cycle at package init
        out = native.hash_bytes(algo, data)
        if out is not None:
            return out
        return f"{_crc32c_py(bytes(data)):08x}"
    if algo == "blake2b":
        return hashlib.blake2b(data, digest_size=32).hexdigest()
    return hashlib.new(algo, data).hexdigest()


def hash_stream(algo: str, chunks: Iterator[bytes]) -> str:
    if algo == "crc32c":
        from ..storage import native
        acc = 0
        use_native = native.available()
        for c in chunks:
            if use_native:
                acc = native.crc32c_update(c, acc)
            else:
                acc = _crc32c_py(c, acc)
        return f"{acc:08x}"
    if algo == "blake2b":
        h = hashlib.blake2b(digest_size=32)
    else:
        h = hashlib.new(algo)
    for c in chunks:
        h.update(c)
    return h.hexdigest()


def verify(digest: str, data: bytes | memoryview) -> bool:
    algo, want = parse(digest)
    return hash_bytes(algo, data) == want


def for_bytes(algo: str, data: bytes | memoryview) -> str:
    return f"{algo}:{hash_bytes(algo, data)}"


# -- pure-python crc32c (Castagnoli), fallback when native lib is absent -----

_CRC32C_POLY = 0x82F63B78
_crc32c_table: list[int] | None = None


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    global _crc32c_table
    if _crc32c_table is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
            tbl.append(c)
        _crc32c_table = tbl
    c = crc ^ 0xFFFFFFFF
    tbl = _crc32c_table
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF
