"""Digest parsing and verification.

Role parity: reference ``pkg/digest`` — "algo:hex" strings, verifying readers,
and per-piece hash checks. The hot path (hashing 4-16 MiB pieces) dispatches
to the C++ native library when built (``native/libdfnative.so``), falling back
to hashlib.
"""

from __future__ import annotations

import hashlib
import zlib
from typing import Iterator

SUPPORTED = ("sha256", "sha512", "sha1", "md5", "crc32c", "crc32", "blake2b")


_HEX_LEN = {"sha256": 64, "sha512": 128, "sha1": 40, "md5": 32, "crc32c": 8,
            "crc32": 8, "blake2b": 64}
_HEX_CHARS = set("0123456789abcdef")


def parse(digest: str) -> tuple[str, str]:
    """Split "sha256:abcd..." into (algo, hexvalue); validates algo + hex + length."""
    algo, sep, value = digest.partition(":")
    if not sep or not value:
        raise ValueError(f"invalid digest {digest!r}; want 'algo:hex'")
    algo = algo.lower()
    if algo not in SUPPORTED:
        raise ValueError(f"unsupported digest algorithm {algo!r}")
    value = value.lower()
    if len(value) != _HEX_LEN[algo] or not set(value) <= _HEX_CHARS:
        raise ValueError(f"invalid {algo} digest value {value!r}")
    return algo, value


def hash_bytes(algo: str, data: bytes | memoryview) -> str:
    """Hex digest of ``data`` under ``algo``.

    crc32c (the per-piece default) dispatches to the native library's
    hardware-accelerated path (~4.5 GB/s measured vs ~10 MB/s pure Python);
    sha/md5 stay on hashlib, whose OpenSSL backend outruns portable C++.
    """
    if algo == "crc32c":
        from ..storage import native  # local import: avoid cycle at package init
        out = native.hash_bytes(algo, data)
        if out is not None:
            return out
        return f"{_crc32c_py(data):08x}"
    if algo == "crc32":
        # zlib.crc32 takes any buffer — a bytes() conversion here would
        # re-copy every piece on hosts without the native lib
        return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"
    if algo == "blake2b":
        return hashlib.blake2b(data, digest_size=32).hexdigest()
    return hashlib.new(algo, data).hexdigest()


def preferred_piece_algo() -> str:
    """Per-piece digest default: hardware crc32c when the native library is
    built, zlib's C crc32 otherwise — never the pure-Python crc32c loop
    (~10 MB/s, visible in end-to-end throughput)."""
    from ..storage import native
    return "crc32c" if native.load() is not None else "crc32"


class Hasher:
    """Incremental hasher covering all SUPPORTED algos (incl. crc32c)."""

    def __init__(self, algo: str):
        self.algo = algo
        self._crc: int | None = None
        self._h = None
        if algo == "crc32c":
            self._crc = 0
            from ..storage import native
            self._native = native if native.available() else None
        elif algo == "crc32":
            self._crc = 0
            self._native = None
            self._zlib = True
        elif algo == "blake2b":
            self._h = hashlib.blake2b(digest_size=32)
        else:
            self._h = hashlib.new(algo)

    def update(self, data: bytes) -> None:
        if self._crc is not None:
            if getattr(self, "_zlib", False):
                self._crc = zlib.crc32(data, self._crc) & 0xFFFFFFFF
            elif self._native is not None:
                self._crc = self._native.crc32c_update(data, self._crc)
            else:
                self._crc = _crc32c_py(data, self._crc)
        else:
            self._h.update(data)

    def hexdigest(self) -> str:
        if self._crc is not None:
            return f"{self._crc:08x}"
        return self._h.hexdigest()


def hash_stream(algo: str, chunks: Iterator[bytes]) -> str:
    h = Hasher(algo)
    for c in chunks:
        h.update(c)
    return h.hexdigest()


def verify(digest: str, data: bytes | memoryview) -> bool:
    algo, want = parse(digest)
    return hash_bytes(algo, data) == want


def for_bytes(algo: str, data: bytes | memoryview) -> str:
    return f"{algo}:{hash_bytes(algo, data)}"


# -- pure-python crc32c (Castagnoli), fallback when native lib is absent -----

_CRC32C_POLY = 0x82F63B78
_crc32c_table: list[int] | None = None


def _crc32c_py(data, crc: int = 0) -> int:
    global _crc32c_table
    if _crc32c_table is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
            tbl.append(c)
        _crc32c_table = tbl
    c = crc ^ 0xFFFFFFFF
    tbl = _crc32c_table
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF
