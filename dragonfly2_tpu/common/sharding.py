"""Sharded-task math: manifest geometry, piece mapping, readiness, affinity.

Role parity: none in the reference — Dragonfly2 moves opaque files. The
production scenario behind this module (ROADMAP item 3) is model rollout:
every TPU host in a serving fleet simultaneously needs *its own* named
array shards of a multi-GB checkpoint, and the interesting metric is not
"file landed" but "shard became a ready array in HBM". This module holds
the pure arithmetic every layer shares:

  * a shard is a NAMED contiguous byte range of the task's content
    (``idl.ShardInfo``: name + [start, start+size) + dtype/shape + an
    optional per-shard digest). Integrity rides the existing per-piece
    digest machinery — every piece of a shard verifies at landing, so a
    shard is trustworthy the moment its last piece lands;
  * ``pieces_for_shards`` maps a requested shard subset onto the piece
    numbers that cover it (shard boundaries need not align to pieces: a
    boundary mid-piece pulls the whole piece, which may complete two
    shards at once);
  * ``ShardTracker`` watches verified byte spans land (any order, any
    overlap) and answers "which shards just became fully covered" — the
    conductor drives ``shard_ready`` flight events and the incremental
    HBM handoff off its answers;
  * ``split_affinity`` is the deterministic disjoint-assignment rule the
    scheduler's shard-affinity arm and dfbench share: rendezvous hashing
    (highest-random-weight) of shard names over the co-located replica
    set, so every shard has exactly one tree-fetch owner among the
    replicas that requested it, assignments move minimally when the
    membership churns, and two schedulers (or a replay) rule
    identically with no shared state.

Everything here is synchronous, allocation-light, and wall-clock-free —
it runs on daemon landing paths and inside dfbench's virtual-clock sim.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Sequence


def parse_shard_names(csv: str) -> list[str]:
    """``UrlMeta.shards`` wire form ("a,b,c") -> names, order kept,
    duplicates dropped."""
    out: list[str] = []
    for name in csv.split(","):
        name = name.strip()
        if name and name not in out:
            out.append(name)
    return out


def validate_manifest(shards: Sequence, content_length: int = -1) -> None:
    """Raise ValueError on a malformed manifest: empty/duplicate names,
    non-positive sizes, overlapping ranges, or ranges beyond the content
    (when its length is known). Gaps are LEGAL — a manifest may name only
    the tensors worth landing (optimizer state can stay unnamed)."""
    seen: set[str] = set()
    spans: list[tuple[int, int, str]] = []
    for s in shards:
        if not s.name:
            raise ValueError("shard with empty name")
        if s.name in seen:
            raise ValueError(f"duplicate shard name {s.name!r}")
        seen.add(s.name)
        if s.range_size <= 0:
            raise ValueError(f"shard {s.name}: non-positive size")
        if s.range_start < 0:
            raise ValueError(f"shard {s.name}: negative start")
        if content_length >= 0 and s.range_start + s.range_size > content_length:
            raise ValueError(
                f"shard {s.name}: [{s.range_start}, "
                f"{s.range_start + s.range_size}) beyond content "
                f"{content_length}")
        spans.append((s.range_start, s.range_start + s.range_size, s.name))
    spans.sort()
    for (_, e0, n0), (s1, _, n1) in zip(spans, spans[1:]):
        if s1 < e0:
            raise ValueError(f"shards {n0} and {n1} overlap")


def pieces_for_shards(shards: Iterable, piece_size: int,
                      total_pieces: int) -> set[int]:
    """Piece numbers covering the given shards. A shard boundary mid-piece
    claims the whole piece (the piece is the transfer/verify unit)."""
    if piece_size <= 0:
        raise ValueError("piece_size must be known")
    out: set[int] = set()
    for s in shards:
        first = s.range_start // piece_size
        last = (s.range_start + s.range_size - 1) // piece_size
        if total_pieces >= 0:
            last = min(last, total_pieces - 1)
        out.update(range(first, last + 1))
    return out


def split_affinity(shard_names: Sequence[str],
                   members: Iterable[str]) -> dict[str, str]:
    """Deterministic BALANCED disjoint assignment: shard name -> owner.

    Bounded-load rendezvous: every member scores every shard via
    sha256(member | shard); shards are processed in a deterministic hash
    order and each goes to its highest-scoring member still under the
    per-member cap of ceil(shards / members). No coordination, no state
    — any party holding the same (shards, members) computes the same
    split, and membership churn moves only a ~1/n slice. The cap is the
    point: naked rendezvous is uniform in expectation but a 6-shard /
    2-replica rollout can land every shard on one host (observed live),
    which re-raises exactly the tree fetch the affinity exists to
    split — bounded load makes the spread exact, not probabilistic.
    Independent of input order (the processing order is hash-derived)."""
    pool = sorted(set(members))
    if not pool:
        return {}
    names = list(dict.fromkeys(shard_names))
    cap = -(-len(names) // len(pool))
    load = {m: 0 for m in pool}
    out: dict[str, str] = {}

    def score(m: str, n: str) -> bytes:
        return hashlib.sha256(f"{m}|{n}".encode()).digest()

    for name in sorted(names,
                       key=lambda n: hashlib.sha256(n.encode()).digest()):
        ranked = sorted(pool, key=lambda m: score(m, name), reverse=True)
        owner = next((m for m in ranked if load[m] < cap), ranked[0])
        load[owner] += 1
        out[name] = owner
    return out


class _Coverage:
    """Merged [start, end) interval set — the same arithmetic as
    ``tpu.hbm_sink.CoverageMap`` without its thread lock (the tracker
    runs on the daemon's event loop / the bench's single thread)."""

    __slots__ = ("_ranges",)

    def __init__(self) -> None:
        self._ranges: list[tuple[int, int]] = []

    def add(self, start: int, end: int) -> None:
        if start >= end:
            return
        lo, hi = start, end
        out: list[tuple[int, int]] = []
        for s, e in self._ranges:
            if e < lo or s > hi:
                out.append((s, e))
            else:
                lo, hi = min(lo, s), max(hi, e)
        out.append((lo, hi))
        out.sort()
        self._ranges = out

    def covered(self) -> int:
        return sum(e - s for s, e in self._ranges)


class ShardTracker:
    """Watches verified byte spans land; answers which shards completed.

    ``shards`` are ShardInfo-likes (name/range_start/range_size) — the
    manifest order is preserved in ``index_of``. ``requested`` narrows
    tracking to a subset (None = every shard). Spans may arrive in any
    order, overlap, duplicate, or straddle shard boundaries; a shard is
    READY exactly once, when its byte range is fully covered."""

    def __init__(self, shards: Sequence, requested: Sequence[str] | None = None):
        want = set(requested) if requested is not None else None
        self.shards = [s for s in shards
                       if want is None or s.name in want]
        if requested is not None:
            missing = set(requested) - {s.name for s in shards}
            if missing:
                raise ValueError(
                    f"requested shards not in manifest: {sorted(missing)}")
        # sorted by range for the overlap scan
        self._order = sorted(self.shards, key=lambda s: s.range_start)
        self._cov: dict[str, _Coverage] = {s.name: _Coverage()
                                           for s in self.shards}
        self.ready: dict[str, float] = {}       # name -> t of completion

    @property
    def total(self) -> int:
        return len(self.shards)

    def pending(self) -> list[str]:
        return [s.name for s in self.shards if s.name not in self.ready]

    def requested_bytes(self) -> int:
        return sum(s.range_size for s in self.shards)

    def shard_bytes_in(self, start: int, end: int) -> int:
        """Bytes of [start, end) that fall inside TRACKED shards — the
        honest denominator for byte accounting (manifest gaps and
        un-requested shards contribute nothing)."""
        total = 0
        for s in self._order:
            s_end = s.range_start + s.range_size
            if s_end <= start:
                continue
            if s.range_start >= end:
                break
            total += min(end, s_end) - max(start, s.range_start)
        return total

    def needed_pieces(self, piece_size: int, total_pieces: int) -> set[int]:
        return pieces_for_shards(self.shards, piece_size, total_pieces)

    def shard_for(self, name: str):
        for s in self.shards:
            if s.name == name:
                return s
        return None

    def on_span(self, start: int, end: int, t: float = 0.0) -> list[str]:
        """A verified byte span landed; returns names of shards this span
        COMPLETED (empty for most spans). Duplicate/overlapping spans are
        merged; an already-ready shard can never re-complete."""
        done: list[str] = []
        for s in self._order:
            s_end = s.range_start + s.range_size
            if s_end <= start:
                continue
            if s.range_start >= end:
                break
            if s.name in self.ready:
                continue
            cov = self._cov[s.name]
            cov.add(max(start, s.range_start), min(end, s_end))
            if cov.covered() >= s.range_size:
                self.ready[s.name] = t
                done.append(s.name)
        return done
