"""In-memory TTL cache. Role parity: reference ``pkg/cache`` (go-cache style)."""

from __future__ import annotations

import threading
import time
from typing import Any, Hashable


class TTLCache:
    NO_EXPIRE = 0.0

    def __init__(self, default_ttl: float = 60.0):
        self._default_ttl = default_ttl
        self._lock = threading.Lock()
        self._data: dict[Hashable, tuple[Any, float]] = {}  # key -> (value, expiry; 0 = never)

    def set(self, key: Hashable, value: Any, ttl: float | None = None) -> None:
        ttl = self._default_ttl if ttl is None else ttl
        expiry = time.monotonic() + ttl if ttl > 0 else 0.0
        with self._lock:
            self._data[key] = (value, expiry)

    def get(self, key: Hashable, default: Any = None) -> Any:
        now = time.monotonic()
        with self._lock:
            item = self._data.get(key)
            if item is None:
                return default
            value, expiry = item
            if expiry and expiry < now:
                del self._data[key]
                return default
            return value

    def delete(self, key: Hashable) -> None:
        with self._lock:
            self._data.pop(key, None)

    def purge_expired(self) -> int:
        now = time.monotonic()
        with self._lock:
            dead = [k for k, (_, e) in self._data.items() if e and e < now]
            for k in dead:
                del self._data[k]
            return len(dead)

    def __len__(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for _, e in self._data.values() if not e or e >= now)
