"""Interval-driven GC runner: named tasks swept on their own periods.

Role parity: reference ``pkg/gc`` (``gc.go:28-130``) and
``client/daemon/gc`` — storage managers and the scheduler's resource
managers register sweepers here.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Awaitable, Callable

log = logging.getLogger("df.gc")


@dataclass
class GCTask:
    id: str
    interval: float
    run: Callable[[], Awaitable[int] | int]  # returns number reclaimed


class GC:
    def __init__(self) -> None:
        self._tasks: dict[str, GCTask] = {}
        self._runners: list[asyncio.Task] = []
        self._stopped = asyncio.Event()

    def add(self, task: GCTask) -> None:
        if task.id in self._tasks:
            raise ValueError(f"gc task exists: {task.id}")
        self._tasks[task.id] = task

    async def run_one(self, task_id: str) -> int:
        task = self._tasks[task_id]
        out = task.run()
        if asyncio.iscoroutine(out):
            out = await out
        return int(out or 0)

    async def _loop(self, task: GCTask) -> None:
        while not self._stopped.is_set():
            try:
                await asyncio.wait_for(self._stopped.wait(), timeout=task.interval)
                return
            except asyncio.TimeoutError:
                pass
            try:
                n = await self.run_one(task.id)
                if n:
                    log.debug("gc %s reclaimed %d", task.id, n)
            except Exception:
                log.exception("gc task %s failed", task.id)

    def start(self) -> None:
        self._stopped.clear()
        for task in self._tasks.values():
            self._runners.append(asyncio.get_running_loop().create_task(self._loop(task)))

    async def stop(self) -> None:
        self._stopped.set()
        for r in self._runners:
            r.cancel()
        for r in self._runners:
            try:
                await r
            except (asyncio.CancelledError, Exception):
                pass
        self._runners.clear()
