"""Interval-driven GC runner: named tasks swept on their own periods.

Role parity: reference ``pkg/gc`` (``gc.go:28-130``) and
``client/daemon/gc`` — storage managers and the scheduler's resource
managers register sweepers here.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Awaitable, Callable

from .metrics import REGISTRY

log = logging.getLogger("df.gc")

_gc_last_run = REGISTRY.gauge(
    "df_gc_last_run_timestamp_seconds",
    "unix time a GC task last completed a sweep", ("task",))
_gc_duration = REGISTRY.histogram(
    "df_gc_run_duration_seconds", "wall time of each GC sweep", ("task",))
_gc_reclaimed = REGISTRY.counter(
    "df_gc_reclaimed_total", "items reclaimed by GC sweeps", ("task",))
_gc_runs = REGISTRY.counter(
    "df_gc_runs_total", "GC sweeps by outcome", ("task", "result"))


@dataclass
class GCTask:
    id: str
    interval: float
    run: Callable[[], Awaitable[int] | int]  # returns number reclaimed


class GC:
    def __init__(self) -> None:
        self._tasks: dict[str, GCTask] = {}
        self._runners: list[asyncio.Task] = []
        self._stopped = asyncio.Event()

    def add(self, task: GCTask) -> None:
        if task.id in self._tasks:
            raise ValueError(f"gc task exists: {task.id}")
        self._tasks[task.id] = task

    async def run_one(self, task_id: str) -> int:
        task = self._tasks[task_id]
        t0 = time.monotonic()
        try:
            out = task.run()
            if asyncio.iscoroutine(out):
                out = await out
        except asyncio.CancelledError:
            raise            # shutdown catching a sweep mid-flight: not an
            # error — counting it would pollute the alertable counter on
            # every restart
        except Exception:
            _gc_runs.labels(task_id, "error").inc()
            raise
        n = int(out or 0)
        # a sweep that found nothing still proves the runner is alive —
        # the last-run timestamp is the liveness signal a dashboard alerts
        # on (a wedged runner shows a frozen timestamp, not a zero count)
        _gc_last_run.labels(task_id).set(time.time())
        _gc_duration.labels(task_id).observe(time.monotonic() - t0)
        _gc_runs.labels(task_id, "ok").inc()
        if n:
            _gc_reclaimed.labels(task_id).inc(n)
        return n

    async def _loop(self, task: GCTask) -> None:
        while not self._stopped.is_set():
            try:
                await asyncio.wait_for(self._stopped.wait(), timeout=task.interval)
                return
            except asyncio.TimeoutError:
                pass
            try:
                n = await self.run_one(task.id)
                if n:
                    log.debug("gc %s reclaimed %d", task.id, n)
            except Exception:
                log.exception("gc task %s failed", task.id)

    def start(self) -> None:
        self._stopped.clear()
        for task in self._tasks.values():
            self._runners.append(asyncio.get_running_loop().create_task(self._loop(task)))

    async def stop(self) -> None:
        self._stopped.set()
        for r in self._runners:
            r.cancel()
        for r in self._runners:
            try:
                await r
            except (asyncio.CancelledError, Exception):
                pass
        self._runners.clear()
