"""Structured logging setup: per-concern loggers with optional rotating files.

Role parity: reference ``internal/dflog`` (zap cores per concern — core, grpc,
gc, gin — with rotation and context loggers). We use stdlib logging with a
key=value formatter; ``with_fields`` returns a LoggerAdapter carrying task/peer
context the way ``SugaredLoggerOnWith`` does.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
from typing import Any

CONCERNS = ("core", "rpc", "gc", "http", "storage", "sched")


class KVFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        fields = getattr(record, "df_fields", None)
        if fields:
            kv = " ".join(f"{k}={v}" for k, v in fields.items())
            return f"{base} {kv}"
        return base


class ContextLogger(logging.LoggerAdapter):
    def process(self, msg: str, kwargs: dict[str, Any]):
        extra = kwargs.setdefault("extra", {})
        merged = dict(self.extra or {})
        merged.update(extra.get("df_fields", {}))
        extra["df_fields"] = merged
        return msg, kwargs

    def with_fields(self, **fields: Any) -> "ContextLogger":
        merged = dict(self.extra or {})
        merged.update(fields)
        return ContextLogger(self.logger, merged)


def with_fields(name: str, **fields: Any) -> ContextLogger:
    return ContextLogger(logging.getLogger(name), fields)


_configured = False


def setup(level: str = "INFO", log_dir: str | None = None, console: bool = True,
          max_bytes: int = 50 * 1024 * 1024, backups: int = 3) -> None:
    """Configure the ``df`` logger tree. Idempotent."""
    global _configured
    root = logging.getLogger("df")
    if _configured:
        root.setLevel(level.upper())
        return
    _configured = True
    root.setLevel(level.upper())
    root.propagate = False
    fmt = KVFormatter("%(asctime)s %(levelname).1s %(name)s %(message)s")
    if console:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(fmt)
        root.addHandler(h)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        for concern in CONCERNS:
            fh = logging.handlers.RotatingFileHandler(
                os.path.join(log_dir, f"{concern}.log"),
                maxBytes=max_bytes, backupCount=backups)
            fh.setFormatter(fmt)
            lg = logging.getLogger(f"df.{concern}")
            lg.addHandler(fh)
    if not root.handlers:
        root.addHandler(logging.NullHandler())
