"""Deterministic fault-injection plane: named sites, scripted faults.

Role parity: none in the reference — Dragonfly2 tests its failure ladders
with ad-hoc mocks per suite. At pod scale the retry/failover behaviour IS
the product (a single stalled input shard stalls the whole training step),
so this repo gives every layer a named injection site that tests and the
stress tool can arm with deterministic scripts:

    site            fired from
    --------------  ----------------------------------------------------
    rpc.unary       rpc/client.py ServiceClient.unary (before the stub)
    rpc.stream.read rpc/client.py stream read halves
    piece.wire      daemon/piece_downloader.py body read (inside the
                    request's timeout window, so 'hang' trips the
                    per-piece deadline exactly like a wedged parent)
    source.fetch    source/client.py module-level download()
    hbm.ingest      tpu/hbm_sink.py DeviceIngest.write (sync path)
    sched.register  daemon/scheduler_session.py register, keyed by the
                    scheduler address under attempt
    pex.gossip      daemon/pex.py gossip round, keyed by the target peer
                    address ('corrupt' flips an envelope byte so the
                    receiver's digest verify rejects it)
    relay.stall     daemon/upload_server.py streaming relay wait, keyed
                    by the task id: a parent whose landing watermark
                    stops advancing mid-relay ('hang' parks the serve so
                    the child's piece deadline fires and the piece is
                    re-pulled from another holder)
    upload.serve    daemon/upload_server.py piece-serve path, keyed by
                    "<host_id>|<task_id>": a byzantine daemon —
                    'corrupt' flips a byte in the served range so every
                    child's landing verification rejects it (the swarm
                    immune system's chaos lever; arm with pct= to poison
                    a deterministic fraction of serves,
                    ``stress.py --byzantine``)
    sched.snapshot.io
                    scheduler/statestore.py persist path, keyed by the
                    snapshot reason: torn ('corrupt' flips a byte of the
                    serialized blob so load refuses it wholesale), ENOSPC
                    ('error'/'fail' raise mid-persist), or a wedged disk
                    ('delay'; 'hang' degrades to fail — the writer is
                    sync). The store swallows every one of them: a failed
                    snapshot is counted, never raised into a ruling path

Script syntax (one clause per site, ';'-separated)::

    site[@keysub]=kind[:arg]...
    kind := fail | error | delay | hang | corrupt
    arg  := n=<count|-1>        fire count, -1 = forever   (default 1)
            code=<Code name|int>  DFError code raised      (default UNAVAILABLE)
            after_ms=<ms>       retry_after_ms hint on the raised error
            delay_s=<seconds>   sleep length for kind=delay
            pct=<1-100>         fire on this percentage of matching
                                attempts (deterministic striding, not
                                random — attempt k fires iff
                                floor(k*pct/100) > floor((k-1)*pct/100))
            <float>             positional shorthand for delay_s
            <int>               positional shorthand for n

Examples::

    sched.register@127.0.0.1:9000=fail:n=-1      # that scheduler is dead
    source.fetch=error:code=SOURCE_ERROR:after_ms=400   # origin 503 once
    piece.wire=hang:n=1                          # parent wedges mid-piece
    piece.wire=corrupt:n=1                       # digest-mismatch once
    rpc.unary=fail:n=2                           # fail twice, then succeed

Overhead contract: every call site guards with ``if faultgate.ARMED:`` —
one module-attribute load and a falsy test when disarmed; the module is
never entered on the hot path of a production process.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time

from .errors import Code, DFError
from .metrics import REGISTRY

log = logging.getLogger("df.faultgate")

# The site registry. Arming an unknown site is an error, and the tier-1
# lint (tests/test_faults.py) asserts every name here is both fired
# somewhere in the tree and documented in docs/RESILIENCE.md.
SITES = frozenset({
    "rpc.unary",
    "rpc.stream.read",
    "piece.wire",
    "source.fetch",
    "hbm.ingest",
    "sched.register",
    "pex.gossip",
    "relay.stall",
    "upload.serve",
    "sched.snapshot.io",
})

KINDS = frozenset({"fail", "error", "delay", "hang", "corrupt"})

# fast-path flag: True iff at least one script is armed
ARMED = False

_injected = REGISTRY.counter("df_fault_injected_total",
                             "faults injected by the faultgate plane",
                             ("site", "kind"))


class FaultScript:
    """One armed fault at one site, optionally key-scoped."""

    __slots__ = ("site", "kind", "key", "n", "code", "after_ms", "delay_s",
                 "pct", "attempts", "fired")

    def __init__(self, site: str, kind: str, *, key: str = "", n: int = 1,
                 code: Code = Code.UNAVAILABLE, after_ms: int = 0,
                 delay_s: float = 0.5, pct: int = 100):
        if site not in SITES:
            raise ValueError(f"unknown faultgate site {site!r} "
                             f"(known: {sorted(SITES)})")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(known: {sorted(KINDS)})")
        if not 1 <= int(pct) <= 100:
            raise ValueError(f"pct must be 1-100, got {pct!r}")
        self.site = site
        self.kind = kind
        self.key = key
        self.n = n              # remaining fires; -1 = forever
        self.code = Code(code)
        self.after_ms = int(after_ms)
        self.delay_s = float(delay_s)
        self.pct = int(pct)     # fire on this % of matching attempts
        self.attempts = 0       # matching attempts seen (pct striding)
        self.fired = 0

    def matches(self, key: str) -> bool:
        return self.n != 0 and (not self.key or self.key in key)

    def due(self) -> bool:
        """Advance the deterministic pct stride: attempt k fires iff the
        integer floor of k*pct/100 advanced — pct=100 fires every
        attempt (the pre-pct behavior), pct=25 every 4th, with no rng
        (chaos runs must replay)."""
        self.attempts += 1
        if self.pct >= 100:
            return True
        return (self.attempts * self.pct) // 100 \
            > ((self.attempts - 1) * self.pct) // 100

    def consume(self) -> None:
        self.fired += 1
        if self.n > 0:
            self.n -= 1

    def describe(self) -> dict:
        return {"site": self.site, "kind": self.kind, "key": self.key,
                "remaining": self.n, "fired": self.fired,
                "attempts": self.attempts, "pct": self.pct,
                "code": self.code.name, "after_ms": self.after_ms,
                "delay_s": self.delay_s}


_scripts: list[FaultScript] = []
_lock = threading.Lock()   # hbm.ingest fires from the sink's caller thread


def _recompute_armed() -> None:
    global ARMED
    ARMED = any(s.n != 0 for s in _scripts)


def arm(site: str, kind: str, **kwargs) -> FaultScript:
    """Arm one scripted fault; returns the script (live counters)."""
    script = FaultScript(site, kind, **kwargs)
    with _lock:
        _scripts.append(script)
        _recompute_armed()
    log.info("faultgate armed: %s", script.describe())
    return script


def arm_script(text: str) -> list[FaultScript]:
    """Arm from the textual syntax (see module docstring)."""
    armed = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        head, _, spec = clause.partition("=")
        if not spec:
            raise ValueError(f"bad faultgate clause {clause!r} "
                             "(want site[@key]=kind[:arg]...)")
        site, _, key = head.partition("@")
        parts = spec.split(":")
        kind = parts[0].strip()
        kwargs: dict = {"key": key.strip()}
        for arg in parts[1:]:
            arg = arg.strip()
            if not arg:
                continue
            name, eq, value = arg.partition("=")
            if not eq:
                # positional: float -> delay_s, int -> n
                if "." in name:
                    kwargs["delay_s"] = float(name)
                else:
                    kwargs["n"] = int(name)
                continue
            if name == "n":
                kwargs["n"] = int(value)
            elif name == "code":
                kwargs["code"] = (Code[value] if not value.lstrip("-").isdigit()
                                  else Code(int(value)))
            elif name == "after_ms":
                kwargs["after_ms"] = int(value)
            elif name == "delay_s":
                kwargs["delay_s"] = float(value)
            elif name == "pct":
                kwargs["pct"] = int(value)
            else:
                raise ValueError(f"unknown faultgate arg {name!r} in {clause!r}")
        armed.append(arm(site.strip(), kind, **kwargs))
    return armed


def reset() -> None:
    """Disarm everything (tests call this in teardown)."""
    with _lock:
        _scripts.clear()
        _recompute_armed()


def status() -> dict:
    with _lock:
        return {"armed": ARMED, "scripts": [s.describe() for s in _scripts]}


def _claim(site: str, key: str, *, kinds: frozenset | None = None
           ) -> FaultScript | None:
    """Find-and-consume the first matching armed script. A matching
    script whose pct stride says "not this attempt" counts the attempt
    and yields no fire (later scripts still get a chance)."""
    with _lock:
        for s in _scripts:
            if s.site == site and s.matches(key) and (
                    kinds is None or s.kind in kinds):
                if not s.due():
                    continue
                s.consume()
                _recompute_armed()
                return s
    return None


def peek(site: str, key: str = "", *, kinds: frozenset | None = None) -> bool:
    """True when an armed script WOULD match (site, key) — without
    consuming a fire or advancing the pct stride. Call sites whose fast
    path bypasses Python (the upload server's sendfile branch) use this
    to route through the corruptible path only while a script is armed."""
    with _lock:
        return any(s.site == site and s.matches(key)
                   and (kinds is None or s.kind in kinds)
                   for s in _scripts)


_RAISING = frozenset({"fail", "error"})
_ASYNC_KINDS = frozenset({"fail", "error", "delay", "hang"})


def _raise(script: FaultScript) -> None:
    err = DFError(script.code,
                  f"faultgate[{script.site}]: injected {script.kind}")
    if script.after_ms:
        err.retry_after_ms = script.after_ms
    raise err


async def fire(site: str, key: str = "") -> None:
    """Fire at an async site. fail/error raise a DFError (error carries a
    retry_after_ms hint), delay sleeps, hang parks until the caller's own
    deadline cancels it. 'corrupt' scripts are not consumed here — they
    belong to maybe_corrupt()."""
    script = _claim(site, key, kinds=_ASYNC_KINDS)
    if script is None:
        return
    _injected.labels(site, script.kind).inc()
    log.info("faultgate fired: %s key=%r", script.describe(), key)
    if script.kind in _RAISING:
        _raise(script)
    elif script.kind == "delay":
        await asyncio.sleep(script.delay_s)
    elif script.kind == "hang":
        await asyncio.sleep(3600.0)   # parked; the site's deadline cancels us


def fire_sync(site: str, key: str = "") -> None:
    """Sync-path variant (hbm.ingest): fail/error raise; delay blocks the
    calling thread; hang is treated as fail (a sync site cannot park
    cancellably)."""
    script = _claim(site, key, kinds=_ASYNC_KINDS)
    if script is None:
        return
    _injected.labels(site, script.kind).inc()
    log.info("faultgate fired (sync): %s key=%r", script.describe(), key)
    if script.kind == "delay":
        time.sleep(script.delay_s)
        return
    _raise(script)


def corrupt(site: str, data: bytes, key: str = "") -> bytes:
    """Consume one 'corrupt' script if armed for (site, key): flips a byte
    so digest verification downstream fails deterministically. Returns the
    (possibly corrupted) bytes."""
    script = _claim(site, key, kinds=frozenset({"corrupt"}))
    if script is None:
        return data
    _injected.labels(site, script.kind).inc()
    log.info("faultgate corrupting %d bytes at %s key=%r", len(data), site,
             key)
    if not data:
        return data
    buf = bytearray(data)
    buf[0] ^= 0xFF
    return bytes(buf)


def add_fault_routes(router) -> None:
    """Debug control surface (mounted on the daemon upload server when
    ``upload.debug_endpoints`` is on — arming faults mutates live behaviour
    so it stays off the always-on surface):

        GET    /debug/faults   -> {"armed": bool, "scripts": [...]}
        POST   /debug/faults   -> body is a script string; arms it
        DELETE /debug/faults   -> reset()
    """
    import json

    from aiohttp import web

    async def get_faults(_r: web.Request) -> web.Response:
        return web.json_response(status())

    async def post_faults(request: web.Request) -> web.Response:
        text = (await request.text()).strip()
        try:
            armed = arm_script(text)
        except (ValueError, KeyError) as exc:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": str(exc)}),
                content_type="application/json")
        return web.json_response({"armed": [s.describe() for s in armed]})

    async def delete_faults(_r: web.Request) -> web.Response:
        reset()
        return web.json_response(status())

    router.add_get("/debug/faults", get_faults)
    router.add_post("/debug/faults", post_faults)
    router.add_delete("/debug/faults", delete_faults)
