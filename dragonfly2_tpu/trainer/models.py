"""JAX models: MLP bandwidth predictor + host-graph GNN.

Role parity: the models the reference *intended* (``trainer/training``
GNN+MLP stubs, ``manager/models/model.go`` model registry names) built
TPU-first:

* static shapes everywhere (edge lists padded + masked) so XLA tiles onto
  the MXU;
* bfloat16 matmul compute with float32 params/accumulators;
* a single fused ``train_step`` (loss + grads + adamw update) designed to be
  ``jax.jit``-ed over a ``Mesh`` — batch sharded on ``dp``, hidden features
  on ``tp`` (see ``shard_params`` / ``shard_batch``).

The MLP consumes the 7-feature parent row (``scheduler/evaluator_ml.py``
``feature_row`` — keep in sync) and predicts a goodness score; the GNN
consumes the host graph (nodes = hosts, edges = probed links with RTT) and
predicts per-link bandwidth class.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MLP_FEATURES = 7          # scheduler/evaluator_ml.py feature_row length
GNN_NODE_FEATURES = 7     # host features: type, upload ratio, load,
                          # coords, pod id (features.NODE_FEATURES v2)
GNN_EDGE_FEATURES = 2     # log-rtt, link-class

Params = Any  # pytree of jnp arrays


# ------------------------------------------------------------------ init

def _dense_init(key, n_in: int, n_out: int) -> dict:
    w_key, _ = jax.random.split(key)
    scale = (2.0 / n_in) ** 0.5
    return {"w": jax.random.normal(w_key, (n_in, n_out), jnp.float32) * scale,
            "b": jnp.zeros((n_out,), jnp.float32)}


def init_mlp(key, *, in_dim: int = MLP_FEATURES, hidden: int = 128,
             depth: int = 2, out_dim: int = 1) -> Params:
    keys = jax.random.split(key, depth + 1)
    layers = [_dense_init(keys[0], in_dim, hidden)]
    for i in range(1, depth):
        layers.append(_dense_init(keys[i], hidden, hidden))
    layers.append(_dense_init(keys[-1], hidden, out_dim))
    return {"layers": layers}


def init_gnn(key, *, node_dim: int = GNN_NODE_FEATURES,
             edge_dim: int = GNN_EDGE_FEATURES, hidden: int = 128,
             layers: int = 2) -> Params:
    keys = jax.random.split(key, 2 * layers + 2)
    params: dict = {"encode": _dense_init(keys[0], node_dim, hidden),
                    "msg": [], "upd": []}
    for i in range(layers):
        params["msg"].append(
            _dense_init(keys[1 + 2 * i], 2 * hidden + edge_dim, hidden))
        params["upd"].append(
            _dense_init(keys[2 + 2 * i], 2 * hidden, hidden))
    # head reads NODE EMBEDDINGS only: feeding edge_feat (which contains
    # the observed log-RTT the label is computed from) lets training learn
    # the trivial copy-the-answer shortcut — the model must predict a
    # link's quality from where its endpoints sit in the graph, which is
    # the only information available for an UNPROBED pair at impute time
    params["head"] = _dense_init(keys[-1], 2 * hidden, 1)
    return params


# ------------------------------------------------------------------ forward

def _dense(p: dict, x: jnp.ndarray) -> jnp.ndarray:
    # bf16 matmul on the MXU, f32 accumulate via preferred_element_type
    y = jax.lax.dot_general(
        x.astype(jnp.bfloat16), p["w"].astype(jnp.bfloat16),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return y + p["b"]


def mlp_forward(params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """x: [batch, MLP_FEATURES] -> [batch] predicted goodness."""
    h = x.astype(jnp.float32)
    for layer in params["layers"][:-1]:
        h = jax.nn.gelu(_dense(layer, h))
    out = _dense(params["layers"][-1], h)
    return out[..., 0]


def gnn_forward(params: Params, nodes: jnp.ndarray, edge_src: jnp.ndarray,
                edge_dst: jnp.ndarray, edge_feat: jnp.ndarray,
                edge_mask: jnp.ndarray) -> jnp.ndarray:
    """Host-graph message passing.

    nodes:      [N, node_dim]   edge_src/dst: [E] int32 (padded)
    edge_feat:  [E, edge_dim]   edge_mask:    [E] {0,1}
    returns     [E] predicted link bandwidth score for EVERY edge index
    (the caller masks; query edges ride with mask=0 so they never inject
    fabricated messages into aggregation yet still get head scores)

    Observed edges' features (incl. their measured log-RTT) inform the
    MESSAGES — a node's links say where it sits — but the head scores a
    pair from the two node embeddings alone (no label leak; see init_gnn).

    Static [N, E] shapes: the scheduler pads its host graph to the next
    bucket so recompilation only happens on bucket growth.
    """
    n = nodes.shape[0]
    h = jax.nn.gelu(_dense(params["encode"], nodes))
    mask = edge_mask[:, None].astype(jnp.float32)
    for msg_p, upd_p in zip(params["msg"], params["upd"]):
        src_h = h[edge_src]                       # [E, H] gather
        dst_h = h[edge_dst]
        m = jax.nn.gelu(_dense(msg_p, jnp.concatenate(
            [src_h, dst_h, edge_feat], axis=-1))) * mask
        agg = jax.ops.segment_sum(m, edge_dst, num_segments=n)
        deg = jax.ops.segment_sum(mask, edge_dst, num_segments=n)
        agg = agg / jnp.maximum(deg, 1.0)
        h = jax.nn.gelu(_dense(upd_p, jnp.concatenate([h, agg], axis=-1)))
    return _dense(params["head"], jnp.concatenate(
        [h[edge_src], h[edge_dst]], axis=-1))[..., 0]


# ------------------------------------------------------------------ training

def mlp_loss(params: Params, batch: dict) -> jnp.ndarray:
    pred = mlp_forward(params, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2)


def gnn_loss(params: Params, batch: dict) -> jnp.ndarray:
    pred = gnn_forward(params, batch["nodes"], batch["edge_src"],
                       batch["edge_dst"], batch["edge_feat"],
                       batch["edge_mask"])
    err = (pred - batch["y"]) ** 2 * batch["edge_mask"]
    return jnp.sum(err) / jnp.maximum(jnp.sum(batch["edge_mask"]), 1.0)


def make_optimizer(lr: float = 1e-3) -> optax.GradientTransformation:
    return optax.adamw(lr, weight_decay=1e-4)


def make_train_step(loss_fn, optimizer):
    """(params, opt_state, batch) -> (params, opt_state, loss); pure, jittable."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


# ------------------------------------------------------------------ sharding

def make_mesh(n_devices: int | None = None, *,
              dp: int | None = None) -> Mesh:
    """A (dp, tp) mesh over available devices; tp gets the residue."""
    devices = np.array(jax.devices())
    n = n_devices or devices.size
    devices = devices[:n]
    if dp is None:
        dp = max(1, n // 2) if n > 1 else 1
    tp = n // dp
    return Mesh(devices[:dp * tp].reshape(dp, tp), ("dp", "tp"))


def _param_spec(leaf: jnp.ndarray, tp: int) -> P:
    # weight matrices shard the output-features dim over tp (when it tiles
    # evenly — the 1-wide output head replicates); biases/scalars replicate.
    if leaf.ndim == 2 and tp > 1 and leaf.shape[1] % tp == 0 \
            and leaf.shape[1] >= tp:
        return P(None, "tp")
    return P()


def shard_params(params: Params, mesh: Mesh) -> Params:
    tp = mesh.shape.get("tp", 1)

    def put(leaf):
        return jax.device_put(leaf, NamedSharding(mesh, _param_spec(leaf, tp)))
    return jax.tree_util.tree_map(put, params)


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    def put(leaf):
        spec = P("dp") if leaf.ndim >= 1 else P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return {k: put(v) for k, v in batch.items()}


def sharded_train_step(loss_fn, optimizer, mesh: Mesh):
    """jit the full train step over the mesh: batch dp-sharded, weight
    matrices tp-sharded; XLA inserts the psum/all-gather collectives."""
    step = make_train_step(loss_fn, optimizer)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def jitted(params, opt_state, batch):
        return step(params, opt_state, batch)

    return jitted


# ------------------------------------------------------------------ synthetic data (tests/dryrun)

def synthetic_mlp_batch(key, batch_size: int = 256) -> dict:
    x_key, n_key = jax.random.split(key)
    x = jax.random.uniform(x_key, (batch_size, MLP_FEATURES))
    w = jnp.linspace(1.0, 0.2, MLP_FEATURES)
    y = x @ w + 0.05 * jax.random.normal(n_key, (batch_size,))
    return {"x": x, "y": y}


def synthetic_gnn_batch(key, n_nodes: int = 32, n_edges: int = 128) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    nodes = jax.random.uniform(k1, (n_nodes, GNN_NODE_FEATURES))
    edge_src = jax.random.randint(k2, (n_edges,), 0, n_nodes)
    edge_dst = jax.random.randint(k3, (n_edges,), 0, n_nodes)
    edge_feat = jax.random.uniform(k4, (n_edges, GNN_EDGE_FEATURES))
    y = 1.0 / (1.0 + edge_feat[:, 0])      # bandwidth ~ inverse log-rtt
    edge_mask = jnp.ones((n_edges,), jnp.float32)
    return {"nodes": nodes, "edge_src": edge_src, "edge_dst": edge_dst,
            "edge_feat": edge_feat, "edge_mask": edge_mask, "y": y}
