"""Trainer gRPC service: dataset sink + training kick + parity inference.

Role parity: reference ``trainer/service/service_v1.go:59-162`` — the
``Train`` client-stream receives gzip'd datasets keyed by (hostname, ip),
lands them in ``trainer/storage``, and on stream close kicks a training
run. The reference stopped there (fitting was a stub and the model never
reached the manager); here the run fits the JAX models
(``trainer/training.py``) and registers the result with the manager's model
registry, closing BASELINE config #5.

``ModelInfer`` serves the latest fitted MLP for parity with the reference's
Triton client surface (``pkg/rpc/inference``); production scoring pulls the
model into the scheduler instead (see ``trainer/serving.py`` rationale).
"""

from __future__ import annotations

import asyncio
import logging

from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import (CreateModelRequest, ModelInferRequest,
                            ModelInferResponse, TrainResponse)
from ..rpc.server import ServiceDef
from . import pipeline, serving, training
from .storage import TrainerStorage

log = logging.getLogger("df.trainer.service")

TRAINER_SERVICE = "df.trainer.Trainer"

_fits_total = REGISTRY.counter(
    "df_trainer_fits_total",
    "training runs per model by outcome (fitted = a new version produced, "
    "skipped = snapshot below the usable-row floor)", ("model", "result"))
_fit_rows = REGISTRY.gauge(
    "df_trainer_fit_rows",
    "rows consumed by the most recent fit, per model", ("model",))
_fit_seconds = REGISTRY.gauge(
    "df_trainer_fit_seconds",
    "wall time of the most recent fit, per model", ("model",))


class TrainerService:
    def __init__(self, storage: TrainerStorage, *, manager=None,
                 min_rows: int = 32, train_in_thread: bool = True):
        """``manager``: a ManagerLink used to register fitted models; None
        keeps models local (tests, standalone runs)."""
        self.storage = storage
        self.manager = manager
        self.min_rows = min_rows
        self.train_in_thread = train_in_thread
        self.latest: dict[str, tuple[bytes, dict]] = {}   # name -> (blob, metrics)
        self._infer_cache: dict[str, object] = {}         # name -> callable
        self._spool_lock = asyncio.Lock()        # guards spool append/snapshot
        self._fit_lock = asyncio.Lock()          # serializes model fitting
        self._spool_clusters: set[int] = set()   # clusters feeding the spool

    # -- Train (client-stream) -----------------------------------------

    async def train(self, request_iter, context) -> TrainResponse:
        # one gzip stream per dataset may span many chunks — buffer until
        # the stream ends, then decompress whole (a sliced gzip stream is
        # not independently decompressible)
        bufs: dict[str, bytearray] = {}
        uploader = ("", "")
        cluster_id = 0
        async for req in request_iter:
            if not req.dataset:
                raise DFError(Code.INVALID_ARGUMENT, "dataset required")
            uploader = (req.hostname, req.ip)
            cluster_id = req.cluster_id or cluster_id
            if req.chunk:
                bufs.setdefault(req.dataset, bytearray()).extend(req.chunk)
        # spool-append and the training snapshot share one lock, but the
        # FIT runs outside it: holding a lock across a 100-epoch fit would
        # park every other scheduler's upload stream behind the training
        # run — wrong shape for a fleet of schedulers feeding one trainer
        async with self._spool_lock:
            got: dict[str, int] = {}
            for dataset, buf in bufs.items():
                got[dataset] = await asyncio.to_thread(
                    self.storage.append_chunk, dataset, uploader[0],
                    uploader[1], bytes(buf))
            log.info("dataset upload from %s@%s (cluster %d): %s",
                     uploader[0], uploader[1], cluster_id, got or "empty")
            if cluster_id:
                self._spool_clusters.add(cluster_id)
            snap = await self._snapshot()
        version = ""
        if snap is not None:
            try:
                version = await self._fit(snap)
            except BaseException:
                # the snapshot cleared the spools; a failed fit (bad rows,
                # OOM) must put the rows back or the dataset is silently
                # lost — contradicting the announcer's at-least-once design
                rows, topo_rows, _ = snap
                async with self._spool_lock:
                    if rows:
                        await asyncio.to_thread(
                            self.storage.requeue_rows, "download", rows)
                    if topo_rows:
                        await asyncio.to_thread(
                            self.storage.requeue_rows, "networktopology",
                            topo_rows)
                raise
        return TrainResponse(ok=True, model_version=version,
                             message=f"rows={got}")

    async def _snapshot(self):
        """Under ``_spool_lock``: decide what to fit, take the rows, and
        clear the consumed spools so concurrent uploads start a fresh
        dataset. Returns None when no floor is met."""
        rows = await asyncio.to_thread(self.storage.rows, "download")
        topo_rows = await asyncio.to_thread(self.storage.rows,
                                            "networktopology")
        # each model gates on ITS OWN dataset floor — topo rows being
        # present must not let the MLP fit on a handful of download rows
        fit_mlp = len(rows) >= self.min_rows
        fit_gnn = len(topo_rows) >= 4
        if not fit_mlp and not fit_gnn:
            return None
        # a model fit on one cluster's rows belongs to that cluster; a
        # mixed spool is a global model (cluster 0), not the last uploader's
        clusters = self._spool_clusters
        cluster_id = next(iter(clusters)) if len(clusters) == 1 else 0
        if fit_mlp:
            await asyncio.to_thread(self.storage.clear, "download")
        if fit_gnn:
            await asyncio.to_thread(self.storage.clear, "networktopology")
        if fit_mlp and fit_gnn:
            self._spool_clusters = set()
        return (rows if fit_mlp else None,
                topo_rows if fit_gnn else None, cluster_id)

    async def _fit(self, snap) -> str:
        """Fit on a snapshot (serialized by ``_fit_lock``, uploads NOT
        blocked). Returns the MLP version (the one schedulers serve);
        falls back to the GNN's when only the GNN fit."""
        rows, topo_rows, cluster_id = snap
        async with self._fit_lock:
            # the MLP fits through the pipeline's supervision policy:
            # decision-outcome folds when the uploaded records carry
            # joined rulings, raw piece rows otherwise
            mlp = gnn = None
            if self.train_in_thread:
                if rows is not None:
                    mlp = await asyncio.to_thread(
                        pipeline.train_decision_model, rows)
                if topo_rows is not None:
                    gnn = await asyncio.to_thread(training.train_gnn,
                                                  topo_rows)
            else:
                # dflint: disable=DF001 — train_in_thread=False is the deterministic unit-test knob; production fits ride to_thread above
                mlp = (pipeline.train_decision_model(rows)
                       if rows is not None else None)
                # dflint: disable=DF001 — see above: test-only direct-fit knob
                gnn = (training.train_gnn(topo_rows)
                       if topo_rows is not None else None)
            for name, fitted, attempted in (
                    (training.MLP_MODEL_NAME, mlp, rows is not None),
                    (training.GNN_MODEL_NAME, gnn, topo_rows is not None)):
                if fitted is None:
                    if attempted:
                        _fits_total.labels(name, "skipped").inc()
                    continue
                blob, metrics = fitted
                _fits_total.labels(name, "fitted").inc()
                _fit_rows.labels(name).set(metrics.get("rows", 0))
                _fit_seconds.labels(name).set(
                    metrics.get("train_seconds", 0.0))
                self.latest[name] = (blob, metrics)
                self._infer_cache.pop(name, None)
                await self._publish(name, blob, metrics, cluster_id)
        if mlp is not None:
            return mlp[1]["version"]
        return gnn[1]["version"] if gnn is not None else ""

    async def _publish(self, name: str, blob: bytes, metrics: dict,
                       cluster_id: int) -> None:
        if self.manager is None:
            return
        try:
            await self.manager.create_model(CreateModelRequest(
                name=name, version=metrics["version"], data=blob,
                metrics=metrics, scheduler_cluster_id=cluster_id))
        except Exception as exc:  # noqa: BLE001 - registry may be down
            log.warning("model %s@%s not registered: %s", name,
                        metrics["version"], exc)

    # -- ModelInfer (parity surface) -----------------------------------

    async def model_infer(self, req: ModelInferRequest,
                          context) -> ModelInferResponse:
        name = req.model_name or training.MLP_MODEL_NAME
        fitted = self.latest.get(name)
        if fitted is None:
            raise DFError(Code.NOT_FOUND, f"no trained model {name!r}")
        blob, metrics = fitted
        infer = self._infer_cache.get(name)
        if infer is None:
            # deserialize + hash the blob off-loop (cold cache only)
            infer = await asyncio.to_thread(serving.make_mlp_infer, blob)
            # a training round may have published a new model while the
            # build was suspended — caching then would pin the OLD model
            # past train()'s invalidating pop; serve this request from
            # the blob it read, but only cache a still-current build
            if self.latest.get(name, (None,))[0] is blob:
                self._infer_cache[name] = infer
        outputs = await asyncio.to_thread(infer, req.features or [])
        return ModelInferResponse(outputs=outputs,
                                  model_version=metrics["version"])


def build_service(svc: TrainerService) -> ServiceDef:
    d = ServiceDef(TRAINER_SERVICE)
    d.stream_unary("Train", svc.train)
    d.unary_unary("ModelInfer", svc.model_infer)
    return d
