"""Model serving: turn a registered model blob into an ``infer`` callable.

Role parity: reference ``pkg/rpc/inference/client/client_v1.go:76-102`` — a
Triton ``ModelInfer`` client intended for the ``ml`` evaluator but unused
in-tree. TPU-native change: the evaluator scores a handful of candidates
per schedule tick, thousands of times a second — an RPC per tick would
dominate scheduling latency. So models are *pulled* from the manager
registry and served in-process with a pure-numpy forward pass (the jax/TPU
side is training-only); the trainer also exposes a ``ModelInfer`` RPC for
parity and tests (``trainer/service.py``).
"""

from __future__ import annotations

import logging
from typing import Callable

import numpy as np

from . import features, params_io

log = logging.getLogger("df.trainer.serving")

Infer = Callable[[list[list[float]]], list[float]]


def _gelu(x: np.ndarray) -> np.ndarray:
    # tanh approximation — matches jax.nn.gelu's default closely enough for
    # a ranking model (monotone, max abs diff ~1e-3)
    return 0.5 * x * (1.0 + np.tanh(0.7978845608 * (x + 0.044715 * x ** 3)))


def mlp_forward_np(params: dict, x: np.ndarray) -> np.ndarray:
    h = x.astype(np.float32)
    layers = params["layers"]
    for layer in layers[:-1]:
        h = _gelu(h @ layer["w"] + layer["b"])
    out = h @ layers[-1]["w"] + layers[-1]["b"]
    return out[..., 0]


def make_mlp_infer(model_bytes: bytes) -> Infer:
    """Deserialize a ``bandwidth_mlp`` blob into ``infer(rows) -> scores``.

    Raises ValueError on feature-schema mismatch — the scheduler must not
    score with a model trained on a different layout.
    """
    params, meta = params_io.deserialize_params(model_bytes)
    dim = int(meta.get("feature_dim", features.FEATURE_DIM))
    if dim != features.FEATURE_DIM:
        raise ValueError(
            f"model feature_dim {dim} != scheduler {features.FEATURE_DIM}")
    version = meta.get("version", params_io.version_of(model_bytes))

    def infer(rows: list[list[float]]) -> list[float]:
        x = np.asarray(rows, np.float32)
        if x.ndim != 2 or x.shape[1] != dim:
            raise ValueError(f"expected [n, {dim}] features, got {x.shape}")
        return mlp_forward_np(params, x).tolist()

    infer.version = version          # type: ignore[attr-defined]
    infer.meta = meta                # type: ignore[attr-defined]
    return infer
