"""Model serving: turn a registered model blob into an ``infer`` callable.

Role parity: reference ``pkg/rpc/inference/client/client_v1.go:76-102`` — a
Triton ``ModelInfer`` client intended for the ``ml`` evaluator but unused
in-tree. TPU-native change: the evaluator scores a handful of candidates
per schedule tick, thousands of times a second — an RPC per tick would
dominate scheduling latency. So models are *pulled* from the manager
registry and served in-process with a pure-numpy forward pass (the jax/TPU
side is training-only); the trainer also exposes a ``ModelInfer`` RPC for
parity and tests (``trainer/service.py``).
"""

from __future__ import annotations

import logging
from typing import Callable

import numpy as np

from . import features, params_io

log = logging.getLogger("df.trainer.serving")

Infer = Callable[[list[list[float]]], list[float]]


def _gelu(x: np.ndarray) -> np.ndarray:
    # tanh approximation — matches jax.nn.gelu's default closely enough for
    # a ranking model (monotone, max abs diff ~1e-3)
    return 0.5 * x * (1.0 + np.tanh(0.7978845608 * (x + 0.044715 * x ** 3)))


def mlp_forward_np(params: dict, x: np.ndarray) -> np.ndarray:
    h = x.astype(np.float32)
    layers = params["layers"]
    for layer in layers[:-1]:
        h = _gelu(h @ layer["w"] + layer["b"])
    out = h @ layers[-1]["w"] + layers[-1]["b"]
    return out[..., 0]


def make_mlp_infer(model_bytes: bytes) -> Infer:
    """Deserialize a ``bandwidth_mlp`` blob into ``infer(rows) -> scores``.

    Raises ValueError when the blob must be refused at bind time — the
    scheduler must not score with it: undecodable bytes (garbage rollout),
    a feature-schema mismatch (model trained on a different layout), or
    non-finite weights (a diverged fit would NaN every ranking). The
    refresh loop catches the refusal, keeps the current evaluator on its
    heuristic floor, and remembers the refused version (same discipline as
    ``make_gnn_impute``'s stale-schema gate).
    """
    try:
        params, meta = params_io.deserialize_params(model_bytes)
    except Exception as exc:  # noqa: BLE001 - np.load raises zoo-of-errors
        raise ValueError(f"model blob undecodable: {exc}") from exc
    dim = int(meta.get("feature_dim", features.FEATURE_DIM))
    if dim != features.FEATURE_DIM:
        raise ValueError(
            f"model feature_dim {dim} != scheduler {features.FEATURE_DIM}")
    version = meta.get("version", params_io.version_of(model_bytes))
    # bind-time probe: one forward pass over a zero row. A model whose
    # weights went non-finite (NaN/Inf anywhere on the path) fails HERE,
    # once, instead of on every scheduling tick
    try:
        probe = mlp_forward_np(params, np.zeros((1, dim), np.float32))
    except Exception as exc:  # noqa: BLE001 - malformed layer shapes
        raise ValueError(f"model forward pass broken: {exc}") from exc
    if not np.all(np.isfinite(probe)):
        raise ValueError(
            f"model {version} emits non-finite scores — diverged fit "
            "refused at bind time; the heuristic floor keeps ruling")

    def infer(rows: list[list[float]]) -> list[float]:
        x = np.asarray(rows, np.float32)
        if x.ndim != 2 or x.shape[1] != dim:
            raise ValueError(f"expected [n, {dim}] features, got {x.shape}")
        return mlp_forward_np(params, x).tolist()

    infer.version = version          # type: ignore[attr-defined]
    infer.meta = meta                # type: ignore[attr-defined]
    return infer


# ------------------------------------------------------------------ GNN

def gnn_forward_np(params: dict, graph: dict) -> np.ndarray:
    """Numpy port of ``models.gnn_forward`` (same rationale as the MLP:
    the scheduler imputes in-process, no RPC and no jax on the hot path)."""
    nodes = graph["nodes"].astype(np.float32)
    edge_src = graph["edge_src"]
    edge_dst = graph["edge_dst"]
    edge_feat = graph["edge_feat"].astype(np.float32)
    mask = graph["edge_mask"].astype(np.float32)[:, None]
    n = nodes.shape[0]

    def dense(p, x):
        return x @ p["w"] + p["b"]

    h = _gelu(dense(params["encode"], nodes))
    for msg_p, upd_p in zip(params["msg"], params["upd"]):
        src_h = h[edge_src]
        dst_h = h[edge_dst]
        m = _gelu(dense(msg_p, np.concatenate(
            [src_h, dst_h, edge_feat], axis=-1))) * mask
        agg = np.zeros((n, m.shape[-1]), np.float32)
        np.add.at(agg, edge_dst, m)
        deg = np.zeros((n, 1), np.float32)
        np.add.at(deg, edge_dst, mask)
        agg = agg / np.maximum(deg, 1.0)
        h = _gelu(dense(upd_p, np.concatenate([h, agg], axis=-1)))
    # head scores every edge index from node embeddings only (query edges
    # ride with mask=0: excluded from aggregation, still scored)
    return dense(params["head"], np.concatenate(
        [h[edge_src], h[edge_dst]], axis=-1))[..., 0]


def make_gnn_impute(model_bytes: bytes):
    """Deserialize a ``topology_gnn`` blob into
    ``impute(topo_rows, pairs) -> {(src, dst): rtt_us}``.

    Query links are appended to the observed graph with ``edge_mask=0``:
    they contribute NOTHING to message passing (a fabricated edge must not
    perturb the embeddings that score it), but the head — which reads only
    the two node embeddings — still scores them; the score is inverted
    back to an RTT estimate (``features.topology_to_graph`` label
    transform; reference intent:
    ``scheduler/networktopology/network_topology.go:334`` Neighbours).
    """
    import math

    params, meta = params_io.deserialize_params(model_bytes)
    version = meta.get("version", params_io.version_of(model_bytes))
    # schema gate: a blob trained against an older NODE_FEATURES layout
    # (v1 had no pod_id column) would crash the evaluator hot path with
    # a shape error on the first imputation — refuse it HERE, at bind
    # time, so the refresh loop logs and keeps the current imputer (or
    # the static-locality fallback) until the trainer refits
    node_dim = int(params["encode"]["w"].shape[0])
    if node_dim != len(features.NODE_FEATURES):
        raise ValueError(
            f"topology_gnn node dim {node_dim} != schema "
            f"{len(features.NODE_FEATURES)} (feature schema "
            f"v{features.FEATURE_SCHEMA_VERSION}) — stale model refused; "
            "retrain against the current NODE_FEATURES")

    def impute(topo_rows: list[dict],
               pairs: list[tuple[str, str]]) -> dict[tuple[str, str], float]:
        if not topo_rows or not pairs:
            return {}
        graph = features.topology_to_graph(topo_rows)
        if graph is None:
            return {}
        index = {hid: i for i, hid in enumerate(graph["host_ids"].tolist())}
        known = [(s, d) for s, d in pairs if s in index and d in index]
        if not known:
            return {}
        # append query edges (numpy arrays, not jax: shape changes free)
        q = len(known)
        graph = {k: (v.copy() if isinstance(v, np.ndarray) else v)
                 for k, v in graph.items()}
        graph["edge_src"] = np.concatenate(
            [graph["edge_src"],
             np.asarray([index[s] for s, _ in known], np.int32)])
        graph["edge_dst"] = np.concatenate(
            [graph["edge_dst"],
             np.asarray([index[d] for _, d in known], np.int32)])
        graph["edge_feat"] = np.concatenate(
            [graph["edge_feat"], np.zeros((q, graph["edge_feat"].shape[1]),
                                          np.float32)])
        graph["edge_mask"] = np.concatenate(
            [graph["edge_mask"], np.zeros((q,), np.float32)])
        scores = gnn_forward_np(params, graph)[-q:]
        out: dict[tuple[str, str], float] = {}
        for (s, d), y in zip(known, scores):
            y = float(np.clip(y, 1e-3, 1.0))
            # invert the label transform: y = 1/(1+max(0, log10(rtt)-1))
            log_rtt = 1.0 + (1.0 / y - 1.0)
            out[(s, d)] = float(math.pow(10.0, min(log_rtt, 7.0)))
        return out

    impute.version = version         # type: ignore[attr-defined]
    impute.meta = meta               # type: ignore[attr-defined]
    return impute
