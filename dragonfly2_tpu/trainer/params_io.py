"""Model-blob serialization: numpy-only, importable by the scheduler.

The npz archive of the flattened param pytree (no pickle) is the contract
between the trainer (writes after fitting, ``trainer/training.py``), the
manager registry (stores the blob), and the scheduler's serving side
(``trainer/serving.py`` reloads with plain numpy — jax never enters the
scheduling process).
"""

from __future__ import annotations

import hashlib
import io
import json

import numpy as np


def _flatten(tree, prefix="") -> dict:
    out: dict = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat: dict):
    root: dict = {}
    for key, arr in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    def listify(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.isdigit() for k in node):
            return [listify(node[str(i)]) for i in range(len(node))]
        return {k: listify(v) for k, v in node.items()}

    return listify(root)


def serialize_params(params, meta: dict) -> bytes:
    buf = io.BytesIO()
    flat = {k: np.asarray(v) for k, v in _flatten(params).items()}
    flat["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(buf, **flat)
    return buf.getvalue()


def deserialize_params(data: bytes) -> tuple[dict, dict]:
    with np.load(io.BytesIO(data)) as z:
        flat = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode()) \
            if "__meta__" in z.files else {}
    return _unflatten(flat), meta


def version_of(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]
