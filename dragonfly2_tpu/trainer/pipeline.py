"""Offline training pipeline: scheduler records JSONL → parent-quality MLP.

The live loop (announcer upload → ``trainer/service.py`` spool → fit)
needs a running trainer; this module is the same fit reachable from a
file. It reads the scheduler's own ``records_dir`` artifacts
(``download.jsonl`` + its rotated ``.1`` half — the exact files
``scheduler/records.py`` writes), folds the ``kind=decision`` candidate
rows with their joined ``kind=piece`` outcomes into trainer rows
(``features.decision_outcome_rows``, v1 and v2 schemas both parse), and
runs the seeded deterministic fit from ``trainer/training.py``. Same
(rows, seed) → same blob bytes → same ``version_of`` hash: dfbench
--pr19 gates refit-to-refit determinism on this, and the rollout path
dedupes on it.

Usage:
    python -m dragonfly2_tpu.trainer.pipeline --records records/ \
        --out bandwidth_mlp.npz [--seed 7] [--json]

``train_from_records`` is also the supervision policy the live trainer
service applies to its spool: decision-outcome folds when the records
carry joined decisions, raw piece rows as the cold-start fallback.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from . import features, training

log = logging.getLogger("df.trainer.pipeline")

MIN_TRAIN_ROWS = 8       # matches train_mlp's usable-row floor

# a pod's decision-fold snapshot is hundreds of rows, far under one
# batch — an "epoch" is a single optimizer step, so train_mlp's default
# 40 never converges (loss stalls ~8 on folds whose labels span barely
# 0.1). 600 steps takes the fit to ~2e-3 and flips the replay-regret
# comparison in the learned model's favour; still < 1s of jitted steps
DEFAULT_EPOCHS = 600


def load_records_jsonl(path: str) -> list[dict]:
    """Rows from a records JSONL file, or a records dir holding
    ``download.jsonl`` (the rotated ``.1`` half first, so decisions
    precede their outcomes in replay order). Torn tail lines of a live
    file are skipped, never fatal — the scheduler may still be writing.
    """
    if os.path.isdir(path):
        base = os.path.join(path, "download.jsonl")
        paths = [p for p in (base + ".1", base) if os.path.exists(p)]
        if not paths:
            raise FileNotFoundError(f"no download.jsonl under {path}")
    else:
        paths = [path]
    rows: list[dict] = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue       # torn tail line of a live file
    return rows


def training_rows(rows: list[dict]) -> tuple[list[dict], str]:
    """The supervision policy: prefer decision-outcome folds (one row per
    (ruling, parent) pair that actually served, labelled by observed
    bandwidth), fall back to raw piece rows when the records carry no
    joinable decisions (cold fleet, decision sink disarmed). Returns
    (rows, source) with source in {"decision_outcomes", "piece_rows"}.
    """
    folded = features.decision_outcome_rows(rows)
    if folded:
        return folded, "decision_outcomes"
    return rows, "piece_rows"


def train_decision_model(rows: list[dict], *, seed: int = 0,
                         epochs: int = DEFAULT_EPOCHS, batch_size: int = 512,
                         use_mesh: bool = True
                         ) -> tuple[bytes, dict] | None:
    """Seeded deterministic fit of the parent-quality MLP over raw
    scheduler record rows (decisions + outcomes mixed, any schema
    version). Returns (blob, metrics) or None when the rows hold too few
    usable feature/label pairs; metrics carry the supervision source and
    fold count on top of ``train_mlp``'s own."""
    fit_rows, source = training_rows(rows)
    fitted = training.train_mlp(fit_rows, epochs=epochs,
                                batch_size=batch_size, seed=seed,
                                use_mesh=use_mesh)
    if fitted is None and source == "decision_outcomes":
        # a handful of joined decisions (fleet mid-upgrade, decision sink
        # freshly armed) must not starve the fit when raw piece rows are
        # plentiful — degrade to the piece-row supervision
        fitted = training.train_mlp(rows, epochs=epochs,
                                    batch_size=batch_size, seed=seed,
                                    use_mesh=use_mesh)
        source = "piece_rows"
    if fitted is None:
        log.info("pipeline: %d record rows folded to %d %s rows — below "
                 "the trainable floor", len(rows), len(fit_rows), source)
        return None
    blob, metrics = fitted
    metrics["supervision"] = source
    metrics["record_rows"] = len(rows)
    return blob, metrics


def train_from_records(path: str, *, seed: int = 0,
                       epochs: int = DEFAULT_EPOCHS, batch_size: int = 512,
                       use_mesh: bool = True
                       ) -> tuple[bytes, dict] | None:
    """File-to-model: everything above in one call."""
    return train_decision_model(load_records_jsonl(path), seed=seed,
                                epochs=epochs, batch_size=batch_size,
                                use_mesh=use_mesh)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="df-trainer-pipeline",
        description="offline fit: scheduler records JSONL -> versioned "
                    "parent-quality MLP blob")
    p.add_argument("--records", required=True,
                   help="records JSONL file, or the scheduler records dir "
                   "holding download.jsonl")
    p.add_argument("--out", default="",
                   help="blob output path (omit to fit without writing)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--epochs", type=int, default=DEFAULT_EPOCHS)
    p.add_argument("--json", action="store_true",
                   help="machine-readable fit metrics on stdout")
    args = p.parse_args(argv)
    try:
        fitted = train_from_records(args.records, seed=args.seed,
                                    epochs=args.epochs)
    except (OSError, ValueError) as exc:
        print(f"pipeline: {type(exc).__name__}: {exc}", file=sys.stderr)
        return 1
    if fitted is None:
        print("pipeline: too few usable rows to fit", file=sys.stderr)
        return 1
    blob, metrics = fitted
    if args.out:
        with open(args.out, "wb") as f:
            f.write(blob)
    if args.json:
        print(json.dumps(metrics, indent=2, sort_keys=True))
    else:
        print(f"pipeline: fit {metrics['model']}@{metrics['version']} on "
              f"{metrics['rows']} rows ({metrics['supervision']}), loss "
              f"{metrics['first_epoch_loss']:.4f} -> "
              f"{metrics['final_loss']:.4f}"
              + (f", wrote {args.out}" if args.out else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
