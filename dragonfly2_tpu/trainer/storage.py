"""Trainer dataset storage: uploaded rows keyed by (source host, dataset).

Role parity: reference ``trainer/storage/storage.go:148`` — one file per
uploading scheduler instance, created on first chunk, cleared after a
training run consumes it. Datasets are JSONL (gzip on the wire, stored
decompressed so training can stream rows without re-inflating).
"""

from __future__ import annotations

import gzip
import json
import logging
import os
import re

log = logging.getLogger("df.trainer.storage")

DATASETS = ("download", "networktopology")


def _safe_key(hostname: str, ip: str) -> str:
    raw = f"{hostname}_{ip}"
    return re.sub(r"[^A-Za-z0-9_.-]", "-", raw) or "unknown"


class TrainerStorage:
    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _path(self, dataset: str, hostname: str, ip: str) -> str:
        if dataset not in DATASETS:
            raise ValueError(f"unknown dataset {dataset!r}")
        return os.path.join(self.base_dir,
                            f"{dataset}_{_safe_key(hostname, ip)}.jsonl")

    def append_chunk(self, dataset: str, hostname: str, ip: str,
                     chunk: bytes, *, compressed: bool = True) -> int:
        """Append one uploaded chunk; returns rows written."""
        data = gzip.decompress(chunk) if compressed else chunk
        text = data.decode("utf-8")
        rows = sum(1 for line in text.splitlines() if line.strip())
        with open(self._path(dataset, hostname, ip), "a",
                  encoding="utf-8") as f:
            f.write(text if text.endswith("\n") or not text else text + "\n")
        return rows

    def rows(self, dataset: str) -> list[dict]:
        """All rows of one dataset across every uploader."""
        out: list[dict] = []
        prefix = f"{dataset}_"
        for name in sorted(os.listdir(self.base_dir)):
            if not (name.startswith(prefix) and name.endswith(".jsonl")):
                continue
            with open(os.path.join(self.base_dir, name),
                      encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        log.warning("bad row in %s skipped", name)
        return out

    def requeue_rows(self, dataset: str, rows: list[dict]) -> None:
        """Return consumed rows after a FAILED fit (at-least-once delivery:
        the announcer's upload already succeeded, so losing the snapshot
        here would silently drop the dataset)."""
        if not rows:
            return
        path = self._path(dataset, "requeued", "local")
        with open(path, "a", encoding="utf-8") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")

    def clear(self, dataset: str | None = None) -> None:
        """Drop consumed datasets after a training run (reference clears
        per-host files the same way)."""
        for name in os.listdir(self.base_dir):
            if not name.endswith(".jsonl"):
                continue
            if dataset is None or name.startswith(f"{dataset}_"):
                os.unlink(os.path.join(self.base_dir, name))
