"""Trainer bootstrap: storage + gRPC service + manager link.

Role parity: reference ``trainer/trainer.go:187`` New/Serve — wires the
dataset storage, the Train sink, and the manager connection the fitted
models are registered through.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..rpc.server import RPCServer
from .service import TrainerService, build_service
from .storage import TrainerStorage

log = logging.getLogger("df.trainer.server")


@dataclass
class TrainerConfig:
    listen_ip: str = "0.0.0.0"
    advertise_ip: str = "127.0.0.1"
    port: int = 0                       # 0 = ephemeral
    data_dir: str = ""                  # dataset spool; "" = ./trainer-data
    manager_addresses: list[str] = field(default_factory=list)
    min_rows: int = 32                  # don't fit on noise


class Trainer:
    def __init__(self, cfg: TrainerConfig):
        self.cfg = cfg
        self.storage = TrainerStorage(cfg.data_dir or "./trainer-data")
        self.manager = None
        self.service: TrainerService | None = None
        self.rpc: RPCServer | None = None
        self.port: int | None = None

    @property
    def address(self) -> str:
        return f"{self.cfg.advertise_ip}:{self.port}"

    async def start(self) -> None:
        if self.cfg.manager_addresses:
            from ..rpc.manager_link import ManagerLink
            self.manager = ManagerLink(self.cfg.manager_addresses)
        self.service = TrainerService(self.storage, manager=self.manager,
                                      min_rows=self.cfg.min_rows)
        self.rpc = RPCServer(f"{self.cfg.listen_ip}:{self.cfg.port}")
        self.rpc.register(build_service(self.service))
        await self.rpc.start()
        self.port = self.rpc.port
        log.info("trainer up on %s (spool=%s)", self.address,
                 self.storage.base_dir)

    async def stop(self) -> None:
        if self.manager is not None:
            await self.manager.close()
        if self.rpc is not None:
            await self.rpc.stop(0.5)
