"""Trainer: fits the bandwidth-prediction models on TPU and serves them back
into scheduler decisions.

Role parity: reference ``trainer/`` — the gRPC dataset sink exists there but
model fitting is a TODO stub (``trainer/training/training.go:80-97``); this
package completes the loop in JAX (BASELINE config #5): an MLP piece-cost
predictor and a host-graph GNN, trained with a pjit-able step over a
``jax.sharding.Mesh``.
"""
