"""Shared feature schema: scheduler records → model tensors.

Role parity: reference ``scheduler/storage/types.go:30-297`` defines the
download-record schema the trainer consumes; the reference never finished
the consuming side (``trainer/training/training.go:80-97`` stubs). Here the
schema is the contract between three parties, kept in one module:

* ``scheduler/records.py`` writes rows with ``PARENT_FEATURES`` +
  ``label_from_cost`` labels at piece-report time;
* ``scheduler/evaluator_ml.py`` builds the identical row at scoring time
  (``MLEvaluator.feature_row`` delegates here);
* this module turns accumulated rows into dense numpy arrays for
  ``trainer/models.py`` (MLP) and topology snapshots into padded graph
  batches (GNN).
"""

from __future__ import annotations

import logging
import math

import numpy as np

_log = logging.getLogger("df.trainer.features")

# Feature layout for one (child, parent) candidate row. Any change here is
# a model-version bump: the scheduler refuses models whose feature_dim
# doesn't match (see trainer/training.py metadata).
# Registry names (numpy-only module so the scheduler can import them
# without dragging jax/optax into its process)
MLP_MODEL_NAME = "bandwidth_mlp"
GNN_MODEL_NAME = "topology_gnn"

PARENT_FEATURES = (
    "piece_score",            # parent finished pieces / total
    "upload_success_ratio",   # parent host historical upload success
    "free_upload_score",      # free slots / limit on parent host
    "host_type_score",        # seed classes rank above normal peers
    "locality_score",         # LOCAL > ICI > DCN > WAN (tpu/topology.py)
    "finished_pieces",        # absolute piece count held by parent
    "concurrent_uploads",     # in-flight uploads on parent host
)
FEATURE_DIM = len(PARENT_FEATURES)

# Schema version, stamped into trained-model metadata so the scheduler
# refuses mismatched arrays. v2 (cross-pod federation): NODE_FEATURES
# grew ``pod_id`` and decision-outcome rows carry ``link_tier``/``pod``
# METADATA columns — PARENT_FEATURES (and therefore FEATURE_DIM and the
# committed BENCH_pr8 candidate rows) is deliberately UNCHANGED, so
# every logged v1 decision row still parses and replays byte-identically.
FEATURE_SCHEMA_VERSION = 2

# GNN graph schema: nodes = hosts, edges = probed (src, dst) links.
# ``pod_id`` is a dense integer the caller assigns per pod (e.g. index
# into the sorted pod list; -1 = no pod identity) — the GNN sees the
# federation boundary the scheduler routes by, so learned imputation can
# tell "slow because pod-crossing" from "slow because that host".
NODE_FEATURES = ("host_type", "upload_ratio", "upload_load", "slice_id",
                 "coord_x", "coord_y", "pod_id")
EDGE_FEATURES = ("log_rtt", "link_class")

# Pad edge lists to the next bucket so XLA recompiles only on bucket growth
# (static shapes; SURVEY §7 "emulating a pod in CI" note applies to shapes
# too — dynamic shapes would retrace per report).
_EDGE_BUCKETS = (32, 128, 512, 2048, 8192)
_NODE_BUCKETS = (16, 64, 256, 1024)


def label_from_cost(piece_length: int, cost_ms: float) -> float:
    """Observed goodness of a parent from one piece download.

    Bounded (0, 1]: log-throughput squashed so the MLP regresses a target
    in the same range as the rule-based score it replaces. 4 MiB in 40 ms
    (~100 MB/s) ≈ 0.62; 4 MiB in 4 ms (1 GB/s, ICI-class) ≈ 0.78; stalls
    (<1 MB/s) fall below 0.3.
    """
    mbps = (piece_length / 1e6) / (max(cost_ms, 0.1) / 1e3)
    return 1.0 / (1.0 + math.exp(-0.7 * (math.log10(max(mbps, 1e-3)) - 0.5)))


def records_to_arrays(rows: list[dict]) -> dict[str, np.ndarray] | None:
    """Download-record rows → {"x": [N, FEATURE_DIM] f32, "y": [N] f32}.

    Rows missing features (back-source records have no parent) are skipped.
    """
    xs, ys = [], []
    for row in rows:
        feats = row.get("features")
        label = row.get("label")
        if feats is None or label is None or len(feats) != FEATURE_DIM:
            continue
        xs.append(feats)
        ys.append(label)
    if not xs:
        return None
    return {"x": np.asarray(xs, np.float32), "y": np.asarray(ys, np.float32)}


def decision_outcome_rows(rows: list[dict]) -> list[dict]:
    """The decision-ledger join contract (scheduler/decision_ledger.py):
    fold ``kind=decision`` candidate rows with the ``kind=piece`` outcomes
    that joined back to them into trainer-ready rows.

    Each output row is one (decision, parent) pair that actually served:
    the candidate's scoring-time feature vector (``PARENT_FEATURES``
    layout, exactly what the ``ml`` evaluator would have seen), the mean
    observed ``label_from_cost`` label over the pieces it delivered, and
    the rank the live evaluator predicted. ``records_to_arrays``-
    compatible, so a learned parent-quality model trains on the precise
    rows the offline A/B (``dfbench --pr8``) judges it against — and the
    rank column is the supervision a learning-to-rank variant needs.
    """
    decisions: dict[str, dict] = {}
    for row in rows:
        if row.get("kind") == "decision" and row.get("decision_id"):
            decisions[row["decision_id"]] = row
    stats: dict[tuple, list] = {}
    for row in rows:
        if row.get("kind") != "piece" or not row.get("decision_id"):
            continue
        if row["decision_id"] not in decisions:
            continue
        key = (row["decision_id"], row.get("parent_peer_id", ""))
        agg = stats.setdefault(key, [0, 0.0])
        agg[0] += 1
        agg[1] += float(row.get("label") or 0.0)
    out: list[dict] = []
    for (did, parent_id), (n, label_sum) in stats.items():
        decision = decisions[did]
        cand = next((c for c in decision.get("candidates") or []
                     if c.get("peer_id") == parent_id), None)
        if cand is None or len(cand.get("features") or []) != FEATURE_DIM:
            continue
        out.append({
            "decision_id": did,
            "task_id": decision.get("task_id", ""),
            "peer_id": decision.get("peer_id", ""),
            "parent_peer_id": parent_id,
            "features": [float(v) for v in cand["features"]],
            "label": label_sum / n,
            "rank": cand.get("rank"),
            "pieces": n,
            # federation metadata (v2, defaults keep v1/BENCH_pr8 rows
            # parsing): which link tier the ruling chose and which pod
            # the child sat in — a learned evaluator can condition on
            # the DCN boundary without the feature array changing shape
            "link_tier": cand.get("link_tier", ""),
            "pod": (decision.get("federation") or {}).get("pod", ""),
        })
    return out


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _node_row(host_row: dict) -> list[float]:
    return [float(host_row.get("host_type", 0.5)),
            float(host_row.get("upload_ratio", 1.0)),
            float(host_row.get("upload_load", 0.0)),
            float(host_row.get("slice_id", -1)),
            float(host_row.get("coord_x", -1)),
            float(host_row.get("coord_y", -1)),
            float(host_row.get("pod_id", -1))]


def topology_to_graph(topo_rows: list[dict],
                      host_rows: dict[str, dict] | None = None
                      ) -> dict[str, np.ndarray] | None:
    """Topology snapshot rows → padded GNN batch.

    topo_rows: ``TopologyStore.snapshot_rows()`` dicts (src, dst,
    avg_rtt_us, count). host_rows: optional per-host feature dicts keyed by
    host id. Label = observed inverse log-RTT (bandwidth proxy) — the GNN
    learns to impute it for unprobed links.
    """
    if not topo_rows:
        return None
    ids: list[str] = []
    index: dict[str, int] = {}
    for row in topo_rows:
        for hid in (row["src"], row["dst"]):
            if hid not in index:
                index[hid] = len(ids)
                ids.append(hid)
    n_pad = _bucket(len(ids), _NODE_BUCKETS)
    if len(ids) > n_pad:
        # beyond the largest bucket: keep edges whose hosts fit, drop the
        # rest loudly (no silent caps)
        kept = [r for r in topo_rows
                if index[r["src"]] < n_pad and index[r["dst"]] < n_pad]
        _log.warning("topology graph truncated: %d hosts > bucket %d; "
                     "%d/%d edges kept", len(ids), n_pad, len(kept),
                     len(topo_rows))
        topo_rows = kept
        ids = ids[:n_pad]
    e_pad = _bucket(len(topo_rows), _EDGE_BUCKETS)
    if len(topo_rows) > e_pad:
        _log.warning("topology graph truncated: %d edges > bucket %d",
                     len(topo_rows), e_pad)
    nodes = np.zeros((n_pad, len(NODE_FEATURES)), np.float32)
    for hid, i in index.items():
        if i < n_pad:
            nodes[i] = _node_row((host_rows or {}).get(hid, {}))
    edge_src = np.zeros((e_pad,), np.int32)
    edge_dst = np.zeros((e_pad,), np.int32)
    edge_feat = np.zeros((e_pad, len(EDGE_FEATURES)), np.float32)
    edge_mask = np.zeros((e_pad,), np.float32)
    y = np.zeros((e_pad,), np.float32)
    for e, row in enumerate(topo_rows[:e_pad]):
        edge_src[e] = index[row["src"]]
        edge_dst[e] = index[row["dst"]]
        log_rtt = math.log10(max(float(row["avg_rtt_us"]), 1.0))
        edge_feat[e] = (log_rtt, float(row.get("link_class", 0.0)))
        edge_mask[e] = 1.0
        # bandwidth proxy: 10us (ICI) -> ~1.0, 10ms (DCN/WAN) -> ~0.2
        y[e] = 1.0 / (1.0 + max(0.0, log_rtt - 1.0))
    return {"nodes": nodes, "edge_src": edge_src, "edge_dst": edge_dst,
            "edge_feat": edge_feat, "edge_mask": edge_mask, "y": y,
            "host_ids": np.asarray(ids)}
