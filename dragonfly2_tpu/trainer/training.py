"""Training runs: fit the MLP/GNN on uploaded scheduler records.

Role parity: reference ``trainer/training/training.go:60-97`` — the
pipeline exists there, the fitting is a TODO stub. This module completes
it: minibatch adamw over the fused ``sharded_train_step`` from
``trainer/models.py`` (dp×tp mesh when >1 device; single-device jit
otherwise), with model serialization + content-addressed versioning for the
manager registry (reference ``manager/models/model.go:36``).

Serialization is npz (numpy archive) of the flattened param pytree — no
pickle; the scheduler's serving side (``trainer/serving.py``) reloads it
with plain numpy and never needs jax on the hot path.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from . import features, models
from .params_io import serialize_params, version_of  # noqa: F401 - re-export

log = logging.getLogger("df.trainer.training")

MLP_MODEL_NAME = features.MLP_MODEL_NAME
GNN_MODEL_NAME = features.GNN_MODEL_NAME


# ---------------------------------------------------------------- fitting

def _make_step(loss_fn, opt, mesh):
    if mesh is not None and mesh.devices.size > 1:
        return models.sharded_train_step(loss_fn, opt, mesh)
    import jax
    return jax.jit(models.make_train_step(loss_fn, opt))


def train_mlp(rows: list[dict], *, epochs: int = 40, batch_size: int = 512,
              lr: float = 1e-3, seed: int = 0,
              use_mesh: bool = True) -> tuple[bytes, dict] | None:
    """Fit the parent-goodness MLP on download-record rows.

    Returns (model_bytes, metrics) or None when the rows hold no usable
    feature/label pairs. Batch dp-sharded + weights tp-sharded when more
    than one device is visible.
    """
    import jax

    data = features.records_to_arrays(rows)
    if data is None or data["x"].shape[0] < 8:
        return None
    n = data["x"].shape[0]
    rng = np.random.default_rng(seed)
    key = jax.random.PRNGKey(seed)
    params = models.init_mlp(key)
    opt = models.make_optimizer(lr)
    mesh = models.make_mesh() if use_mesh and len(jax.devices()) > 1 else None
    if mesh is not None:
        params = models.shard_params(params, mesh)
    opt_state = opt.init(params)
    step = _make_step(models.mlp_loss, opt, mesh)

    bs = min(batch_size, n)
    # static batch shape: pad the epoch to a multiple of bs via wraparound
    steps_per_epoch = max(1, n // bs)
    first_loss = last_loss = None
    t0 = time.monotonic()
    for _ in range(epochs):
        order = rng.permutation(n)
        for s in range(steps_per_epoch):
            idx = order[(s * bs) % n:(s * bs) % n + bs]
            if idx.size < bs:
                idx = np.concatenate([idx, order[:bs - idx.size]])
            batch = {"x": data["x"][idx], "y": data["y"][idx]}
            if mesh is not None:
                batch = models.shard_batch(batch, mesh)
            params, opt_state, loss = step(params, opt_state, batch)
        loss_f = float(loss)
        if first_loss is None:
            first_loss = loss_f
        last_loss = loss_f
    metrics = {
        "model": MLP_MODEL_NAME,
        "rows": int(n),
        "epochs": epochs,
        "seed": int(seed),
        "first_epoch_loss": first_loss,
        "final_loss": last_loss,
        "feature_dim": features.FEATURE_DIM,
        "feature_names": list(features.PARENT_FEATURES),
        "schema_version": features.FEATURE_SCHEMA_VERSION,
        "devices": len(jax.devices()),
    }
    host_params = jax.tree_util.tree_map(np.asarray, params)
    data_bytes = serialize_params(host_params, metrics)
    # version + wall clock ride in the RETURNED metrics only: the
    # serialized meta must be a function of (rows, seed) alone so the
    # same fit yields the same blob bytes — the rollout path dedupes on
    # version and dfbench --pr19 gates refit-to-refit determinism on it
    metrics["version"] = version_of(data_bytes)
    metrics["train_seconds"] = time.monotonic() - t0
    log.info("mlp fit: rows=%d loss %.4f -> %.4f (%.1fs, %d devices)",
             n, first_loss, last_loss, metrics["train_seconds"],
             metrics["devices"])
    return data_bytes, metrics


def train_gnn(topo_rows: list[dict], *, epochs: int = 60, lr: float = 1e-3,
              seed: int = 0, use_mesh: bool = True
              ) -> tuple[bytes, dict] | None:
    """Fit the host-graph GNN on topology snapshot rows (bandwidth
    imputation for unprobed links)."""
    import jax

    graph = features.topology_to_graph(topo_rows)
    if graph is None or float(graph["edge_mask"].sum()) < 4:
        return None
    batch = {k: v for k, v in graph.items() if k != "host_ids"}
    key = jax.random.PRNGKey(seed)
    params = models.init_gnn(key)
    opt = models.make_optimizer(lr)
    mesh = models.make_mesh() if use_mesh and len(jax.devices()) > 1 else None
    if mesh is not None:
        params = models.shard_params(params, mesh)
        # graph batches replicate (node/edge dims aren't batch dims)
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        batch = {k: _jax.device_put(v, NamedSharding(mesh, P()))
                 for k, v in batch.items()}
    opt_state = opt.init(params)
    step = _make_step(models.gnn_loss, opt, mesh)
    first_loss = last_loss = None
    t0 = time.monotonic()
    for _ in range(epochs):
        params, opt_state, loss = step(params, opt_state, batch)
        loss_f = float(loss)
        if first_loss is None:
            first_loss = loss_f
        last_loss = loss_f
    metrics = {
        "model": GNN_MODEL_NAME,
        "edges": int(graph["edge_mask"].sum()),
        "nodes": int(len(graph["host_ids"])),
        "node_features": list(features.NODE_FEATURES),
        "schema_version": features.FEATURE_SCHEMA_VERSION,
        "epochs": epochs,
        "seed": int(seed),
        "first_epoch_loss": first_loss,
        "final_loss": last_loss,
        "devices": len(jax.devices()),
    }
    host_params = jax.tree_util.tree_map(np.asarray, params)
    data_bytes = serialize_params(host_params, metrics)
    # same determinism contract as train_mlp: wall clock stays out of
    # the serialized meta so identical (rows, seed) → identical bytes
    metrics["version"] = version_of(data_bytes)
    metrics["train_seconds"] = time.monotonic() - t0
    log.info("gnn fit: edges=%d loss %.4f -> %.4f (%.1fs)",
             metrics["edges"], first_loss, last_loss,
             metrics["train_seconds"])
    return data_bytes, metrics
