"""hdfs:// origin client over the WebHDFS REST surface.

Role parity: reference ``pkg/source/clients/hdfs`` (native RPC client).
TPU-native choice: WebHDFS — every Hadoop distribution serves it, it needs
no protocol library, and range reads map to ``op=OPEN&offset&length``
(WebHDFS does NOT honor the HTTP Range header; offsets ride the query).

URL form: ``hdfs://namenode:9870/path/to/file`` (the port is the NameNode
HTTP port). Auth: ``user.name`` from ``DF_HDFS_USER`` (simple auth);
kerberized clusters front WebHDFS with a gateway.
"""

from __future__ import annotations

import os
from typing import AsyncIterator
from urllib.parse import quote

import aiohttp

from ..common.errors import Code, DFError
from .client import (ListEntry, SessionPool, SourceRequest, SourceResponse,
                     register_client, timeout_for)

_CHUNK = 1 << 20


def _split(url: str) -> tuple[str, str]:
    rest = url.split("://", 1)[1]
    authority, _, path = rest.partition("/")
    if not authority or not path:
        raise DFError(Code.INVALID_ARGUMENT, f"bad hdfs url: {url}")
    return authority, "/" + path


def _api(url: str, op: str, **params: str) -> str:
    authority, path = _split(url)
    q = f"op={op}"
    user = os.environ.get("DF_HDFS_USER", "")
    if user:
        q += f"&user.name={quote(user)}"
    for k, v in params.items():
        q += f"&{k}={quote(str(v))}"
    return (f"http://{authority}/webhdfs/v1"
            f"{quote(path, safe='/-_.~')}?{q}")


class HDFSSourceClient:
    def __init__(self) -> None:
        self._pool = SessionPool()

    async def _session(self) -> aiohttp.ClientSession:
        return await self._pool.get()

    async def close(self) -> None:
        await self._pool.close()

    async def _status(self, req: SourceRequest) -> dict:
        s = await self._session()
        try:
            resp_cm = s.get(_api(req.url, "GETFILESTATUS"),
                            headers=req.header, timeout=timeout_for(req))
        except aiohttp.ClientError as exc:
            raise DFError(Code.SOURCE_ERROR,
                          f"webhdfs: {exc}") from None
        async with resp_cm as resp:
            if resp.status == 404:
                raise DFError(Code.SOURCE_NOT_FOUND, req.url)
            if resp.status >= 400:
                raise DFError(Code.SOURCE_ERROR,
                              f"webhdfs {resp.status}: {req.url}")
            body = await resp.json()
            return body.get("FileStatus", {})

    async def content_length(self, req: SourceRequest) -> int:
        total = int((await self._status(req)).get("length", -1))
        if req.range is not None and total >= 0:
            return min(req.range.length, max(0, total - req.range.start))
        return total

    async def supports_range(self, req: SourceRequest) -> bool:
        return True                   # offset/length on op=OPEN

    async def last_modified(self, req: SourceRequest) -> str:
        ms = (await self._status(req)).get("modificationTime", 0)
        return str(ms)

    async def download(self, req: SourceRequest) -> SourceResponse:
        params: dict[str, str] = {}
        if req.range is not None:
            params["offset"] = str(req.range.start)
            params["length"] = str(req.range.length)
        s = await self._session()
        # WebHDFS redirects OPEN to a datanode; aiohttp follows it
        try:
            resp = await s.get(_api(req.url, "OPEN", **params),
                               headers=req.header, allow_redirects=True,
                               timeout=timeout_for(req))
        except aiohttp.ClientError as exc:
            raise DFError(Code.SOURCE_ERROR,
                          f"webhdfs OPEN: {exc}") from None
        if resp.status == 404:
            resp.close()
            raise DFError(Code.SOURCE_NOT_FOUND, req.url)
        if resp.status >= 400:
            status = resp.status
            resp.close()
            raise DFError(Code.SOURCE_ERROR,
                          f"webhdfs OPEN {status}: {req.url}")
        length = int(resp.headers.get("Content-Length", "-1"))

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for data in resp.content.iter_chunked(_CHUNK):
                    yield data
            finally:
                resp.close()

        return SourceResponse(
            status=206 if req.range is not None else resp.status,
            content_length=length, total_length=-1, supports_range=True,
            header=dict(resp.headers), chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        s = await self._session()
        try:
            resp_cm = s.get(_api(req.url, "LISTSTATUS"),
                            headers=req.header, timeout=timeout_for(req))
        except aiohttp.ClientError as exc:
            raise DFError(Code.SOURCE_ERROR,
                          f"webhdfs LISTSTATUS: {exc}") from None
        async with resp_cm as resp:
            if resp.status >= 400:
                raise DFError(Code.SOURCE_ERROR,
                              f"webhdfs LISTSTATUS {resp.status}: {req.url}")
            body = await resp.json()
        out = []
        for st in body.get("FileStatuses", {}).get("FileStatus", []):
            name = st.get("pathSuffix", "")
            out.append(ListEntry(
                url=req.url.rstrip("/") + "/" + name, name=name,
                is_dir=st.get("type") == "DIRECTORY",
                content_length=int(st.get("length", -1))))
        return out


register_client(["hdfs"], HDFSSourceClient())
