"""memory:// origin client — in-process blob registry for tests and for the
dfcache import path (content injected locally, then P2P-distributed)."""

from __future__ import annotations

from typing import AsyncIterator

from ..common.errors import Code, DFError
from .client import ListEntry, SourceRequest, SourceResponse, register_client

_BLOBS: dict[str, bytes] = {}


def put_blob(name: str, data: bytes) -> str:
    """Register a blob; returns its memory:// URL."""
    _BLOBS[name] = data
    return f"memory://{name}"


def delete_blob(name: str) -> None:
    _BLOBS.pop(name, None)


def _name(url: str) -> str:
    return url.split("://", 1)[1] if "://" in url else url


class MemorySourceClient:
    async def content_length(self, req: SourceRequest) -> int:
        blob = _BLOBS.get(_name(req.url))
        if blob is None:
            raise DFError(Code.SOURCE_NOT_FOUND, f"no blob {req.url}")
        if req.range is not None:
            return min(req.range.length, max(0, len(blob) - req.range.start))
        return len(blob)

    async def supports_range(self, req: SourceRequest) -> bool:
        return True

    async def last_modified(self, req: SourceRequest) -> str:
        return ""

    async def download(self, req: SourceRequest) -> SourceResponse:
        blob = _BLOBS.get(_name(req.url))
        if blob is None:
            raise DFError(Code.SOURCE_NOT_FOUND, f"no blob {req.url}")
        total = len(blob)
        if req.range is not None:
            blob = blob[req.range.start:req.range.end]

        async def chunks() -> AsyncIterator[bytes]:
            step = 1 << 18
            for i in range(0, len(blob), step):
                yield blob[i:i + step]

        return SourceResponse(status=200, content_length=len(blob),
                              total_length=total, supports_range=True,
                              chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        prefix = _name(req.url)
        return [ListEntry(url=f"memory://{k}", name=k, is_dir=False,
                          content_length=len(v))
                for k, v in sorted(_BLOBS.items()) if k.startswith(prefix)]


register_client(["memory"], MemorySourceClient())
