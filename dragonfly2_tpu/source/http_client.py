"""http(s):// origin client over aiohttp.

Parity notes: HEAD for metadata with GET-range fallback (some origins reject
HEAD), Range header for piece-group reads, Accept-Ranges/Content-Range
detection, Last-Modified passthrough (reference ``source/clients/httpprotocol``).
"""

from __future__ import annotations

from typing import AsyncIterator

import aiohttp

from ..common.errors import Code, DFError
from .client import ListEntry, SourceRequest, SourceResponse, register_client

_CHUNK = 1 << 20


def _timeout(req: SourceRequest) -> aiohttp.ClientTimeout:
    if req.timeout_s and req.timeout_s > 0:
        return aiohttp.ClientTimeout(total=req.timeout_s)
    return aiohttp.ClientTimeout(total=None, sock_connect=30, sock_read=120)


def _status_error(status: int, url: str, headers=None) -> DFError:
    if status == 404:
        return DFError(Code.SOURCE_NOT_FOUND, f"origin 404: {url}")
    if status in (401, 403):
        return DFError(Code.SOURCE_AUTH_ERROR, f"origin {status}: {url}")
    err = DFError(Code.SOURCE_ERROR, f"origin status {status}: {url}")
    if headers is not None and status in (429, 503):
        # surface the origin's own pacing hint so the back-source retry
        # ladder (common/retry.py) waits what the origin asked for instead
        # of its default backoff
        value = str(headers.get("Retry-After", "")).strip()
        if value.isdigit():
            err.retry_after_ms = int(value) * 1000
    return err


class HTTPSourceClient:
    def __init__(self) -> None:
        # sessions are loop-bound; the registry client is a process singleton
        # that may serve several asyncio.run lifetimes (CLIs, tests)
        self._sessions: dict[int, aiohttp.ClientSession] = {}
        self._ssl = None           # None: system trust; False: no verify;
                                   # SSLContext: custom CA bundle

    def set_tls(self, *, insecure: bool = False, ca_file: str = "") -> None:
        """TLS trust for https origins: a private registry signed by a
        custom CA (or the proxy's own MITM CA) needs ``ca_file`` — added ON
        TOP of system trust (public origins must keep working while a
        private CA is configured); ``insecure`` disables verification
        (tests only)."""
        import ssl as _ssl

        if insecure:
            self._ssl = False
        elif ca_file:
            ctx = _ssl.create_default_context()
            ctx.load_verify_locations(cafile=ca_file)
            self._ssl = ctx
        else:
            self._ssl = None

    async def _get_session(self) -> aiohttp.ClientSession:
        import asyncio

        loop = asyncio.get_running_loop()
        session = self._sessions.get(id(loop))
        if session is None or session.closed:
            session = aiohttp.ClientSession()
            self._sessions[id(loop)] = session
            self._sessions = {k: s for k, s in self._sessions.items()
                              if not s.closed}
        return session

    async def close(self) -> None:
        import asyncio

        loop = asyncio.get_running_loop()
        session = self._sessions.pop(id(loop), None)
        if session and not session.closed:
            await session.close()

    async def _head(self, req: SourceRequest) -> tuple[int, dict]:
        # Probes carry ``Connection: close`` so their connections never enter
        # the pool: a misbehaving origin that writes a body for HEAD (seen in
        # the wild; any hand-rolled streaming handler) otherwise leaves the
        # stale body in the pooled connection and the next GET that reuses
        # it hangs waiting for response headers that never come.
        session = await self._get_session()
        probe_headers = {**req.header, "Connection": "close"}
        try:
            async with session.head(req.url, headers=probe_headers,
                                    allow_redirects=True, ssl=self._ssl,
                                    timeout=_timeout(req)) as resp:
                if resp.status < 400:
                    return resp.status, dict(resp.headers)
        except aiohttp.ClientError:
            pass
        # some origins reject HEAD: 1-byte ranged GET as metadata probe
        probe = {**probe_headers, "Range": "bytes=0-0"}
        try:
            async with session.get(req.url, headers=probe, allow_redirects=True,
                                   ssl=self._ssl,
                                   timeout=_timeout(req)) as resp:
                if resp.status >= 400:
                    raise _status_error(resp.status, req.url,
                                        headers=resp.headers)
                headers = dict(resp.headers)
                cr = headers.get("Content-Range", "")
                if "/" in cr:
                    headers["Content-Length"] = cr.rsplit("/", 1)[1]
                    headers["Accept-Ranges"] = "bytes"
                return resp.status, headers
        except aiohttp.ClientError as exc:
            raise DFError(Code.SOURCE_ERROR, f"origin probe failed: {exc}") from None

    async def content_length(self, req: SourceRequest) -> int:
        _, headers = await self._head(req)
        try:
            total = int(headers.get("Content-Length", "-1"))
        except ValueError:
            return -1
        if req.range is not None and total >= 0:
            return min(req.range.length, max(0, total - req.range.start))
        return total

    async def supports_range(self, req: SourceRequest) -> bool:
        _, headers = await self._head(req)
        return headers.get("Accept-Ranges", "").lower() == "bytes"

    async def last_modified(self, req: SourceRequest) -> str:
        _, headers = await self._head(req)
        return headers.get("Last-Modified", "")

    async def download(self, req: SourceRequest) -> SourceResponse:
        session = await self._get_session()
        headers = dict(req.header)
        if req.range is not None:
            headers["Range"] = req.range.http_header()
        try:
            resp = await session.get(req.url, headers=headers, allow_redirects=True,
                                     ssl=self._ssl, timeout=_timeout(req))
        except aiohttp.ClientError as exc:
            raise DFError(Code.SOURCE_ERROR, f"origin get failed: {exc}") from None
        if resp.status >= 400:
            status = resp.status
            headers = dict(resp.headers)
            resp.close()
            raise _status_error(status, req.url, headers=headers)
        if req.range is not None and resp.status != 206:
            resp.close()
            raise DFError(Code.SOURCE_RANGE_UNSUPPORTED,
                          f"origin ignored range request: status {resp.status}")
        length = int(resp.headers.get("Content-Length", "-1"))
        total = length
        cr = resp.headers.get("Content-Range", "")
        if "/" in cr:
            tail = cr.rsplit("/", 1)[1]
            if tail.isdigit():
                total = int(tail)

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for data in resp.content.iter_chunked(_CHUNK):
                    yield data
            finally:
                resp.close()

        return SourceResponse(
            status=resp.status, content_length=length, total_length=total,
            supports_range=resp.status == 206
            or resp.headers.get("Accept-Ranges", "").lower() == "bytes",
            last_modified=resp.headers.get("Last-Modified", ""),
            header=dict(resp.headers), chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        # plain HTTP has no directory protocol; single entry
        return [ListEntry(url=req.url, name=req.url.rsplit("/", 1)[-1],
                          is_dir=False, content_length=await self.content_length(req))]


register_client(["http", "https"], HTTPSourceClient())
