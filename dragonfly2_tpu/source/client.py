"""ResourceClient protocol + scheme registry.

Reference surface (``source/source_client.go:102-128``): GetContentLength,
IsSupportRange, Download(+expire info), GetLastModified, plus the recursive
lister. Downloads are async chunk iterators so the piece engine can hash and
store while bytes stream in.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import AsyncIterator, Protocol

from ..common import faultgate
from ..common.errors import Code, DFError
from ..common.piece import Range

log = logging.getLogger("df.source")


@dataclass
class SourceRequest:
    url: str
    header: dict[str, str] = field(default_factory=dict)
    range: Range | None = None
    timeout_s: float = 0.0


@dataclass
class SourceResponse:
    """Handle on an in-flight origin download."""

    status: int = 200
    content_length: int = -1       # of THIS response body (range-aware)
    total_length: int = -1         # of the whole resource when known
    supports_range: bool = False
    last_modified: str = ""
    header: dict[str, str] = field(default_factory=dict)
    chunks: AsyncIterator[bytes] | None = None

    async def read_all(self) -> bytes:
        out = bytearray()
        assert self.chunks is not None
        async for c in self.chunks:
            out.extend(c)
        return bytes(out)


@dataclass
class ListEntry:
    url: str
    name: str
    is_dir: bool
    content_length: int = -1


class ResourceClient(Protocol):
    async def content_length(self, req: SourceRequest) -> int: ...
    async def supports_range(self, req: SourceRequest) -> bool: ...
    async def download(self, req: SourceRequest) -> SourceResponse: ...
    async def last_modified(self, req: SourceRequest) -> str: ...
    async def list(self, req: SourceRequest) -> list[ListEntry]: ...


_REGISTRY: dict[str, ResourceClient] = {}


def register_client(schemes: list[str] | str, client: ResourceClient) -> None:
    if isinstance(schemes, str):
        schemes = [schemes]
    for s in schemes:
        _REGISTRY[s.lower()] = client


def client_for(url: str) -> ResourceClient:
    scheme = url.split("://", 1)[0].lower() if "://" in url else "file"
    client = _REGISTRY.get(scheme)
    if client is None:
        raise DFError(Code.SOURCE_ERROR, f"no source client for scheme {scheme!r}")
    return client


# module-level conveniences mirroring the reference's package-level funcs

async def content_length(req: SourceRequest) -> int:
    return await client_for(req.url).content_length(req)


async def supports_range(req: SourceRequest) -> bool:
    return await client_for(req.url).supports_range(req)


async def download(req: SourceRequest) -> SourceResponse:
    if faultgate.ARMED:
        # the back-to-source entry: an 'error' script with after_ms plays
        # an origin 503+Retry-After; the piece manager's retry ladder must
        # honor the hint (tests/test_faults.py)
        await faultgate.fire("source.fetch", key=req.url)
    return await client_for(req.url).download(req)


async def walk(url: str, *, timeout_s: float = 0.0,
               header: dict | None = None, max_depth: int = 64
               ) -> AsyncIterator[tuple[ListEntry, str]]:
    """BFS the listing under ``url``, yielding (entry, relative_path) for
    every FILE (reference lister + ``recursiveDownload`` traversal,
    ``client/dfget/dfget.go:317``). Origin credentials in ``header`` ride
    every listing request. Directory symlink cycles are broken by realpath
    identity for file:// and a depth cap for every scheme."""
    import os
    from collections import deque
    from urllib.parse import urlparse

    client = client_for(url)
    base_path = urlparse(url).path.rstrip("/")

    def ident(u: str) -> str:
        p = urlparse(u)
        if p.scheme in ("", "file"):
            return "file://" + os.path.realpath(p.path)
        return u

    queue = deque([(url, 0)])
    seen = {ident(url)}
    while queue:
        cur, depth = queue.popleft()
        entries = await client.list(SourceRequest(
            url=cur, header=dict(header or {}), timeout_s=timeout_s))
        for e in entries:
            if e.is_dir:
                key = ident(e.url)
                if key in seen:
                    continue
                if depth + 1 > max_depth:
                    log.warning("walk: skipping %s (deeper than max_depth"
                                "=%d) — mirror will be incomplete",
                                e.url, max_depth)
                    continue
                seen.add(key)
                queue.append((e.url, depth + 1))
                continue
            rel = urlparse(e.url).path
            # strip base_path only at a SEGMENT boundary: an entry under
            # /data2/f listed from base /data must stay "data2/f", not
            # become "2/f"
            if base_path:
                base = base_path.rstrip("/")
                if rel == base:
                    rel = ""
                elif rel.startswith(base + "/"):
                    rel = rel[len(base):]
            rel = os.path.normpath(rel.lstrip("/") or e.name)
            # traversal check by path SEGMENT: "../x" escapes, a file
            # legitimately named "..config" does not
            if rel.split(os.sep, 1)[0] == ".." or os.path.isabs(rel):
                # origin-controlled names must not escape the output dir
                # (object keys may legally contain '..'; a hostile lister
                # could name its way into ~/.ssh with the daemon's
                # privileges)
                log.warning("walk: refusing traversal entry %r", e.url)
                continue
            yield e, rel


async def close_clients() -> None:
    """Close every registered client's session bound to the CURRENT loop.

    In-process daemons (tests, the bench's tpu phase) share the process-wide
    client registry; without this their back-source aiohttp sessions outlive
    ``Daemon.stop()`` and asyncio reports them as leaked on loop close."""
    seen: set[int] = set()
    for client in _REGISTRY.values():
        if id(client) in seen:
            continue
        seen.add(id(client))
        close = getattr(client, "close", None)
        if close is not None:
            await close()


def timeout_for(req: "SourceRequest"):
    """Per-request aiohttp timeout: honor req.timeout_s; otherwise no total
    cap (multi-GB origin streams legitimately run >5min) with sane
    connect/read bounds."""
    import aiohttp

    if req.timeout_s and req.timeout_s > 0:
        return aiohttp.ClientTimeout(total=req.timeout_s)
    return aiohttp.ClientTimeout(total=None, sock_connect=30, sock_read=120)


class SessionPool:
    """Loop-bound aiohttp sessions (one per running loop, closed ones
    pruned). The origin clients are process singletons serving several
    asyncio.run lifetimes (CLIs, tests) — a session from a dead loop must
    never be reused."""

    def __init__(self, factory=None):
        import aiohttp

        self._factory = factory or (lambda: aiohttp.ClientSession())
        self._sessions: dict[int, object] = {}

    async def get(self):
        import asyncio

        loop = asyncio.get_running_loop()
        s = self._sessions.get(id(loop))
        if s is None or s.closed:
            s = self._factory()
            self._sessions[id(loop)] = s
            self._sessions = {k: v for k, v in self._sessions.items()
                              if not v.closed}
        return s

    async def close(self):
        import asyncio

        s = self._sessions.pop(id(asyncio.get_running_loop()), None)
        if s is not None and not s.closed:
            await s.close()
