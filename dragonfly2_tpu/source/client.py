"""ResourceClient protocol + scheme registry.

Reference surface (``source/source_client.go:102-128``): GetContentLength,
IsSupportRange, Download(+expire info), GetLastModified, plus the recursive
lister. Downloads are async chunk iterators so the piece engine can hash and
store while bytes stream in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import AsyncIterator, Protocol

from ..common.errors import Code, DFError
from ..common.piece import Range


@dataclass
class SourceRequest:
    url: str
    header: dict[str, str] = field(default_factory=dict)
    range: Range | None = None
    timeout_s: float = 0.0


@dataclass
class SourceResponse:
    """Handle on an in-flight origin download."""

    status: int = 200
    content_length: int = -1       # of THIS response body (range-aware)
    total_length: int = -1         # of the whole resource when known
    supports_range: bool = False
    last_modified: str = ""
    header: dict[str, str] = field(default_factory=dict)
    chunks: AsyncIterator[bytes] | None = None

    async def read_all(self) -> bytes:
        out = bytearray()
        assert self.chunks is not None
        async for c in self.chunks:
            out.extend(c)
        return bytes(out)


@dataclass
class ListEntry:
    url: str
    name: str
    is_dir: bool
    content_length: int = -1


class ResourceClient(Protocol):
    async def content_length(self, req: SourceRequest) -> int: ...
    async def supports_range(self, req: SourceRequest) -> bool: ...
    async def download(self, req: SourceRequest) -> SourceResponse: ...
    async def last_modified(self, req: SourceRequest) -> str: ...
    async def list(self, req: SourceRequest) -> list[ListEntry]: ...


_REGISTRY: dict[str, ResourceClient] = {}


def register_client(schemes: list[str] | str, client: ResourceClient) -> None:
    if isinstance(schemes, str):
        schemes = [schemes]
    for s in schemes:
        _REGISTRY[s.lower()] = client


def client_for(url: str) -> ResourceClient:
    scheme = url.split("://", 1)[0].lower() if "://" in url else "file"
    client = _REGISTRY.get(scheme)
    if client is None:
        raise DFError(Code.SOURCE_ERROR, f"no source client for scheme {scheme!r}")
    return client


# module-level conveniences mirroring the reference's package-level funcs

async def content_length(req: SourceRequest) -> int:
    return await client_for(req.url).content_length(req)


async def supports_range(req: SourceRequest) -> bool:
    return await client_for(req.url).supports_range(req)


async def download(req: SourceRequest) -> SourceResponse:
    return await client_for(req.url).download(req)
