"""oras:// origin client — OCI-registry artifacts as download sources.

Role parity: reference ``pkg/source/clients/oras`` — model weights and
datasets increasingly ship as OCI artifacts (ORAS). URL form:

    oras://registry.example.com/repo/name:tag

Resolution: GET ``/v2/<repo>/manifests/<tag>`` (OCI + Docker manifest
accept headers), pick the artifact's single layer (multi-layer artifacts:
first layer, the ORAS file convention), then stream
``/v2/<repo>/blobs/<digest>`` — blob GETs honor standard Range headers, so
piece-group reads work like any HTTP origin. Auth: anonymous, with the
WWW-Authenticate bearer-token dance (``realm``/``service``/``scope``)
handled transparently; static tokens via ``DF_ORAS_TOKEN``.
``DF_ORAS_INSECURE=1`` uses http (local registries/tests).
"""

from __future__ import annotations

import json
import os
from typing import AsyncIterator

import aiohttp

from ..common.errors import Code, DFError
from .client import (ListEntry, SessionPool, SourceRequest, SourceResponse,
                     register_client, timeout_for)

_CHUNK = 1 << 20
_MANIFEST_ACCEPT = ", ".join([
    "application/vnd.oci.image.manifest.v1+json",
    "application/vnd.docker.distribution.manifest.v2+json",
    "application/vnd.oci.artifact.manifest.v1+json",
])


def _scheme() -> str:
    return "http" if os.environ.get("DF_ORAS_INSECURE") else "https"


def _parse(url: str) -> tuple[str, str, str]:
    """(registry, repo, tag)."""
    rest = url.split("://", 1)[1]
    registry, _, repo_tag = rest.partition("/")
    repo, _, tag = repo_tag.rpartition(":")
    if not registry or not repo or not tag:
        raise DFError(Code.INVALID_ARGUMENT,
                      f"bad oras url (registry/repo:tag): {url}")
    return registry, repo, tag


class ORASSourceClient:
    def __init__(self) -> None:
        self._pool = SessionPool()
        self._tokens: dict[str, str] = {}      # registry -> bearer token

    async def _session(self) -> aiohttp.ClientSession:
        return await self._pool.get()

    async def close(self) -> None:
        await self._pool.close()

    def _auth_headers(self, registry: str) -> dict[str, str]:
        token = self._tokens.get(registry) or os.environ.get(
            "DF_ORAS_TOKEN", "")
        return {"Authorization": f"Bearer {token}"} if token else {}

    async def _bearer_dance(self, registry: str, challenge: str) -> bool:
        """WWW-Authenticate: Bearer realm=...,service=...,scope=... ->
        fetch an anonymous token (the public-registry flow)."""
        if not challenge.lower().startswith("bearer"):
            return False
        _, _, param_str = challenge.partition(" ")
        if not param_str:
            return False                # bare "Bearer": nothing to dance with
        # split on commas OUTSIDE quotes (scope="repository:x:pull,push")
        import re as _re
        parts = _re.findall(r'(\w+)="([^"]*)"|(\w+)=([^,\s]+)', param_str)
        fields = {(a or c): (b or d) for a, b, c, d in parts}
        realm = fields.get("realm", "")
        if not realm:
            return False
        params = {k: v for k, v in fields.items()
                  if k in ("service", "scope")}
        s = await self._session()
        async with s.get(realm, params=params) as resp:
            if resp.status >= 400:
                return False
            body = await resp.json()
        token = body.get("token") or body.get("access_token", "")
        if not token:
            return False
        self._tokens[registry] = token
        return True

    async def _get(self, registry: str, path: str,
                   headers: dict[str, str],
                   req: SourceRequest | None = None):
        """GET with one automatic bearer-challenge retry."""
        url = f"{_scheme()}://{registry}{path}"
        s = await self._session()
        timeout = timeout_for(req) if req is not None else None
        for attempt in (0, 1):
            h = {**headers, **self._auth_headers(registry)}
            try:
                resp = await s.get(url, headers=h, timeout=timeout)
            except aiohttp.ClientError as exc:
                raise DFError(Code.SOURCE_ERROR,
                              f"oras: {exc}") from None
            if resp.status == 401 and attempt == 0:
                challenge = resp.headers.get("WWW-Authenticate", "")
                resp.close()
                if await self._bearer_dance(registry, challenge):
                    continue
                raise DFError(Code.SOURCE_AUTH_ERROR, f"oras 401: {url}")
            return resp
        raise DFError(Code.SOURCE_AUTH_ERROR, url)   # pragma: no cover

    async def _resolve_layer(self, req: SourceRequest) -> tuple[str, str, dict]:
        """(registry, blob path, layer descriptor) for the artifact's
        payload layer."""
        registry, repo, tag = _parse(req.url)
        resp = await self._get(registry, f"/v2/{repo}/manifests/{tag}",
                               {"Accept": _MANIFEST_ACCEPT, **req.header},
                               req=req)
        try:
            if resp.status == 404:
                raise DFError(Code.SOURCE_NOT_FOUND, req.url)
            if resp.status >= 400:
                raise DFError(Code.SOURCE_ERROR,
                              f"oras manifest {resp.status}: {req.url}")
            manifest = json.loads(await resp.read())
        finally:
            resp.close()
        layers = manifest.get("layers") or manifest.get("blobs") or []
        if not layers:
            raise DFError(Code.SOURCE_ERROR,
                          f"oras manifest has no layers: {req.url}")
        layer = layers[0]
        digest = layer.get("digest", "")
        if not digest:
            raise DFError(Code.SOURCE_ERROR, f"layer missing digest: {req.url}")
        return registry, f"/v2/{repo}/blobs/{digest}", layer

    async def content_length(self, req: SourceRequest) -> int:
        _, _, layer = await self._resolve_layer(req)
        total = int(layer.get("size", -1))
        if req.range is not None and total >= 0:
            return min(req.range.length, max(0, total - req.range.start))
        return total

    async def supports_range(self, req: SourceRequest) -> bool:
        return True                    # OCI blob GETs serve ranges

    async def last_modified(self, req: SourceRequest) -> str:
        return ""                      # content-addressed: digest is identity

    async def download(self, req: SourceRequest) -> SourceResponse:
        registry, blob_path, layer = await self._resolve_layer(req)
        headers = dict(req.header)
        if req.range is not None:
            headers["Range"] = req.range.http_header()
        resp = await self._get(registry, blob_path, headers, req=req)
        if resp.status >= 400:
            status = resp.status
            resp.close()
            raise DFError(Code.SOURCE_ERROR,
                          f"oras blob {status}: {req.url}")
        if req.range is not None and resp.status != 206:
            # OCI makes blob Range support OPTIONAL: a 200-with-full-body
            # answer would make every piece-group slice wrong bytes from
            # offset 0 (http_client.py has the same guard)
            resp.close()
            raise DFError(Code.SOURCE_RANGE_UNSUPPORTED,
                          f"registry ignored Range: {req.url}")
        length = int(resp.headers.get("Content-Length", "-1"))

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for data in resp.content.iter_chunked(_CHUNK):
                    yield data
            finally:
                resp.close()

        return SourceResponse(
            status=resp.status, content_length=length,
            total_length=int(layer.get("size", -1)), supports_range=True,
            header=dict(resp.headers), chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        return [ListEntry(url=req.url, name=req.url.rsplit("/", 1)[-1],
                          is_dir=False,
                          content_length=await self.content_length(req))]


register_client(["oras"], ORASSourceClient())
