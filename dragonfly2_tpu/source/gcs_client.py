"""gs:// origin client — GCS over its JSON/XML HTTP surface.

The seed-peer's back-source path on TPU pods reads model weights and dataset
shards from GCS (BASELINE configs #1/#4). Implemented against the public
endpoints via the HTTP client:

- metadata: ``GET storage.googleapis.com/storage/v1/b/{bucket}/o/{object}``
- media:    ``.../o/{object}?alt=media`` with standard Range headers
- listing:  ``.../o?prefix=...&delimiter=/``

Auth: bearer token from ``GOOGLE_APPLICATION_TOKEN`` or the GCE metadata
server when reachable; anonymous for public buckets. The build environment
has zero egress, so tests exercise request shaping against a local fake
(tests/test_source.py) — the live path is the same code.
"""

from __future__ import annotations

import json
import os
from urllib.parse import quote

from ..common.errors import Code, DFError
from .client import ListEntry, SourceRequest, SourceResponse, register_client
from .http_client import HTTPSourceClient

_DEFAULT_ENDPOINT = "https://storage.googleapis.com"


def _endpoint() -> str:
    # override for testing against a local fake and for private service connect
    return os.environ.get("DF_GCS_ENDPOINT", _DEFAULT_ENDPOINT).rstrip("/")


def _parse(url: str) -> tuple[str, str]:
    rest = url.split("://", 1)[1]
    bucket, _, obj = rest.partition("/")
    if not bucket or not obj:
        raise DFError(Code.INVALID_ARGUMENT, f"bad gs url: {url}")
    return bucket, obj


def _media_url(url: str) -> str:
    bucket, obj = _parse(url)
    return f"{_endpoint()}/storage/v1/b/{bucket}/o/{quote(obj, safe='')}?alt=media"


def _meta_url(url: str) -> str:
    bucket, obj = _parse(url)
    return f"{_endpoint()}/storage/v1/b/{bucket}/o/{quote(obj, safe='')}"


async def _auth_header() -> dict[str, str]:
    token = os.environ.get("GOOGLE_APPLICATION_TOKEN", "")
    if token:
        return {"Authorization": f"Bearer {token}"}
    return {}


class GCSSourceClient:
    def __init__(self) -> None:
        self._http = HTTPSourceClient()

    async def _req(self, req: SourceRequest, url: str) -> SourceRequest:
        header = {**(await _auth_header()), **req.header}
        return SourceRequest(url=url, header=header, range=req.range,
                             timeout_s=req.timeout_s)

    async def content_length(self, req: SourceRequest) -> int:
        return await self._http.content_length(await self._req(req, _media_url(req.url)))

    async def supports_range(self, req: SourceRequest) -> bool:
        return True  # GCS media downloads always honor Range

    async def last_modified(self, req: SourceRequest) -> str:
        meta = await self._http.download(await self._req(req, _meta_url(req.url)))
        try:
            data = json.loads(await meta.read_all())
            return data.get("updated", "")
        except (json.JSONDecodeError, UnicodeDecodeError):
            return ""

    async def download(self, req: SourceRequest) -> SourceResponse:
        return await self._http.download(await self._req(req, _media_url(req.url)))

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        bucket, prefix = _parse(req.url + ("/" if not req.url.endswith("/") else ""))
        url = (f"{_endpoint()}/storage/v1/b/{bucket}/o"
               f"?prefix={quote(prefix, safe='')}&delimiter=%2F")
        resp = await self._http.download(await self._req(
            SourceRequest(url=req.url, header=req.header), url))
        data = json.loads(await resp.read_all())
        out = []
        for item in data.get("items", []):
            out.append(ListEntry(url=f"gs://{bucket}/{item['name']}",
                                 name=item["name"], is_dir=False,
                                 content_length=int(item.get("size", -1))))
        for sub in data.get("prefixes", []):
            out.append(ListEntry(url=f"gs://{bucket}/{sub}", name=sub, is_dir=True))
        return out


register_client(["gs", "gcs"], GCSSourceClient())
