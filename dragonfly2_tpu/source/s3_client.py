"""s3:// origin client — SigV4-signed reads from S3-compatible stores.

Role parity: reference ``pkg/source/clients/s3/s3.go`` (component #54's
first missing scheme). Covers AWS S3, MinIO, Ceph RGW, OSS/OBS-compatible
endpoints via path-style URLs; credentials from config/env
(``common.objectstorage.S3Credentials``); anonymous for public buckets.

URL forms:
  s3://bucket/key              (endpoint from DF_S3_ENDPOINT or AWS default)
Endpoint override: ``DF_S3_ENDPOINT=http://minio:9000`` — also how tests
point the client at a local fake (zero-egress build env).
"""

from __future__ import annotations

import os
from typing import AsyncIterator
from urllib.parse import quote

import aiohttp

from ..common.errors import Code, DFError
from ..common.objectstorage import S3Credentials, _sha256_hex, sign_v4
from .client import (ListEntry, SessionPool, SourceRequest,
                     SourceResponse, register_client)

_CHUNK = 1 << 20


def _endpoint() -> str:
    ep = os.environ.get("DF_S3_ENDPOINT", "")
    if ep:
        return ep.rstrip("/")
    region = os.environ.get("AWS_REGION",
                            os.environ.get("AWS_DEFAULT_REGION", ""))
    host = f"s3.{region}.amazonaws.com" if region else "s3.amazonaws.com"
    return f"https://{host}"


def _parse(url: str) -> tuple[str, str, str]:
    """(endpoint, bucket, key). Plain ``s3://bucket/key`` resolves the
    endpoint from env/AWS defaults; ``s3+http(s)://host[:port]/bucket/key``
    carries it inline (the object gateway uses this so reads hit the SAME
    backend its writes were configured for)."""
    scheme, rest = url.split("://", 1)
    if scheme in ("s3+http", "s3+https"):
        host, _, rest = rest.partition("/")
        endpoint = f"{scheme[3:]}://{host}"
    else:
        endpoint = _endpoint()
    bucket, _, key = rest.partition("/")
    if not bucket or not key:
        raise DFError(Code.INVALID_ARGUMENT, f"bad s3 url: {url}")
    return endpoint, bucket, key


def _http_url(url: str) -> str:
    endpoint, bucket, key = _parse(url)
    return (f"{endpoint}/{quote(bucket)}/"
            f"{quote(key, safe='/-_.~')}")


class S3SourceClient:
    def __init__(self) -> None:
        self._pool = SessionPool()
        self._creds: S3Credentials | None = None

    def set_credentials(self, creds: S3Credentials) -> None:
        self._creds = creds

    def _credentials(self) -> S3Credentials:
        return self._creds or S3Credentials.from_env()

    async def _session(self) -> aiohttp.ClientSession:
        return await self._pool.get()

    async def close(self) -> None:
        await self._pool.close()

    def _signed(self, method: str, url: str,
                headers: dict[str, str]) -> dict[str, str]:
        creds = self._credentials()
        if not creds.access_key:
            return headers                  # anonymous bucket
        return sign_v4(creds, method, url, headers,
                       _sha256_hex(b""))

    async def content_length(self, req: SourceRequest) -> int:
        meta = await self._head(req)
        total = int(meta.get("Content-Length", "-1"))
        if req.range is not None and total >= 0:
            return min(req.range.length, max(0, total - req.range.start))
        return total

    async def supports_range(self, req: SourceRequest) -> bool:
        return True                          # S3 always serves ranges

    async def last_modified(self, req: SourceRequest) -> str:
        meta = await self._head(req)
        return meta.get("Last-Modified", "")

    async def _head(self, req: SourceRequest) -> dict:
        url = _http_url(req.url)
        headers = self._signed("HEAD", url, dict(req.header))
        s = await self._session()
        async with s.head(url, headers=headers) as resp:
            if resp.status == 404:
                raise DFError(Code.SOURCE_NOT_FOUND, req.url)
            if resp.status in (401, 403):
                raise DFError(Code.SOURCE_AUTH_ERROR,
                              f"s3 {resp.status}: {req.url}")
            if resp.status >= 400:
                raise DFError(Code.SOURCE_ERROR,
                              f"s3 HEAD {resp.status}: {req.url}")
            return dict(resp.headers)

    async def download(self, req: SourceRequest) -> SourceResponse:
        url = _http_url(req.url)
        headers = dict(req.header)
        if req.range is not None:
            headers["range"] = req.range.http_header()
        headers = self._signed("GET", url, headers)
        s = await self._session()
        resp = await s.get(url, headers=headers)
        if resp.status == 404:
            resp.close()
            raise DFError(Code.SOURCE_NOT_FOUND, req.url)
        if resp.status in (401, 403):
            resp.close()
            raise DFError(Code.SOURCE_AUTH_ERROR,
                          f"s3 {resp.status}: {req.url}")
        if resp.status >= 300:
            status = resp.status
            resp.close()
            raise DFError(Code.SOURCE_ERROR, f"s3 GET {status}: {req.url}")
        length = int(resp.headers.get("Content-Length", "-1"))
        total = length
        cr = resp.headers.get("Content-Range", "")
        if "/" in cr and cr.rsplit("/", 1)[1].isdigit():
            total = int(cr.rsplit("/", 1)[1])

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for data in resp.content.iter_chunked(_CHUNK):
                    yield data
            finally:
                resp.close()

        return SourceResponse(
            status=resp.status, content_length=length, total_length=total,
            supports_range=True,
            last_modified=resp.headers.get("Last-Modified", ""),
            header=dict(resp.headers), chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        return [ListEntry(url=req.url, name=req.url.rsplit("/", 1)[-1],
                          is_dir=False,
                          content_length=await self.content_length(req))]


register_client(["s3", "s3+http", "s3+https"], S3SourceClient())
