"""file:// origin client (also the default for bare paths)."""

from __future__ import annotations

import os
from typing import AsyncIterator
from urllib.parse import unquote, urlsplit

from ..common.errors import Code, DFError
from .client import ListEntry, SourceRequest, SourceResponse, register_client

_CHUNK = 1 << 20


def _path(url: str) -> str:
    if "://" in url:
        parts = urlsplit(url)
        return unquote(parts.path)
    return url


class FileSourceClient:
    async def content_length(self, req: SourceRequest) -> int:
        try:
            size = os.path.getsize(_path(req.url))
        except OSError:
            raise DFError(Code.SOURCE_NOT_FOUND, f"no such file: {req.url}") from None
        if req.range is not None:
            return min(req.range.length, max(0, size - req.range.start))
        return size

    async def supports_range(self, req: SourceRequest) -> bool:
        return True

    async def last_modified(self, req: SourceRequest) -> str:
        try:
            return str(os.path.getmtime(_path(req.url)))
        except OSError:
            return ""

    async def download(self, req: SourceRequest) -> SourceResponse:
        path = _path(req.url)
        try:
            total = os.path.getsize(path)
        except OSError:
            raise DFError(Code.SOURCE_NOT_FOUND, f"no such file: {req.url}") from None
        start, length = 0, total
        if req.range is not None:
            start = req.range.start
            length = min(req.range.length, max(0, total - start))

        async def chunks() -> AsyncIterator[bytes]:
            with open(path, "rb") as f:
                f.seek(start)
                remaining = length
                while remaining > 0:
                    data = f.read(min(_CHUNK, remaining))
                    if not data:
                        return
                    remaining -= len(data)
                    yield data

        return SourceResponse(status=200, content_length=length, total_length=total,
                              supports_range=True, chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        path = _path(req.url)
        if not os.path.isdir(path):
            return [ListEntry(url=req.url, name=os.path.basename(path), is_dir=False,
                              content_length=await self.content_length(req))]
        out = []
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            is_dir = os.path.isdir(full)
            out.append(ListEntry(
                url=f"file://{full}", name=name, is_dir=is_dir,
                content_length=-1 if is_dir else os.path.getsize(full)))
        return out


register_client(["file"], FileSourceClient())
