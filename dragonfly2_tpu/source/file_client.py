"""file:// origin client (also the default for bare paths).

All filesystem work hops through the default executor (DF001): a file://
origin feeds the same back-source path as HTTP origins, so its multi-MiB
piece reads would otherwise traverse buffers on the daemon's one event
loop — exactly the stall class PR 5 removed from the P2P landing path.
"""

from __future__ import annotations

import asyncio
import os
from typing import AsyncIterator
from urllib.parse import unquote, urlsplit

from ..common.errors import Code, DFError
from .client import ListEntry, SourceRequest, SourceResponse, register_client

_CHUNK = 1 << 20


def _path(url: str) -> str:
    if "://" in url:
        parts = urlsplit(url)
        return unquote(parts.path)
    return url


class FileSourceClient:
    async def content_length(self, req: SourceRequest) -> int:
        loop = asyncio.get_running_loop()
        try:
            size = await loop.run_in_executor(None, os.path.getsize,
                                              _path(req.url))
        except OSError:
            raise DFError(Code.SOURCE_NOT_FOUND, f"no such file: {req.url}") from None
        if req.range is not None:
            return min(req.range.length, max(0, size - req.range.start))
        return size

    async def supports_range(self, req: SourceRequest) -> bool:
        return True

    async def last_modified(self, req: SourceRequest) -> str:
        try:
            return str(await asyncio.get_running_loop().run_in_executor(
                None, os.path.getmtime, _path(req.url)))
        except OSError:
            return ""

    async def download(self, req: SourceRequest) -> SourceResponse:
        path = _path(req.url)
        loop = asyncio.get_running_loop()
        try:
            total = await loop.run_in_executor(None, os.path.getsize, path)
        except OSError:
            raise DFError(Code.SOURCE_NOT_FOUND, f"no such file: {req.url}") from None
        start, length = 0, total
        if req.range is not None:
            start = req.range.start
            length = min(req.range.length, max(0, total - start))

        async def chunks() -> AsyncIterator[bytes]:
            def _open():
                f = open(path, "rb")
                f.seek(start)
                return f

            f = await loop.run_in_executor(None, _open)
            try:
                remaining = length
                while remaining > 0:
                    data = await loop.run_in_executor(
                        None, f.read, min(_CHUNK, remaining))
                    if not data:
                        return
                    remaining -= len(data)
                    yield data
            finally:
                f.close()

        return SourceResponse(status=200, content_length=length, total_length=total,
                              supports_range=True, chunks=chunks())

    async def list(self, req: SourceRequest) -> list[ListEntry]:
        path = _path(req.url)

        def _scan() -> list[ListEntry] | None:
            if not os.path.isdir(path):
                return None
            out = []
            for name in sorted(os.listdir(path)):
                full = os.path.join(path, name)
                is_dir = os.path.isdir(full)
                out.append(ListEntry(
                    url=f"file://{full}", name=name, is_dir=is_dir,
                    content_length=-1 if is_dir else os.path.getsize(full)))
            return out

        entries = await asyncio.get_running_loop().run_in_executor(None, _scan)
        if entries is None:
            return [ListEntry(url=req.url, name=os.path.basename(path), is_dir=False,
                              content_length=await self.content_length(req))]
        return entries


register_client(["file"], FileSourceClient())
