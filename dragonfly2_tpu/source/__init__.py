"""Origin ("back-to-source") clients, keyed by URL scheme.

Role parity: reference ``pkg/source`` — ``ResourceClient`` interface
(``source/source_client.go:102-128``), per-scheme registry + loader
(``source/loader``), request adapters, recursive lister. Clients here:
file://, http(s):// (aiohttp), memory:// (tests), gs:// (GCS, gated — the
runtime image has zero egress, so it is exercised only through its request
shaping).
"""

from .client import (  # noqa: F401
    SourceRequest, SourceResponse, ResourceClient, ListEntry,
    register_client, client_for, content_length, supports_range, download,
)
from . import (file_client, http_client, memory_client, gcs_client,  # noqa: F401
               s3_client, hdfs_client, oras_client)
