"""All wire messages for the four services.

Service surface parity (reference ``pkg/rpc/*`` client wrappers, SURVEY §2.6):
scheduler (register/report/announce/probes), daemon (download/piece sync/cache
ops/seeding), manager (entities/keepalive/dynconfig), trainer (dataset upload).
TPU-native additions: ``TopologyInfo`` carries ICI slice coordinates so the
scheduler can score parents by link locality, and ``DeviceSink`` describes an
HBM placement target for a download.
"""

from __future__ import annotations

import enum

from .base import message


# ---------------------------------------------------------------- enums

class SizeScope(enum.IntEnum):
    NORMAL = 0   # many pieces, full P2P
    SMALL = 1    # exactly one piece: skip piece sync, single parent
    TINY = 2     # <=128 KiB: content returned inline in register result
    EMPTY = 3    # zero bytes


class TaskType(enum.IntEnum):
    STANDARD = 0       # downloaded file, GC-able
    PERSISTENT = 1     # dfcache import: pinned until deleted
    PERSISTENT_CACHE = 2


class Priority(enum.IntEnum):
    LEVEL0 = 0  # highest
    LEVEL1 = 1
    LEVEL2 = 2
    LEVEL3 = 3
    LEVEL4 = 4
    LEVEL5 = 5
    LEVEL6 = 6  # lowest


# The multi-tenant QoS service-class vocabulary, pinned here the way
# ``EXCLUSION_REASONS`` pins the scheduling filter's reasons: every surface
# that carries a class (dfget/proxy/object-gateway requests, shaper/upload
# admission, scheduler rulings, ``df_qos_*`` metric labels) must use one of
# these strings, and each must be backticked in docs/RESILIENCE.md /
# docs/OBSERVABILITY.md (dflint DF006 priority-class-vocabulary).
#
#   ``critical`` — latency-sensitive foreground (a serving host pulling a
#                  hot model): holds its SLO under contention, may preempt
#                  ``bulk`` dispatch slots;
#   ``standard`` — the default class; everything pre-QoS behaved as;
#   ``bulk``     — background batch (dataset prefetch, image layers):
#                  first to be throttled, queued, and shed under brownout.
PRIORITY_CLASSES = ("critical", "standard", "bulk")
DEFAULT_PRIORITY_CLASS = "standard"

# numeric Priority a class resolves to when the request carries none:
# ``bulk`` sinks to LEVEL6 so priority-ordered surfaces that predate the
# class vocabulary (storage GC eviction, the per-class back-source budget)
# order it behind foreground traffic without any new plumbing
CLASS_DEFAULT_PRIORITY = {"critical": 0, "standard": 0, "bulk": 6}


def resolve_class(qos_class: str) -> str:
    """Clamp a wire-supplied class onto the pinned vocabulary ("" and
    unknown strings resolve to the default class, never an error — an old
    client must keep working against a QoS-aware pod)."""
    return qos_class if qos_class in PRIORITY_CLASSES \
        else DEFAULT_PRIORITY_CLASS


# Typed piece-failure vocabulary, pinned the way ``PRIORITY_CLASSES`` and
# the scheduler's ``EXCLUSION_REASONS`` are: every failed piece report
# (``PieceResult.fail_code``), flight-journal failure event, ``kind=piece``
# record row, and per-parent verdict-ledger counter uses one of these
# strings, each backticked in docs/OBSERVABILITY.md. ``ok=False`` alone
# told the scheduler nothing about *why* — and a swarm immune system needs
# the why: ``corrupt`` is hard evidence of a lying parent (quarantinable),
# the other three are congestion/liveness shapes that only deprioritize.
#
#   ``corrupt`` — the bytes landed but failed digest verification:
#                 the parent served wrong bytes (bit-rot, bad NIC, or a
#                 byzantine daemon);
#   ``stall``   — the transfer died mid-body (short read, connection
#                 reset): the parent wedged or churned away;
#   ``timeout`` — the per-piece deadline fired before the body finished;
#   ``refused`` — the parent answered with an error (or never accepted
#                 the connection) before any payload moved.
FAIL_CODES = ("corrupt", "stall", "timeout", "refused")


class HostType(enum.IntEnum):
    NORMAL = 0       # ordinary peer
    SUPER_SEED = 1   # seed peer, first to back-source
    STRONG_SEED = 2
    WEAK_SEED = 3


class LinkType(enum.IntEnum):
    """Locality class between two hosts, best to worst."""

    LOCAL = 0  # same host
    ICI = 1    # same TPU slice: wired inter-chip interconnect
    DCN = 2    # same zone, data-center network between slices/hosts
    WAN = 3    # cross-zone / unknown


# ---------------------------------------------------------------- core types

@message
class UrlMeta:
    """Download-relevant metadata; participates in the task id."""

    digest: str = ""                 # "sha256:..." expected digest of whole file
    tag: str = ""                    # task isolation tag
    range: str = ""                  # "bytes=a-b" sub-range request
    filtered_query_params: list[str] | None = None
    header: dict | None = None       # extra origin request headers
    application: str = ""
    priority: Priority = Priority.LEVEL0
    # multi-tenant QoS: who this request belongs to and which service
    # class it rides (PRIORITY_CLASSES; "" = standard). NOT part of the
    # task id — two tenants pulling the same URL share the task and the
    # content store dedupes across them; what differs is admission,
    # shaping, and eviction treatment.
    tenant: str = ""
    qos_class: str = ""
    # sharded tasks (common/sharding.py): comma-joined names of the
    # manifest shards THIS host's mesh position needs ("" = whole task).
    # NOT part of the task id — every host pulling any subset of the
    # same checkpoint joins the same task/swarm and shares pieces; what
    # differs is which pieces each host fetches and which shards become
    # ready arrays. The scheduler reads this at register to assign the
    # host its disjoint tree-fetch subset (RegisterResult.assigned_shards).
    shards: str = ""


@message
class TopologyInfo:
    """Where a host sits in the TPU pod fabric.

    This replaces the reference's IDC/location strings
    (``scheduler/scheduling/evaluator/evaluator_base.go:28-46`` scores) with
    coordinates the evaluator can compute real link classes from.
    """

    slice_name: str = ""             # e.g. "v5p-256-slice-0"; "" = not a TPU host
    worker_index: int = -1           # TPU VM worker number within the slice
    ici_coords: tuple | None = None  # chip-mesh coords of this host's chips, e.g. (x, y, z)
    num_chips: int = 0
    zone: str = ""                   # cloud zone (DCN domain)
    cluster_id: int = 0
    # explicit pod identity (cross-pod federation, ROADMAP item 2): the
    # ICI bandwidth domain this host belongs to. "" = derive from slice
    # identity (``tpu.topology.pod_id``: one slice == one ICI domain ==
    # one pod); set explicitly (DF_POD_ID) only when a deployment groups
    # hosts differently from slice boundaries. Rides every register/
    # announce so the scheduler can route cross-pod pulls through the
    # pod's elected seeds instead of letting the whole fleet cross DCN.
    pod: str = ""


@message
class CPUStat:
    logical_count: int = 0
    percent: float = 0.0


@message
class MemoryStat:
    total: int = 0
    available: int = 0
    used_percent: float = 0.0


@message
class NetworkStat:
    download_rate: int = 0       # bytes/s current
    download_rate_limit: int = 0
    upload_rate: int = 0
    upload_rate_limit: int = 0


@message
class DiskStat:
    total: int = 0
    free: int = 0
    used_percent: float = 0.0


@message
class Host:
    """A daemon instance's identity + address, carried in every register."""

    id: str = ""
    ip: str = ""
    hostname: str = ""
    port: int = 0                  # peer gRPC port
    download_port: int = 0         # piece upload (HTTP) port
    type: HostType = HostType.NORMAL
    os: str = ""
    platform: str = ""
    topology: TopologyInfo | None = None
    cpu: CPUStat | None = None
    memory: MemoryStat | None = None
    network: NetworkStat | None = None
    disk: DiskStat | None = None
    # 0 = "auto": the scheduler applies its per-host-type default (peers
    # serve few children each so fan-outs form trees, not stars)
    concurrent_upload_limit: int = 0
    build_version: str = ""
    # self-quarantine flag (daemon/verdicts.py): the daemon detected its
    # OWN storage bit-rot (boot re-verify or content-store placement
    # re-hash failed) and asks to be excluded as a parent pod-wide. Rides
    # every register/AnnounceHost; the scheduler's quarantine registry
    # treats it as hard evidence (state ``quarantined``, reason self).
    quarantined: bool = False


@message
class PieceInfo:
    piece_num: int = 0
    range_start: int = 0
    range_size: int = 0
    digest: str = ""               # per-piece "crc32c:..." / "md5:..."
    download_cost_ms: int = 0      # filled by downloader when reporting


@message
class PiecePacket:
    """Answer to "which pieces does peer X have" — also carries dst address."""

    task_id: str = ""
    dst_peer_id: str = ""
    dst_addr: str = ""             # "ip:download_port" to fetch pieces from
    piece_infos: list[PieceInfo] | None = None
    total_piece_count: int = -1    # -1: unknown yet
    content_length: int = -1
    piece_size: int = 0
    extend_attribute: dict | None = None
    # the holder's advertised landing watermark: pieces landed so far
    # (-1 = not reported). Rides every announcement so a child can see
    # how complete the partial holder it is pulling from is.
    progress: int = -1
    # cut-through announce-ahead (daemon/relay.py): piece numbers in
    # ``piece_infos`` that are IN-FLIGHT at the holder right now — the
    # upload server serves them to the landing watermark, so a child may
    # begin pulling before the holder finishes receiving them
    relay_nums: list[int] | None = None


@message
class ShardInfo:
    """One named array shard of a sharded task: a contiguous byte range of
    the content plus the array geometry a serving host reassembles it
    with. Integrity rides the existing per-piece digest machinery (every
    piece of the shard verifies at landing); ``digest`` is an OPTIONAL
    whole-shard digest checked at task finalize, not on the incremental
    shard-ready path."""

    name: str = ""                   # e.g. "layers.17.mlp.w1"
    range_start: int = 0             # byte offset within the content
    range_size: int = 0
    dtype: str = "uint8"             # numpy dtype string for the array view
    shape: list[int] | None = None   # array shape; None = flat bytes
    digest: str = ""                 # optional "sha256:..." of the shard


@message
class ShardManifest:
    """A sharded task's shard table (task -> named shards). Shards are
    disjoint contiguous ranges; gaps are legal (unnamed bytes still ride
    the task, they just never become named ready arrays). Identical
    shards across checkpoint versions dedupe in the CA store via the
    ordinary piece-digest/content_key machinery — a rollout that reuses
    unchanged layers transfers only the delta (docs/STORAGE.md)."""

    shards: list[ShardInfo] | None = None


@message
class DeviceSink:
    """TPU-native: optional terminal sink describing how verified bytes land
    in device HBM (which mesh axis shard this host holds, dtype, etc.)."""

    enabled: bool = False
    dtype: str = "uint8"
    shard_index: int = 0
    shard_count: int = 1
    donate: bool = True
    pipeline_shards: int = 0       # DMA units per device; 0 = auto (~32MiB each)


# ---------------------------------------------------------------- scheduler service

@message
class RegisterPeerTaskRequest:
    url: str = ""
    url_meta: UrlMeta | None = None
    task_id: str = ""
    peer_id: str = ""
    peer_host: Host | None = None
    is_migrating: bool = False


@message
class SinglePiece:
    dst_peer_id: str = ""
    dst_addr: str = ""
    piece_info: PieceInfo | None = None


@message
class RegisterResult:
    task_id: str = ""
    size_scope: SizeScope = SizeScope.NORMAL
    direct_content: bytes = b""           # TINY: whole file inline
    single_piece: SinglePiece | None = None  # SMALL
    content_length: int = -1
    piece_size: int = 0
    # the scheduler's resolved priority (explicit > application table >
    # default) echoed back so the daemon's storage GC can order eviction
    # by it even when the request itself carried no explicit priority
    resolved_priority: Priority = Priority.LEVEL0
    # sharded tasks: the disjoint tree-fetch subset of the request's
    # ``UrlMeta.shards`` this peer was assigned (scheduler shard
    # affinity, ``decision_kind=shard``). The daemon fetches these from
    # the distribution tree and waits for co-located replicas to supply
    # the rest over ICI-near P2P (tree fallback after a bounded hold).
    # None = no affinity ruling (scheduler arm disabled / whole-file
    # task): every needed piece is tree-eligible immediately.
    assigned_shards: list[str] | None = None
    # the answering scheduler's boot epoch (crash resilience): a daemon
    # that sees this CHANGE knows the brain restarted and re-announces
    # its held content so the recovered scheduler relearns who holds
    # what within one announce interval. 0 = pre-epoch scheduler.
    scheduler_epoch: int = 0


@message
class HostLoad:
    cpu_ratio: float = 0.0
    mem_ratio: float = 0.0
    disk_ratio: float = 0.0


@message
class PieceResult:
    """Peer -> scheduler, one per finished/failed piece (the report stream)."""

    task_id: str = ""
    src_peer_id: str = ""           # downloader
    dst_peer_id: str = ""           # parent it fetched from ("" = back-source)
    piece_info: PieceInfo | None = None
    begin_ms: int = 0
    end_ms: int = 0
    success: bool = False
    code: int = 0                   # errors.Code
    # typed failure verdict (FAIL_CODES; "" on success): the *kind* of
    # failure, which ``code`` alone collapsed — the scheduler's quarantine
    # registry promotes ``corrupt`` verdicts into pod-wide exclusion while
    # stall/timeout/refused stay congestion-shaped (blocklist only)
    fail_code: str = ""
    # the failed transfer rode the parent's cut-through relay path
    # (X-DF-Relay): corrupt bytes then originated UPSTREAM of the named
    # parent, so the evidence is circumstantial — it may deprioritize /
    # mark the relay suspect, never shun or quarantine it (the
    # relay-plane form of the anti-slander rule; one poisoner must not
    # get every honest relay below it evicted)
    relayed: bool = False
    host_load: HostLoad | None = None
    finished_count: int = 0         # pieces this peer now holds


@message
class PeerAddr:
    peer_id: str = ""
    ip: str = ""
    rpc_port: int = 0
    download_port: int = 0
    link: LinkType = LinkType.DCN   # scheduler-computed locality to the child
    is_seed: bool = False           # seed/super-seed host (dispatcher steers
                                    # demand to mesh peers when they can serve)


@message
class PeerPacket:
    """Scheduler -> peer: current parent assignment set."""

    task_id: str = ""
    src_peer_id: str = ""
    parallel_count: int = 4
    main_peer: PeerAddr | None = None
    candidate_peers: list[PeerAddr] | None = None
    code: int = 0                   # e.g. SCHED_NEED_BACK_SOURCE
    # advisory packets ADD parents without pruning the current assignment
    # (PEX swarm-index pre-population, daemon/pex.py): the scheduler's own
    # packets stay authoritative — only they replace the assignment set
    advisory: bool = False


@message
class PeerResult:
    """Final report when a peer's task ends."""

    task_id: str = ""
    peer_id: str = ""
    src_ip: str = ""
    url: str = ""
    success: bool = False
    traffic: int = 0                # bytes downloaded P2P
    cost_ms: int = 0
    code: int = 0
    total_piece_count: int = 0
    content_length: int = -1
    # compact flight-recorder summary (daemon/flight_recorder.py
    # ``compact_summary``): per-parent throughput, tail latencies,
    # back-to-source ratio — feeds the scheduler's cluster view and the
    # trainer's record stream; None when the recorder is disabled
    flight_summary: dict | None = None


# Fleet-pulse digest schema version. Bumped when the field semantics
# change incompatibly; the scheduler's ingest (scheduler/fleetpulse.py)
# refuses mismatched versions WHOLESALE (the PEX schema-refusal rule) —
# a half-understood telemetry stream is worse than none, because it
# looks like knowledge.
PULSE_VERSION = 1


@message
class PulseDigest:
    """One daemon's health counters, folded compact and piggybacked on
    the ``AnnounceHost`` heartbeat it already sends (daemon/pulse.py
    builds it; scheduler/fleetpulse.py ingests it). Zero new
    connections; dfbench --pr18 gates the encoded overhead at <= 512 B
    per announce.

    All ``*_total``-style fields are since-boot monotonic counters (the
    scheduler differentiates them; a restart's reset clamps to zero) —
    gauges are instantaneous. Unknown fields from a NEWER daemon are
    dropped by the codec (idl/base.py forward-compat rule); an unknown
    ``v`` rejects the whole digest at ingest, never crashes it."""

    v: int = PULSE_VERSION
    seq: int = 0                    # per-daemon announce counter
    flight_tasks: int = 0           # flight-ring occupancy (gauge)
    flight_evicted: int = 0         # flights dropped oldest (counter)
    served_rungs: dict | None = None    # ladder rung -> entries (counter)
    loop_lag_max_ms: float = 0.0    # event-loop lag high-water (gauge)
    loop_stalls: int = 0            # stall-threshold crossings (counter)
    slo_breaches: int = 0           # per-stage budget breaches (counter)
    corrupt_verdicts: int = 0       # first-hand corrupt verdicts (counter)
    shunned_parents: int = 0        # parents currently shunned (gauge)
    self_quarantined: bool = False  # the daemon pulled itself out
    qos_state: str = "normal"       # QoS governor state (gauge)
    qos_shed: int = 0               # admissions shed (counter)
    storage_tasks: int = 0          # tasks held by the storage manager


@message
class AnnounceHostRequest:
    host: Host | None = None
    interval_s: float = 30.0
    # fleet-pulse piggyback (daemon/pulse.py): None from a pre-pulse
    # daemon — the scheduler treats absence as "no telemetry", never
    # as an anomaly by itself (silent-daemon keys off missed announces)
    pulse: PulseDigest | None = None


@message
class AnnounceHostResponse:
    """Scheduler -> daemon heartbeat answer. Carries the scheduler's
    boot epoch so the announce plane doubles as restart detection (the
    register path carries it too — whichever lands first wins). Old
    schedulers answered Empty; the codec is self-describing, so a
    daemon treats anything without an epoch as epoch 0 (unknown)."""

    scheduler_epoch: int = 0


@message
class HeldContentEntry:
    """One task's holdings in a daemon's recovery re-announce — the PEX
    digest entry shape (daemon/pex.py build_digest), typed for the
    scheduler RPC plane."""

    task_id: str = ""
    url: str = ""
    total_piece_count: int = -1
    content_length: int = -1
    piece_size: int = 0
    done: bool = False
    pieces: list[int] | None = None     # partial holdings (done=False)


@message
class AnnounceContentRequest:
    """Daemon -> scheduler after an epoch change / register failover:
    re-announce held content so a freshly restarted (or newly elected)
    brain rebuilds its resource view from the swarm instead of sending
    the herd back to origin. ``digest`` is the daemon's sealed PEX
    envelope (sha256 + canonical JSON, daemon/pex.py seal) over the
    same entries — the scheduler verifies the seal and refuses torn or
    version-skewed blobs wholesale."""

    host: Host | None = None
    entries: list[HeldContentEntry] | None = None
    digest: bytes = b""
    # same piggyback as AnnounceHostRequest: the recovery re-announce is
    # a heartbeat too, and a freshly restarted brain wants telemetry
    # history started on the FIRST contact, not one interval later
    pulse: PulseDigest | None = None


@message
class AnnounceContentResponse:
    scheduler_epoch: int = 0
    tasks_adopted: int = 0


@message
class LeaveHostRequest:
    host_id: str = ""


@message
class LeavePeerRequest:
    task_id: str = ""
    peer_id: str = ""


@message
class StatTaskRequest:
    task_id: str = ""


@message
class TaskStat:
    id: str = ""
    type: TaskType = TaskType.STANDARD
    content_length: int = -1
    total_piece_count: int = -1
    state: str = ""
    peer_count: int = 0
    has_available_peer: bool = False


@message
class ProbeTarget:
    host_id: str = ""
    ip: str = ""
    port: int = 0


@message
class SyncProbesRequest:
    """Daemon -> scheduler: either asking for targets or reporting results."""

    host: Host | None = None
    probes: list[Probe] | None = None
    failed_host_ids: list[str] | None = None


@message
class Probe:
    target_host_id: str = ""
    rtt_us: int = 0
    created_at_ms: int = 0


@message
class SyncProbesResponse:
    targets: list[ProbeTarget] | None = None
    probe_interval_s: float = 20.0


# ---------------------------------------------------------------- daemon service

@message
class DownloadRequest:
    url: str = ""
    output: str = ""                # abs path; "" = stream/cache only
    url_meta: UrlMeta | None = None
    timeout_s: float = 0.0
    rate_limit_bps: int = 0
    disable_back_source: bool = False
    recursive: bool = False
    recursive_concurrency: int = 8
    keep_original_offset: bool = False
    device_sink: DeviceSink | None = None
    task_type: TaskType = TaskType.STANDARD
    # sharded tasks: the checkpoint's shard table. With a manifest the
    # daemon maps pieces -> shards as they verify, emits ``shard_ready``
    # flight events, hands each complete shard to the HBM sink
    # incrementally, and — when ``url_meta.shards`` names a subset —
    # pulls only the pieces that cover it.
    shard_manifest: ShardManifest | None = None


@message
class DownloadResponse:
    task_id: str = ""
    peer_id: str = ""
    completed_length: int = 0
    content_length: int = -1
    done: bool = False
    output: str = ""                # echo of where this entry landed (recursive)
    code: int = 0
    message: str = ""
    # sharded tasks: a ``shard_ready`` progress frame — this named shard's
    # bytes all verified and (when a device sink rides the request) its
    # HBM handoff is enqueued. ``shard_src`` says how its bytes arrived:
    # ``tree`` (this host's assigned tree-fetch subset) or ``swap``
    # (supplied by co-located replicas over ICI-near P2P). dfget prints
    # one per-shard ready timestamp per frame.
    shard: str = ""
    shard_src: str = ""
    shards_ready: int = 0
    shards_total: int = 0


@message
class PieceTaskRequest:
    task_id: str = ""
    src_peer_id: str = ""           # requester
    dst_peer_id: str = ""           # owner being asked
    start_num: int = 0
    limit: int = 32
    src_slice: str = ""             # requester's TPU slice: super-seeds
                                    # spread reveals one-per-slice so each
                                    # slice gets a local first-tier copy
                                    # that ICI then fans out


@message
class StatTaskDaemonRequest:
    url: str = ""
    url_meta: UrlMeta | None = None
    task_id: str = ""
    local_only: bool = False


@message
class ImportTaskRequest:
    path: str = ""
    url: str = ""                   # cache key url (d7y cache scheme)
    url_meta: UrlMeta | None = None
    task_type: TaskType = TaskType.PERSISTENT


@message
class ExportTaskRequest:
    url: str = ""
    output: str = ""
    url_meta: UrlMeta | None = None
    timeout_s: float = 0.0
    local_only: bool = False


@message
class DeleteTaskRequest:
    url: str = ""
    url_meta: UrlMeta | None = None
    task_id: str = ""


@message
class ObtainSeedsRequest:
    url: str = ""
    url_meta: UrlMeta | None = None
    task_id: str = ""


@message
class PieceSeed:
    peer_id: str = ""
    host_id: str = ""
    piece_info: PieceInfo | None = None
    done: bool = False
    content_length: int = -1
    total_piece_count: int = -1


@message
class Empty:
    pass


# ---------------------------------------------------------------- manager service

@message
class SchedulerEntity:
    id: int = 0
    hostname: str = ""
    ip: str = ""
    port: int = 0
    state: str = "inactive"         # active | inactive
    scheduler_cluster_id: int = 0
    features: list[str] | None = None
    topology: TopologyInfo | None = None


@message
class SeedPeerEntity:
    id: int = 0
    hostname: str = ""
    ip: str = ""
    port: int = 0
    download_port: int = 0
    object_storage_port: int = 0
    type: str = "super"
    state: str = "inactive"
    seed_peer_cluster_id: int = 0
    topology: TopologyInfo | None = None


@message
class ClusterConfig:
    """Scheduler-cluster tunables served via dynconfig."""

    candidate_parent_limit: int = 4
    filter_parent_limit: int = 15
    job_rate_limit: int = 10
    seed_peer_load_limit: int = 300
    peer_load_limit: int = 50
    piece_parallel_count: int = 4


@message
class GetSchedulersRequest:
    hostname: str = ""
    ip: str = ""
    topology: TopologyInfo | None = None
    version: str = ""


@message
class GetSchedulersResponse:
    schedulers: list[SchedulerEntity] | None = None
    cluster_config: ClusterConfig | None = None


@message
class GetSeedPeersRequest:
    cluster_id: int = 0


@message
class GetSeedPeersResponse:
    seed_peers: list[SeedPeerEntity] | None = None


@message
class KeepAliveRequest:
    source_type: str = ""           # "scheduler" | "seed_peer"
    hostname: str = ""
    ip: str = ""
    port: int = 0                   # instance identity is (hostname, ip, port)
    cluster_id: int = 0


@message
class RegisterSchedulerRequest:
    hostname: str = ""
    ip: str = ""
    port: int = 0
    scheduler_cluster_id: int = 0
    topology: TopologyInfo | None = None


@message
class RegisterSeedPeerRequest:
    hostname: str = ""
    ip: str = ""
    port: int = 0
    download_port: int = 0
    object_storage_port: int = 0
    type: str = "super"
    seed_peer_cluster_id: int = 0
    topology: TopologyInfo | None = None


@message
class PreheatRequest:
    """Manager/operator -> scheduler: warm a URL into the seed layer."""

    url: str = ""
    url_meta: UrlMeta | None = None
    wait: bool = True               # block until the seed finishes


@message
class PreheatResponse:
    task_id: str = ""
    state: str = ""                 # pending | running | succeeded | failed
    content_length: int = -1
    total_piece_count: int = -1


# ---------------------------------------------------------------- trainer service

@message
class TrainRequest:
    """Client-stream chunk: schedulers upload CSV datasets for model fitting."""

    hostname: str = ""
    ip: str = ""
    cluster_id: int = 0
    dataset: str = ""               # "download" | "networktopology"
    chunk: bytes = b""
    done: bool = False


@message
class TrainResponse:
    ok: bool = True
    message: str = ""
    model_version: str = ""


@message
class ModelInferRequest:
    model_name: str = "bandwidth_mlp"
    features: list[list] | None = None   # batch of feature rows


@message
class ModelInferResponse:
    outputs: list[float] | None = None
    model_version: str = ""


# ---------------------------------------------------------------- model registry

@message
class ModelEntity:
    """A versioned trained model (reference ``manager/models/model.go:36``)."""

    id: int = 0
    name: str = ""                  # bandwidth_mlp | topology_gnn
    version: str = ""               # content hash of the blob
    state: str = "active"
    scheduler_cluster_id: int = 0
    metrics: dict | None = None     # loss curve, rows, train time...
    data: bytes = b""               # npz param archive ("" in listings)
    created_at: float = 0.0


@message
class CreateModelRequest:
    name: str = ""
    version: str = ""
    scheduler_cluster_id: int = 0
    metrics: dict | None = None
    data: bytes = b""


@message
class GetModelRequest:
    name: str = ""
    version: str = ""               # "" = latest active version
    scheduler_cluster_id: int = 0
    if_none_match: str = ""         # client's current version: matching
                                    # reply omits the blob (poll cheaply)


@message
class GetModelResponse:
    model: ModelEntity | None = None


@message
class CertificateRequest:
    """Fleet cert issuance (reference security_server_v1.go IssueCertificate
    + pkg/issuer): the requester keeps its private key and submits only the
    public half plus the identities to certify."""

    public_key_pem: bytes = b""
    hosts: list[str] | None = None       # DNS names / IPs for the SAN
    validity_s: int = 0                  # 0 = issuer default; server-capped
    token: str = ""                      # issuance token (manager workdir
                                         # issuer.token; distributed to the
                                         # fleet out of band)


@message
class CertificateResponse:
    cert_pem: bytes = b""
    ca_cert_pem: bytes = b""


@message
class ApplicationEntry:
    """One manager-registered application with its download priority
    (reference ``manager/models/application.go:24`` Priority JSONMap —
    the scheduler's CalculatePriority consults this when a request
    carries no explicit priority)."""

    name: str = ""
    url: str = ""
    priority: Priority = Priority.LEVEL0


@message
class ListApplicationsResponse:
    applications: list[ApplicationEntry] | None = None


@message
class TenantEntry:
    """One manager-registered tenant with its quota and default service
    class — the per-tenant half of the QoS plane. Schedulers pull this
    table over dynconfig (``ListTenants``, same cadence as applications)
    and enforce ``max_running`` at register with a 429-shaped
    RESOURCE_EXHAUSTED + retry-after that the common/retry.py ladder
    already honors."""

    name: str = ""
    qos_class: str = ""              # default class for the tenant's
                                     # requests that carry none
    max_running: int = 0             # concurrent running downloads
                                     # cluster-wide (0 = unlimited)
    shed_retry_after_ms: int = 0     # hint stamped on quota sheds
                                     # (0 = scheduler default)


@message
class ListTenantsResponse:
    tenants: list[TenantEntry] | None = None


@message
class SetSchedulerStateRequest:
    """Demoting/stopping scheduler -> manager: park this member's last
    exported quarantine/affinity summary with the config plane of
    record, so the failover successor can import it. ``signature`` is
    an HMAC over ``blob`` with the cluster's issuance token when
    security is on ("" = unsigned, accepted only by managers that hold
    no token either)."""

    scheduler_id: str = ""           # exporter identity (host:port)
    cluster_id: int = 0
    blob: bytes = b""                # sealed summary (pex.seal envelope)
    signature: str = ""


@message
class GetSchedulerStateRequest:
    cluster_id: int = 0
    exclude: str = ""                # don't hand a member its own blob


@message
class GetSchedulerStateResponse:
    scheduler_id: str = ""           # "" = nothing parked
    blob: bytes = b""
    signature: str = ""


@message
class SyncPeersRequest:
    """Manager -> scheduler: dump your live host set (reference
    scheduler/job/job.go:224 syncPeers consumed by manager/job/sync_peers)."""

    cluster_id: int = 0


@message
class SyncPeersResponse:
    hosts: list[Host] | None = None
