"""Message registry + msgpack codec.

``@message`` registers a dataclass under its class name; ``dumps``/``loads``
move any registered message (with nested messages, enums, lists, optionals)
through msgpack. Unknown fields arriving on the wire are dropped — that is the
forward-compat rule (like protobuf's unknown-field tolerance, minus retention).
"""

from __future__ import annotations

import dataclasses
import enum
import types
import typing
from typing import Any, Type, TypeVar

_UNION_TYPES = (typing.Union, types.UnionType)

import msgpack

T = TypeVar("T")

_REGISTRY: dict[str, type] = {}
_HINTS: dict[type, dict[str, Any]] = {}


def message(cls: Type[T]) -> Type[T]:
    """Class decorator: make a dataclass a wire message."""
    cls = dataclasses.dataclass(cls)  # type: ignore[call-overload]
    name = cls.__name__
    if name in _REGISTRY and _REGISTRY[name] is not cls:
        raise ValueError(f"duplicate message name {name}")
    _REGISTRY[name] = cls
    return cls


def _hints(cls: type) -> dict[str, Any]:
    h = _HINTS.get(cls)
    if h is None:
        h = typing.get_type_hints(cls)
        _HINTS[cls] = h
    return h


def encode(obj: Any) -> Any:
    """Message tree -> plain msgpack-able structure."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: dict[str, Any] = {"__t": type(obj).__name__}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            out[f.name] = encode(v)
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {k: encode(v) for k, v in obj.items()}
    return obj


def decode(data: Any, expect: Any = None) -> Any:
    """Plain structure -> message tree. ``expect`` narrows typed coercion."""
    if isinstance(data, dict) and "__t" in data:
        cls = _REGISTRY.get(data["__t"])
        if cls is None:
            raise ValueError(f"unknown message type {data['__t']!r}")
        hints = _hints(cls)
        kwargs: dict[str, Any] = {}
        names = {f.name for f in dataclasses.fields(cls)}
        for k, v in data.items():
            if k == "__t" or k not in names:
                continue
            kwargs[k] = _coerce(hints.get(k), v)
        return cls(**kwargs)
    if expect is not None:
        return _coerce(expect, data)
    if isinstance(data, list):
        return [decode(v) for v in data]
    if isinstance(data, dict):
        return {k: decode(v) for k, v in data.items()}
    return data


def _coerce(ftype: Any, value: Any) -> Any:
    if value is None:
        return None
    if ftype is None or ftype is Any:
        return decode(value)
    origin = typing.get_origin(ftype)
    if origin in _UNION_TYPES:
        args = [a for a in typing.get_args(ftype) if a is not type(None)]
        if len(args) == 1:
            return _coerce(args[0], value)
        return decode(value)
    if isinstance(ftype, type) and issubclass(ftype, enum.Enum):
        return ftype(value)
    if isinstance(ftype, type) and dataclasses.is_dataclass(ftype):
        return decode(value)
    if origin in (list, tuple) or ftype in (list, tuple):
        container = origin or ftype
        elem = (typing.get_args(ftype) or (Any,))[0]
        seq = [_coerce(elem, v) for v in value]
        return tuple(seq) if container is tuple else seq
    if origin is dict:
        kt, vt = (typing.get_args(ftype) or (Any, Any))[:2]
        return {k: _coerce(vt, v) for k, v in value.items()}
    if ftype is float and isinstance(value, int):
        return float(value)
    return value


def dumps(obj: Any) -> bytes:
    return msgpack.packb(encode(obj), use_bin_type=True)


def loads(raw: bytes) -> Any:
    return decode(msgpack.unpackb(raw, raw=False, strict_map_key=False))
