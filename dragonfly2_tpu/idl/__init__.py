"""The wire IDL: typed dataclass messages + msgpack codec.

The reference keeps its protobuf IDL in an external module (d7y.io/api) and
wraps it in ``pkg/rpc``; here the IDL is first-class in-tree. Messages are
frozen-ish dataclasses registered with the codec by name; the wire format is
msgpack maps tagged with ``__t``.
"""

from .base import message, encode, decode, dumps, loads  # noqa: F401
from . import messages  # noqa: F401  (registers all message types)
