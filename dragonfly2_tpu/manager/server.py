"""Manager bootstrap: store + gRPC + REST + liveness sweep.

Role parity: reference ``manager/manager.go:106-234`` ``New``/``Serve``
(DB, REST router, gRPC server, cache) with the keepalive-TTL sweep that
marks silent instances inactive.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

from ..common.gc import GC, GCTask
from ..rpc.server import RPCServer
from .jobs import JobRunner
from .rest import RestAPI
from .service import ManagerService, build_service
from .store import Store

log = logging.getLogger("df.mgr.server")


@dataclass
class ManagerConfig:
    listen_ip: str = "0.0.0.0"
    advertise_ip: str = "127.0.0.1"
    grpc_port: int = 0
    rest_port: int = 0
    db_path: str = ""                  # "" = in-memory
    keepalive_ttl_s: float = 60.0
    sweep_interval_s: float = 15.0
    # REST auth (reference manager/middlewares jwt+PAT+rbac): requires a
    # workdir for the session secret + bootstrap root password files
    auth_enabled: bool = False
    workdir: str = ""
    # certificate issuance for fleet mTLS (reference
    # manager/rpcserver/security_server_v1.go + pkg/issuer)
    issue_certs: bool = False
    # serve the manager's own gRPC port over TLS with a cert minted from
    # the manager CA. REQUIRED wherever issue_certs rides an untrusted
    # network: the issuance token travels in the request, and a plaintext
    # listener would hand it to any on-path observer (open signing oracle).
    # Clients trust manager-ca/proxy-ca.crt (distributed out of band).
    grpc_tls: bool = False
    # searcher plugin override (reference manager/searcher plugin slot):
    # load df_plugin_searcher_default.py from this dir when set
    plugin_dir: str = ""


class Manager:
    def __init__(self, cfg: ManagerConfig):
        self.cfg = cfg
        if cfg.db_path:
            os.makedirs(os.path.dirname(os.path.abspath(cfg.db_path)),
                        exist_ok=True)
        self.store = Store(cfg.db_path or ":memory:")
        self.jobs = JobRunner(self.store)
        workdir = cfg.workdir or (
            os.path.dirname(os.path.abspath(cfg.db_path)) if cfg.db_path
            else "")
        issuer = None
        issue_token = ""
        if cfg.issue_certs:
            import secrets

            from ..common.certs import CertIssuer
            issuer = CertIssuer(os.path.join(workdir or ".", "manager-ca"))
            # issuance gate: generated once, persisted 0600, distributed to
            # the fleet out of band (the reference gates issuance behind its
            # deployment's network policy; an open signing oracle would make
            # the mTLS layer authenticate nothing)
            token_path = os.path.join(workdir or ".", "issuer.token")
            if os.path.exists(token_path):
                with open(token_path, encoding="utf-8") as f:
                    issue_token = f.read().strip()
            else:
                issue_token = secrets.token_urlsafe(24)
                fd = os.open(token_path,
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    f.write(issue_token + "\n")
        self.issuer = issuer
        self.issue_token = issue_token
        self.service = ManagerService(self.store, issuer=issuer,
                                      issue_token=issue_token)
        auth = None
        if cfg.auth_enabled:
            from .auth import Authenticator, bootstrap_root
            auth = Authenticator(
                self.store,
                secret_path=os.path.join(workdir, "session.secret")
                if workdir else "")
            bootstrap_root(self.store, password_path=os.path.join(
                workdir, "root.password") if workdir else "")
        self.auth = auth
        self.rest = RestAPI(self.store, self.jobs, host=cfg.listen_ip,
                            port=cfg.rest_port, auth=auth)
        self.rpc: RPCServer | None = None
        self.gc = GC()
        self.port: int | None = None

    @property
    def address(self) -> str:
        return f"{self.cfg.advertise_ip}:{self.port}"

    @property
    def ca_cert_path(self) -> str:
        return self.issuer.ca_cert_path if self.issuer else ""

    def _grpc_tls(self):
        if not self.cfg.grpc_tls:
            return None
        if self.issuer is None:
            raise ValueError("grpc_tls requires issue_certs (the manager CA "
                             "signs its own server cert)")
        import tempfile

        from ..rpc.server import TLSOptions
        cert_pem, key_pem, _ = self.issuer._mint(self.cfg.advertise_ip)
        d = tempfile.mkdtemp(prefix="df-mgr-tls-")
        cert_p, key_p = os.path.join(d, "s.crt"), os.path.join(d, "s.key")
        # dflint: disable=DF001 — one-shot KB-scale TLS materialization during Manager.start
        with open(cert_p, "wb") as f:
            # dflint: disable=DF001 — see above: startup path
            f.write(cert_pem + self.issuer._ca_pem())
        fd = os.open(key_p, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            # dflint: disable=DF001 — see above: startup path
            f.write(key_pem)
        return TLSOptions(cert_p, key_p)

    async def start(self) -> None:
        if self.cfg.plugin_dir:
            # fail HARD like the scheduler's evaluator plugin slot: an
            # operator who configured a plugin must not silently get the
            # built-in scorer because of a typo in the plugin file
            from .searcher import load_searcher_plugin
            load_searcher_plugin(self.cfg.plugin_dir)
            log.info("searcher plugin loaded from %s", self.cfg.plugin_dir)
        # a default cluster always exists so self-registration lands somewhere
        self.store.default_scheduler_cluster()
        self.rpc = RPCServer(f"{self.cfg.listen_ip}:{self.cfg.grpc_port}",
                             tls=self._grpc_tls())
        self.rpc.register(build_service(self.service))
        await self.rpc.start()
        self.port = self.rpc.port
        # resume BEFORE the REST listener: a job submitted during the boot
        # window must not be double-dispatched by the scan
        await self.jobs.resume_interrupted()
        await self.rest.start()
        self.gc.add(GCTask(
            "keepalive-sweep", self.cfg.sweep_interval_s,
            lambda: self.store.expire_stale(ttl_s=self.cfg.keepalive_ttl_s)))
        self.gc.start()
        log.info("manager up: grpc=%s rest=%d db=%s", self.address,
                 self.rest.port, self.cfg.db_path or ":memory:")

    async def stop(self) -> None:
        await self.gc.stop()
        await self.jobs.close()
        await self.rest.stop()
        if self.rpc is not None:
            await self.rpc.stop(0.5)
        self.store.close()
