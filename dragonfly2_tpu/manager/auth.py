"""Manager REST authentication + RBAC.

Role parity: reference ``manager/middlewares/{jwt,personal_access_token,
rbac}.go`` + ``manager/permission/rbac`` (casbin) + ``manager/auth``. The
same three mechanisms, stdlib-shaped:

- **Session tokens**: ``POST /api/v1/users/signin`` verifies a password
  (scrypt, store-side) and mints an HMAC-SHA256 bearer token with expiry
  (the reference's gin-jwt role).
- **Personal access tokens**: ``dfp_*`` bearer tokens checked against
  their sha256 in the store (reference middleware
  ``personal_access_token.go:30``).
- **RBAC**: method->action mapping (GET/HEAD = read, everything else =
  write; reference ``rbac.HTTPMethodToAction``) with two preset roles —
  ``root`` (all actions) and ``guest`` (read only), the reference's
  bootstrap policy.

The HMAC secret persists next to the DB so restarts don't invalidate
sessions.
"""

from __future__ import annotations

import base64
import hmac
import hashlib
import json
import logging
import os
import secrets
import time

from aiohttp import web

from ..common.errors import Code, DFError

log = logging.getLogger("df.mgr.auth")

SESSION_TTL_S = 7 * 24 * 3600.0
OAUTH_STATE_TTL_S = 600.0
# paths served without credentials (health, metrics, and signin itself);
# /oauth/* (signin redirect + provider callback) is public by prefix
PUBLIC_PATHS = {"/healthy", "/metrics", "/api/v1/users/signin"}
PUBLIC_PREFIXES = ("/oauth/",)


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(data: str) -> bytes:
    pad = -len(data) % 4
    return base64.urlsafe_b64decode(data + "=" * pad)


class Authenticator:
    def __init__(self, store, *, secret_path: str = "",
                 rest_quota_rps: float = 0.0,
                 rest_quota_burst: float = 0.0):
        """``rest_quota_rps``: per-authenticated-identity request-rate
        quota on the REST surface (0 = off, the pre-QoS behavior). Over
        quota answers 429 + Retry-After — the same shed contract as the
        scheduler's tenant quota, so one noisy tenant's dashboard poller
        or CI loop cannot monopolize the manager's sqlite thread."""
        self.store = store
        self.rest_quota_rps = float(rest_quota_rps)
        self.rest_quota_burst = float(rest_quota_burst) \
            or max(self.rest_quota_rps * 2, 1.0)
        self._quota_buckets: dict = {}
        if secret_path and os.path.exists(secret_path):
            with open(secret_path, "rb") as f:
                self._secret = f.read()
        else:
            self._secret = secrets.token_bytes(32)
            if secret_path:
                os.makedirs(os.path.dirname(secret_path) or ".",
                            exist_ok=True)
                # 0600 from CREATION: open+chmod leaves a world-readable
                # window (and a crash in it leaves the secret exposed)
                fd = os.open(secret_path,
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "wb") as f:
                    f.write(self._secret)

    # -- session tokens ------------------------------------------------

    def mint_session(self, user: dict) -> str:
        payload = json.dumps({"uid": user["id"], "name": user["name"],
                              "role": user["role"],
                              "exp": time.time() + SESSION_TTL_S})
        body = _b64(payload.encode())
        sig = _b64(hmac.new(self._secret, body.encode(),
                            hashlib.sha256).digest())
        return f"dfs_{body}.{sig}"

    def verify_session(self, token: str) -> dict | None:
        if not token.startswith("dfs_"):
            return None
        body, _, sig = token[4:].partition(".")
        want = _b64(hmac.new(self._secret, body.encode(),
                             hashlib.sha256).digest())
        if not hmac.compare_digest(sig, want):
            return None
        try:
            payload = json.loads(_unb64(body))
        except (ValueError, json.JSONDecodeError):
            return None
        if time.time() > payload.get("exp", 0):
            return None
        return {"id": payload["uid"], "name": payload["name"],
                "role": payload["role"]}

    # -- request authentication ----------------------------------------

    def authenticate(self, request: web.Request) -> dict | None:
        """The user behind the request's bearer token, or None."""
        auth = request.headers.get("Authorization", "")
        fields = auth.split()
        if len(fields) != 2 or fields[0] != "Bearer":
            return None
        token = fields[1]
        if token.startswith("dfs_"):
            return self.verify_session(token)
        return self.store.pat_user(token)

    @staticmethod
    def allowed(user: dict, method: str) -> bool:
        action = "read" if method in ("GET", "HEAD") else "write"
        if user["role"] == "root":
            return True
        return action == "read"        # guest: read-only

    # -- oauth sign-in state (CSRF guard on the authorize round-trip) ----

    def mint_state(self, provider: str) -> str:
        nonce = _b64(secrets.token_bytes(8))
        exp = time.time() + OAUTH_STATE_TTL_S
        payload = json.dumps({"p": provider, "n": nonce, "exp": exp})
        # server-side nonce: states are SINGLE-USE (a signed state alone
        # was replayable for its whole TTL by anyone who observed it —
        # the signin endpoint is public, so minting costs an attacker
        # nothing; consumption is what proves this exact round-trip).
        # DB-backed: survives restart, shared across replicas, and the
        # table is capped against unauthenticated mint floods.
        if not self.store.save_oauth_nonce(nonce, exp):
            raise DFError(Code.RESOURCE_EXHAUSTED,
                          "too many pending oauth sign-ins")
        body = _b64(payload.encode())
        sig = _b64(hmac.new(self._secret, b"state:" + body.encode(),
                            hashlib.sha256).digest())
        return f"{body}.{sig}"

    def verify_state(self, state: str, provider: str) -> bool:
        body, _, sig = state.partition(".")
        want = _b64(hmac.new(self._secret, b"state:" + body.encode(),
                             hashlib.sha256).digest())
        if not hmac.compare_digest(sig, want):
            return False
        try:
            payload = json.loads(_unb64(body))
        except (ValueError, json.JSONDecodeError):
            return False
        if (payload.get("p") != provider
                or time.time() > payload.get("exp", 0)):
            # provider/expiry checked BEFORE consumption: a mismatched
            # callback must not burn a still-valid state
            return False
        return self.store.consume_oauth_nonce(payload.get("n", ""))

    def check_quota(self, user: dict) -> float:
        """0.0 = admitted; > 0 = over the per-identity REST quota, value
        is the Retry-After seconds. Sync token-bucket math (rate.py
        TokenBucket.try_acquire) — no await, so the middleware can never
        queue requests behind a throttled tenant."""
        if self.rest_quota_rps <= 0:
            return 0.0
        from ..common.rate import TokenBucket
        bucket = self._quota_buckets.get(user["name"])
        if bucket is None:
            if len(self._quota_buckets) > 4096:
                # cap against unauthenticated-name floods via forged PATs:
                # resetting everyone's bucket is strictly safer than
                # unbounded growth
                self._quota_buckets.clear()
            bucket = TokenBucket(self.rest_quota_rps,
                                 self.rest_quota_burst)
            self._quota_buckets[user["name"]] = bucket
        if bucket.try_acquire(1.0):
            return 0.0
        return max(1.0 / self.rest_quota_rps, 1.0)

    def middleware(self):
        @web.middleware
        async def auth_middleware(request: web.Request, handler):
            if (request.path in PUBLIC_PATHS
                    or request.path.startswith(PUBLIC_PREFIXES)):
                return await handler(request)
            user = self.authenticate(request)
            if user is None:
                return web.json_response({"error": "unauthorized"},
                                         status=401)
            if not self.allowed(user, request.method):
                return web.json_response({"error": "forbidden"}, status=403)
            retry_s = self.check_quota(user)
            if retry_s > 0:
                # the 429 contract (docs/RESILIENCE.md): Retry-After so
                # common/retry.py-shaped clients back off instead of
                # hammering
                return web.json_response(
                    {"error": "quota exceeded"}, status=429,
                    headers={"Retry-After": str(int(retry_s))})
            request["user"] = user
            return await handler(request)
        return auth_middleware


class OAuthFlow:
    """Generic OAuth2 authorization-code sign-in.

    Role parity: reference ``manager/models/oauth.go`` +
    ``manager/handlers/oauth.go`` + ``manager/service/user.go`` oauth
    signin — providers are DB rows (github/google are just two rows here),
    the callback exchanges the code, reads the identity endpoint, and signs
    the external identity in as a namespaced local user."""

    def __init__(self, store, authenticator: Authenticator):
        self.store = store
        self.auth = authenticator

    async def signin_url(self, name: str, redirect_uri: str) -> str | None:
        import asyncio
        p = await asyncio.to_thread(self.store.oauth, name)
        if p is None:
            return None
        from urllib.parse import urlencode
        q = {"response_type": "code", "client_id": p["client_id"],
             "redirect_uri": redirect_uri,
             "state": self.auth.mint_state(name)}
        if p["scopes"]:
            q["scope"] = p["scopes"]
        sep = "&" if "?" in p["auth_url"] else "?"
        return p["auth_url"] + sep + urlencode(q)

    async def callback(self, name: str, code: str, state: str,
                       redirect_uri: str) -> dict | None:
        """code -> token -> identity -> local session; None = rejected."""
        import asyncio

        import aiohttp
        p = await asyncio.to_thread(self.store.oauth, name)
        if p is None or not self.auth.verify_state(state, name):
            return None
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(p["token_url"], data={
                        "grant_type": "authorization_code", "code": code,
                        "client_id": p["client_id"],
                        "client_secret": p["client_secret"],
                        "redirect_uri": redirect_uri},
                        headers={"Accept": "application/json"}) as resp:
                    if resp.status != 200:
                        return None
                    tok = await resp.json(content_type=None)
                access = tok.get("access_token")
                if not access:
                    return None
                async with s.get(p["userinfo_url"], headers={
                        "Authorization": f"Bearer {access}",
                        "Accept": "application/json"}) as resp:
                    if resp.status != 200:
                        return None
                    info = await resp.json(content_type=None)
        except Exception as exc:  # noqa: BLE001 - provider is external
            log.warning("oauth %s exchange failed: %s", name, exc)
            return None
        # STABLE identifiers first (sub/id): a mutable display name as the
        # identity key would let anyone rename themselves into someone
        # else's local account on providers without login/email claims
        login = str(info.get("sub") or info.get("id") or info.get("login")
                    or info.get("email") or "")
        if not login:
            return None
        # scrypt on first sign-in + sqlite: off the event loop, like every
        # other REST handler's store call
        user = await asyncio.to_thread(self.store.get_or_create_oauth_user,
                                       name, login)
        return {"token": self.auth.mint_session(user), "user": user}


def bootstrap_root(store, *, password_path: str = "") -> None:
    """First-boot root user: generated password persisted 0600 next to the
    DB (zero-touch bootstrap; the reference seeds a root user through its
    database migrations instead)."""
    rows = store._rows("SELECT id FROM users WHERE name='root'")
    if rows:
        return
    password = secrets.token_urlsafe(16)
    store.create_user("root", password, role="root")
    if password_path:
        fd = os.open(password_path,
                     os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(password + "\n")
        log.info("bootstrapped root user; password at %s", password_path)
    else:
        log.warning("bootstrapped root user with ephemeral password "
                    "(no password_path given): %s", password)
