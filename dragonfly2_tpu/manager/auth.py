"""Manager REST authentication + RBAC.

Role parity: reference ``manager/middlewares/{jwt,personal_access_token,
rbac}.go`` + ``manager/permission/rbac`` (casbin) + ``manager/auth``. The
same three mechanisms, stdlib-shaped:

- **Session tokens**: ``POST /api/v1/users/signin`` verifies a password
  (scrypt, store-side) and mints an HMAC-SHA256 bearer token with expiry
  (the reference's gin-jwt role).
- **Personal access tokens**: ``dfp_*`` bearer tokens checked against
  their sha256 in the store (reference middleware
  ``personal_access_token.go:30``).
- **RBAC**: method->action mapping (GET/HEAD = read, everything else =
  write; reference ``rbac.HTTPMethodToAction``) with two preset roles —
  ``root`` (all actions) and ``guest`` (read only), the reference's
  bootstrap policy.

The HMAC secret persists next to the DB so restarts don't invalidate
sessions.
"""

from __future__ import annotations

import base64
import hmac
import hashlib
import json
import logging
import os
import secrets
import time

from aiohttp import web

log = logging.getLogger("df.mgr.auth")

SESSION_TTL_S = 7 * 24 * 3600.0
# paths served without credentials (health, metrics, and signin itself)
PUBLIC_PATHS = {"/healthy", "/metrics", "/api/v1/users/signin"}


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(data: str) -> bytes:
    pad = -len(data) % 4
    return base64.urlsafe_b64decode(data + "=" * pad)


class Authenticator:
    def __init__(self, store, *, secret_path: str = ""):
        self.store = store
        if secret_path and os.path.exists(secret_path):
            with open(secret_path, "rb") as f:
                self._secret = f.read()
        else:
            self._secret = secrets.token_bytes(32)
            if secret_path:
                os.makedirs(os.path.dirname(secret_path) or ".",
                            exist_ok=True)
                # 0600 from CREATION: open+chmod leaves a world-readable
                # window (and a crash in it leaves the secret exposed)
                fd = os.open(secret_path,
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "wb") as f:
                    f.write(self._secret)

    # -- session tokens ------------------------------------------------

    def mint_session(self, user: dict) -> str:
        payload = json.dumps({"uid": user["id"], "name": user["name"],
                              "role": user["role"],
                              "exp": time.time() + SESSION_TTL_S})
        body = _b64(payload.encode())
        sig = _b64(hmac.new(self._secret, body.encode(),
                            hashlib.sha256).digest())
        return f"dfs_{body}.{sig}"

    def verify_session(self, token: str) -> dict | None:
        if not token.startswith("dfs_"):
            return None
        body, _, sig = token[4:].partition(".")
        want = _b64(hmac.new(self._secret, body.encode(),
                             hashlib.sha256).digest())
        if not hmac.compare_digest(sig, want):
            return None
        try:
            payload = json.loads(_unb64(body))
        except (ValueError, json.JSONDecodeError):
            return None
        if time.time() > payload.get("exp", 0):
            return None
        return {"id": payload["uid"], "name": payload["name"],
                "role": payload["role"]}

    # -- request authentication ----------------------------------------

    def authenticate(self, request: web.Request) -> dict | None:
        """The user behind the request's bearer token, or None."""
        auth = request.headers.get("Authorization", "")
        fields = auth.split()
        if len(fields) != 2 or fields[0] != "Bearer":
            return None
        token = fields[1]
        if token.startswith("dfs_"):
            return self.verify_session(token)
        return self.store.pat_user(token)

    @staticmethod
    def allowed(user: dict, method: str) -> bool:
        action = "read" if method in ("GET", "HEAD") else "write"
        if user["role"] == "root":
            return True
        return action == "read"        # guest: read-only

    def middleware(self):
        @web.middleware
        async def auth_middleware(request: web.Request, handler):
            if request.path in PUBLIC_PATHS:
                return await handler(request)
            user = self.authenticate(request)
            if user is None:
                return web.json_response({"error": "unauthorized"},
                                         status=401)
            if not self.allowed(user, request.method):
                return web.json_response({"error": "forbidden"}, status=403)
            request["user"] = user
            return await handler(request)
        return auth_middleware


def bootstrap_root(store, *, password_path: str = "") -> None:
    """First-boot root user: generated password persisted 0600 next to the
    DB (zero-touch bootstrap; the reference seeds a root user through its
    database migrations instead)."""
    rows = store._rows("SELECT id FROM users WHERE name='root'")
    if rows:
        return
    password = secrets.token_urlsafe(16)
    store.create_user("root", password, role="root")
    if password_path:
        fd = os.open(password_path,
                     os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(password + "\n")
        log.info("bootstrapped root user; password at %s", password_path)
    else:
        log.warning("bootstrapped root user with ephemeral password "
                    "(no password_path given): %s", password)
