"""Searcher: pick the scheduler cluster for an arriving peer.

Role parity: reference ``manager/searcher/searcher.go:106-156`` — weighted
affinity scoring of cluster scopes against the peer. The reference scores
CIDR 0.3 / hostname-regex / IDC / location / cluster-type; here the string
affinities become TPU fabric affinity: slice match outweighs zone match
outweighs CIDR, so peers land on the scheduler cluster closest to their
pod's wired mesh.
"""

from __future__ import annotations

import ipaddress
import json
import re

from ..idl.messages import GetSchedulersRequest

W_SLICE = 0.4
W_ZONE = 0.25
W_CIDR = 0.2
W_HOSTNAME = 0.1
W_DEFAULT = 0.05


def _score(scopes: dict, req: GetSchedulersRequest, is_default: bool) -> float:
    score = W_DEFAULT if is_default else 0.0
    topo = req.topology
    if topo is not None:
        slices = scopes.get("slices") or []
        if topo.slice_name and topo.slice_name in slices:
            score += W_SLICE
        zones = scopes.get("zones") or []
        if topo.zone and topo.zone in zones:
            score += W_ZONE
    for cidr in scopes.get("cidrs") or []:
        try:
            if req.ip and ipaddress.ip_address(req.ip) in \
                    ipaddress.ip_network(cidr, strict=False):
                score += W_CIDR
                break
        except ValueError:
            continue
    pattern = scopes.get("hostname_regex") or ""
    if pattern:
        try:
            if req.hostname and re.search(pattern, req.hostname):
                score += W_HOSTNAME
        except re.error:
            pass
    return score


_plugin_searcher = None


def load_searcher_plugin(plugin_dir: str, name: str = "default") -> None:
    """Operator override of the scoring (reference searcher plugin slot,
    ``manager/searcher/plugin.go``): a ``searcher``-type plugin exposing
    ``find_scheduler_cluster(clusters, req) -> int | None`` replaces the
    built-in affinity scorer."""
    global _plugin_searcher
    from ..common import plugins
    impl, _meta = plugins.load(plugin_dir, "searcher", name)
    if not callable(getattr(impl, "find_scheduler_cluster", None)):
        raise plugins.PluginError(
            "searcher plugin lacks find_scheduler_cluster()")
    _plugin_searcher = impl


def find_scheduler_cluster(clusters: list[dict],
                           req: GetSchedulersRequest) -> int | None:
    """Best-scoring cluster id, or None when there are no clusters."""
    if _plugin_searcher is not None:
        return _plugin_searcher.find_scheduler_cluster(clusters, req)
    best_id, best_score = None, -1.0
    for c in clusters:
        scopes = c.get("scopes")
        scopes = json.loads(scopes) if isinstance(scopes, str) else (scopes or {})
        s = _score(scopes, req, bool(c.get("is_default")))
        if s > best_score:
            best_id, best_score = c["id"], s
    return best_id
