"""Entity store: sqlite-backed tables for the manager's records.

Role parity: reference ``manager/models/*.go`` + ``manager/database`` (GORM
over MySQL/Postgres). The entity set is the subset the running system
consumes: scheduler clusters (with config), scheduler instances, seed-peer
clusters, seed-peer instances, applications, and jobs. sqlite keeps the
"database of record" property (restart-safe) without external services.
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import time
from typing import Any, Iterable

from ..idl.messages import (ClusterConfig, SchedulerEntity, SeedPeerEntity,
                            TopologyInfo)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS scheduler_clusters (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  config TEXT NOT NULL DEFAULT '{}',
  scopes TEXT NOT NULL DEFAULT '{}',
  is_default INTEGER NOT NULL DEFAULT 0,
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS schedulers (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  hostname TEXT NOT NULL, ip TEXT NOT NULL, port INTEGER NOT NULL,
  state TEXT NOT NULL DEFAULT 'inactive',
  scheduler_cluster_id INTEGER NOT NULL,
  features TEXT NOT NULL DEFAULT '[]',
  topology TEXT NOT NULL DEFAULT '{}',
  last_keepalive REAL NOT NULL DEFAULT 0,
  created_at REAL, updated_at REAL,
  UNIQUE(hostname, ip, port)
);
CREATE TABLE IF NOT EXISTS seed_peer_clusters (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  config TEXT NOT NULL DEFAULT '{}',
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS seed_peers (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  hostname TEXT NOT NULL, ip TEXT NOT NULL,
  port INTEGER NOT NULL, download_port INTEGER NOT NULL,
  object_storage_port INTEGER NOT NULL DEFAULT 0,
  type TEXT NOT NULL DEFAULT 'super',
  state TEXT NOT NULL DEFAULT 'inactive',
  seed_peer_cluster_id INTEGER NOT NULL,
  topology TEXT NOT NULL DEFAULT '{}',
  last_keepalive REAL NOT NULL DEFAULT 0,
  created_at REAL, updated_at REAL,
  UNIQUE(hostname, ip, port)
);
CREATE TABLE IF NOT EXISTS applications (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  url TEXT NOT NULL DEFAULT '',
  priority TEXT NOT NULL DEFAULT '{}',
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS tenants (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  qos_class TEXT NOT NULL DEFAULT '',
  max_running INTEGER NOT NULL DEFAULT 0,
  shed_retry_after_ms INTEGER NOT NULL DEFAULT 0,
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS scheduler_states (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  cluster_id INTEGER NOT NULL,
  scheduler_id TEXT NOT NULL,
  blob BLOB NOT NULL,
  signature TEXT NOT NULL DEFAULT '',
  updated_at REAL,
  UNIQUE(cluster_id, scheduler_id)
);
CREATE TABLE IF NOT EXISTS jobs (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  type TEXT NOT NULL,
  state TEXT NOT NULL DEFAULT 'pending',
  args TEXT NOT NULL DEFAULT '{}',
  result TEXT NOT NULL DEFAULT '{}',
  created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS models (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  version TEXT NOT NULL,
  state TEXT NOT NULL DEFAULT 'active',
  scheduler_cluster_id INTEGER NOT NULL DEFAULT 0,
  metrics TEXT NOT NULL DEFAULT '{}',
  data BLOB NOT NULL,
  created_at REAL,
  UNIQUE(name, version, scheduler_cluster_id)
);
CREATE TABLE IF NOT EXISTS users (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL UNIQUE,
  password_hash TEXT NOT NULL,
  role TEXT NOT NULL DEFAULT 'guest',
  created_at REAL
);
CREATE TABLE IF NOT EXISTS oauth_states (
  nonce TEXT PRIMARY KEY,
  expires_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS personal_access_tokens (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  token_hash TEXT NOT NULL UNIQUE,
  label TEXT NOT NULL DEFAULT '',
  user_id INTEGER NOT NULL,
  revoked INTEGER NOT NULL DEFAULT 0,
  expires_at REAL NOT NULL DEFAULT 0,
  created_at REAL
);
CREATE TABLE IF NOT EXISTS oauth_providers (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL UNIQUE,
  client_id TEXT NOT NULL,
  client_secret TEXT NOT NULL,
  auth_url TEXT NOT NULL,
  token_url TEXT NOT NULL,
  userinfo_url TEXT NOT NULL,
  scopes TEXT NOT NULL DEFAULT '',
  created_at REAL
);
"""


def _now() -> float:
    return time.time()


class Store:
    """Thread-safe sqlite store (the manager's aio handlers call via
    ``asyncio.to_thread`` for writes; reads are fast enough inline)."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        self._lock = threading.Lock()
        with self._lock:
            self._db.executescript(_SCHEMA)
            self._db.commit()

    def close(self) -> None:
        self._db.close()

    # -- generic helpers ----------------------------------------------

    def _exec(self, sql: str, args: Iterable[Any] = ()) -> sqlite3.Cursor:
        with self._lock:
            cur = self._db.execute(sql, tuple(args))
            self._db.commit()
            return cur

    def _rows(self, sql: str, args: Iterable[Any] = ()) -> list[sqlite3.Row]:
        with self._lock:
            return self._db.execute(sql, tuple(args)).fetchall()

    # -- clusters ------------------------------------------------------

    def create_scheduler_cluster(self, name: str, *,
                                 config: ClusterConfig | None = None,
                                 scopes: dict | None = None,
                                 is_default: bool = False) -> int:
        cfg = json.dumps(dataclasses.asdict(config or ClusterConfig()))
        cur = self._exec(
            "INSERT INTO scheduler_clusters(name, config, scopes, is_default,"
            " created_at, updated_at) VALUES (?,?,?,?,?,?)",
            (name, cfg, json.dumps(scopes or {}), int(is_default),
             _now(), _now()))
        return int(cur.lastrowid)

    def scheduler_clusters(self) -> list[dict]:
        return [dict(r) for r in self._rows(
            "SELECT * FROM scheduler_clusters ORDER BY id")]

    def cluster_config(self, cluster_id: int) -> ClusterConfig:
        rows = self._rows("SELECT config FROM scheduler_clusters WHERE id=?",
                          (cluster_id,))
        if not rows:
            return ClusterConfig()
        return ClusterConfig(**json.loads(rows[0]["config"]))

    def default_scheduler_cluster(self) -> int:
        rows = self._rows("SELECT id FROM scheduler_clusters WHERE is_default=1"
                          " ORDER BY id LIMIT 1")
        if rows:
            return int(rows[0]["id"])
        return self.create_scheduler_cluster(f"cluster-{_now():.0f}",
                                             is_default=True)

    def create_seed_peer_cluster(self, name: str) -> int:
        cur = self._exec(
            "INSERT INTO seed_peer_clusters(name, created_at, updated_at)"
            " VALUES (?,?,?)", (name, _now(), _now()))
        return int(cur.lastrowid)

    def seed_peer_clusters(self) -> list[dict]:
        return [dict(r) for r in self._rows(
            "SELECT * FROM seed_peer_clusters ORDER BY id")]

    def update_scheduler_cluster(self, cluster_id: int, *,
                                 config: ClusterConfig | None = None,
                                 scopes: dict | None = None) -> bool:
        """Partial update of a cluster's dynconfig payload (reference
        UpdateSchedulerCluster handler); schedulers pick the new config up
        on their next dynconfig refresh."""
        sets, args = [], []
        if config is not None:
            sets.append("config=?")
            args.append(json.dumps(dataclasses.asdict(config)))
        if scopes is not None:
            sets.append("scopes=?")
            args.append(json.dumps(scopes))
        if not sets:
            return False
        sets.append("updated_at=?")
        args += [_now(), cluster_id]
        cur = self._exec(
            f"UPDATE scheduler_clusters SET {', '.join(sets)} WHERE id=?",
            args)
        return cur.rowcount > 0

    def users(self) -> list[dict]:
        return [dict(r) for r in self._rows(
            "SELECT id, name, role, created_at FROM users ORDER BY id")]

    # -- scheduler instances ------------------------------------------

    def upsert_scheduler(self, *, hostname: str, ip: str, port: int,
                         cluster_id: int,
                         topology: TopologyInfo | None = None,
                         features: list[str] | None = None) -> int:
        topo = json.dumps(dataclasses.asdict(topology) if topology else {},
                          default=list)
        cur = self._exec(
            "INSERT INTO schedulers(hostname, ip, port, state,"
            " scheduler_cluster_id, features, topology, last_keepalive,"
            " created_at, updated_at)"
            " VALUES (?,?,?,'active',?,?,?,?,?,?)"
            " ON CONFLICT(hostname, ip, port) DO UPDATE SET"
            " state='active', scheduler_cluster_id=excluded.scheduler_cluster_id,"
            " topology=excluded.topology, last_keepalive=excluded.last_keepalive,"
            " updated_at=excluded.updated_at",
            (hostname, ip, port, cluster_id,
             json.dumps(features or []), topo, _now(), _now(), _now()))
        rows = self._rows(
            "SELECT id FROM schedulers WHERE hostname=? AND ip=? AND port=?",
            (hostname, ip, port))
        return int(rows[0]["id"])

    def schedulers(self, *, cluster_id: int | None = None,
                   only_active: bool = False) -> list[SchedulerEntity]:
        sql = "SELECT * FROM schedulers"
        args: list = []
        conds = []
        if cluster_id is not None:
            conds.append("scheduler_cluster_id=?")
            args.append(cluster_id)
        if only_active:
            conds.append("state='active'")
        if conds:
            sql += " WHERE " + " AND ".join(conds)
        out = []
        for r in self._rows(sql + " ORDER BY id", args):
            topo = json.loads(r["topology"])
            out.append(SchedulerEntity(
                id=r["id"], hostname=r["hostname"], ip=r["ip"],
                port=r["port"], state=r["state"],
                scheduler_cluster_id=r["scheduler_cluster_id"],
                features=json.loads(r["features"]),
                topology=TopologyInfo(**topo) if topo else None))
        return out

    # -- seed peer instances ------------------------------------------

    def upsert_seed_peer(self, *, hostname: str, ip: str, port: int,
                         download_port: int, cluster_id: int,
                         object_storage_port: int = 0, type_: str = "super",
                         topology: TopologyInfo | None = None) -> int:
        topo = json.dumps(dataclasses.asdict(topology) if topology else {},
                          default=list)
        self._exec(
            "INSERT INTO seed_peers(hostname, ip, port, download_port,"
            " object_storage_port, type, state, seed_peer_cluster_id,"
            " topology, last_keepalive, created_at, updated_at)"
            " VALUES (?,?,?,?,?,?,'active',?,?,?,?,?)"
            " ON CONFLICT(hostname, ip, port) DO UPDATE SET"
            " state='active', download_port=excluded.download_port,"
            " topology=excluded.topology, last_keepalive=excluded.last_keepalive,"
            " updated_at=excluded.updated_at",
            (hostname, ip, port, download_port, object_storage_port, type_,
             cluster_id, topo, _now(), _now(), _now()))
        rows = self._rows(
            "SELECT id FROM seed_peers WHERE hostname=? AND ip=? AND port=?",
            (hostname, ip, port))
        return int(rows[0]["id"])

    def seed_peers(self, *, cluster_id: int | None = None,
                   only_active: bool = False) -> list[SeedPeerEntity]:
        sql = "SELECT * FROM seed_peers"
        args: list = []
        conds = []
        if cluster_id is not None:
            conds.append("seed_peer_cluster_id=?")
            args.append(cluster_id)
        if only_active:
            conds.append("state='active'")
        if conds:
            sql += " WHERE " + " AND ".join(conds)
        out = []
        for r in self._rows(sql + " ORDER BY id", args):
            topo = json.loads(r["topology"])
            out.append(SeedPeerEntity(
                id=r["id"], hostname=r["hostname"], ip=r["ip"],
                port=r["port"], download_port=r["download_port"],
                object_storage_port=r["object_storage_port"],
                type=r["type"], state=r["state"],
                seed_peer_cluster_id=r["seed_peer_cluster_id"],
                topology=TopologyInfo(**topo) if topo else None))
        return out

    # -- keepalive -----------------------------------------------------

    def keepalive(self, source_type: str, hostname: str, ip: str,
                  port: int = 0) -> bool:
        """port=0 is a legacy wildcard; identity is (hostname, ip, port) —
        without the port one live instance would keep a dead same-host
        sibling marked active forever."""
        table = "schedulers" if source_type == "scheduler" else "seed_peers"
        sql = (f"UPDATE {table} SET last_keepalive=?, state='active',"
               " updated_at=? WHERE hostname=? AND ip=?")
        args: list = [_now(), _now(), hostname, ip]
        if port:
            sql += " AND port=?"
            args.append(port)
        cur = self._exec(sql, args)
        return cur.rowcount > 0

    def expire_stale(self, *, ttl_s: float) -> int:
        """Instances silent past the TTL flip to inactive (reference
        manager marks keepalive-lost instances the same way)."""
        cutoff = _now() - ttl_s
        n = 0
        for table in ("schedulers", "seed_peers"):
            cur = self._exec(
                f"UPDATE {table} SET state='inactive', updated_at=?"
                " WHERE state='active' AND last_keepalive < ?",
                (_now(), cutoff))
            n += cur.rowcount
        return n

    # -- applications & jobs ------------------------------------------

    def upsert_application(self, name: str, *, url: str = "",
                           priority: dict | None = None) -> int:
        self._exec(
            "INSERT INTO applications(name, url, priority, created_at,"
            " updated_at) VALUES (?,?,?,?,?)"
            " ON CONFLICT(name) DO UPDATE SET url=excluded.url,"
            " priority=excluded.priority, updated_at=excluded.updated_at",
            (name, url, json.dumps(priority or {}), _now(), _now()))
        return int(self._rows("SELECT id FROM applications WHERE name=?",
                              (name,))[0]["id"])

    def applications(self) -> list[dict]:
        return [dict(r) for r in self._rows(
            "SELECT * FROM applications ORDER BY id")]

    # -- tenants (multi-tenant QoS quotas) -----------------------------

    def upsert_tenant(self, name: str, *, qos_class: str = "",
                      max_running: int = 0,
                      shed_retry_after_ms: int = 0) -> int:
        self._exec(
            "INSERT INTO tenants(name, qos_class, max_running,"
            " shed_retry_after_ms, created_at, updated_at)"
            " VALUES (?,?,?,?,?,?)"
            " ON CONFLICT(name) DO UPDATE SET qos_class=excluded.qos_class,"
            " max_running=excluded.max_running,"
            " shed_retry_after_ms=excluded.shed_retry_after_ms,"
            " updated_at=excluded.updated_at",
            (name, qos_class, int(max_running), int(shed_retry_after_ms),
             _now(), _now()))
        return int(self._rows("SELECT id FROM tenants WHERE name=?",
                              (name,))[0]["id"])

    def tenants(self) -> list[dict]:
        return [dict(r) for r in self._rows(
            "SELECT * FROM tenants ORDER BY id")]

    # -- scheduler handoff blobs (control-plane failover) --------------

    def park_scheduler_state(self, *, cluster_id: int, scheduler_id: str,
                             blob: bytes, signature: str = "") -> None:
        """Park a demoting scheduler's exported quarantine/affinity
        summary so its ring successor can import it. One row per
        (cluster, scheduler); the manager relays blobs opaquely — it
        never parses them, and the signature travels with the blob so
        the importer (not the relay) verifies provenance."""
        self._exec(
            "INSERT INTO scheduler_states(cluster_id, scheduler_id, blob,"
            " signature, updated_at) VALUES (?,?,?,?,?)"
            " ON CONFLICT(cluster_id, scheduler_id) DO UPDATE SET"
            " blob=excluded.blob, signature=excluded.signature,"
            " updated_at=excluded.updated_at",
            (int(cluster_id), scheduler_id, blob, signature, _now()))

    def latest_scheduler_state(self, *, cluster_id: int,
                               exclude: str = "") -> dict | None:
        """Freshest parked blob in the cluster, skipping the asker's own
        export (a successor importing its own stale summary would learn
        nothing and age its evidence twice)."""
        rows = self._rows(
            "SELECT * FROM scheduler_states WHERE cluster_id=? AND"
            " scheduler_id != ? ORDER BY updated_at DESC LIMIT 1",
            (int(cluster_id), exclude))
        return dict(rows[0]) if rows else None

    def create_job(self, type_: str, args: dict) -> int:
        cur = self._exec(
            "INSERT INTO jobs(type, state, args, created_at, updated_at)"
            " VALUES (?,?,?,?,?)",
            (type_, "pending", json.dumps(args), _now(), _now()))
        return int(cur.lastrowid)

    def update_job(self, job_id: int, *, state: str,
                   result: dict | None = None) -> None:
        self._exec("UPDATE jobs SET state=?, result=?, updated_at=? WHERE id=?",
                   (state, json.dumps(result or {}), _now(), job_id))

    def job(self, job_id: int) -> dict | None:
        rows = self._rows("SELECT * FROM jobs WHERE id=?", (job_id,))
        return dict(rows[0]) if rows else None

    # -- model registry (reference manager/models/model.go:36) ---------

    def create_model(self, *, name: str, version: str, data: bytes,
                     metrics: dict | None = None,
                     scheduler_cluster_id: int = 0) -> int:
        """Insert one model version; the newest active version per name is
        the one ``get_model`` serves by default. Idempotent per version."""
        self._exec(
            "INSERT INTO models(name, version, state, scheduler_cluster_id,"
            " metrics, data, created_at) VALUES (?,?,'active',?,?,?,?)"
            " ON CONFLICT(name, version, scheduler_cluster_id) DO UPDATE SET"
            " metrics=excluded.metrics, state='active'",
            (name, version, scheduler_cluster_id,
             json.dumps(metrics or {}), data, _now()))
        rows = self._rows(
            "SELECT id FROM models WHERE name=? AND version=?"
            " AND scheduler_cluster_id=?",
            (name, version, scheduler_cluster_id))
        return int(rows[0]["id"])

    def get_model(self, name: str, *, version: str = "",
                  scheduler_cluster_id: int = 0) -> dict | None:
        sql = ("SELECT * FROM models WHERE name=? AND state='active'"
               " AND scheduler_cluster_id IN (0, ?)")
        args: list = [name, scheduler_cluster_id]
        if version:
            sql += " AND version=?"
            args.append(version)
        sql += " ORDER BY created_at DESC, id DESC LIMIT 1"
        rows = self._rows(sql, args)
        if not rows:
            return None
        r = dict(rows[0])
        r["metrics"] = json.loads(r["metrics"])
        return r

    def models(self, *, name: str | None = None) -> list[dict]:
        """Listing without blobs (REST index view)."""
        sql = ("SELECT id, name, version, state, scheduler_cluster_id,"
               " metrics, length(data) AS size, created_at FROM models")
        args: list = []
        if name:
            sql += " WHERE name=?"
            args.append(name)
        out = []
        for r in self._rows(sql + " ORDER BY id", args):
            d = dict(r)
            d["metrics"] = json.loads(d["metrics"])
            out.append(d)
        return out

    def jobs(self, *, state: str | None = None) -> list[dict]:
        if state:
            return [dict(r) for r in self._rows(
                "SELECT * FROM jobs WHERE state=? ORDER BY id", (state,))]
        return [dict(r) for r in self._rows("SELECT * FROM jobs ORDER BY id")]

    # -- users + personal access tokens (reference manager/models/user.go,
    # -- personal_access_token.go; middleware personal_access_token.go) ----

    @staticmethod
    def _hash_password(password: str, salt: bytes | None = None) -> str:
        import hashlib
        import os as _os
        salt = salt or _os.urandom(16)
        dk = hashlib.scrypt(password.encode(), salt=salt, n=2**14, r=8, p=1)
        return salt.hex() + "$" + dk.hex()

    def create_user(self, name: str, password: str, *,
                    role: str = "guest") -> int:
        if role not in ("root", "guest"):
            raise ValueError(f"unknown role {role!r}")
        cur = self._exec(
            "INSERT INTO users(name, password_hash, role, created_at) "
            "VALUES(?,?,?,?)",
            (name, self._hash_password(password), role, _now()))
        return cur.lastrowid

    def verify_user(self, name: str, password: str) -> dict | None:
        import hashlib
        import hmac as _hmac
        rows = self._rows("SELECT * FROM users WHERE name=?", (name,))
        if not rows:
            return None
        user = dict(rows[0])
        salt_hex, _, want = user["password_hash"].partition("$")
        dk = hashlib.scrypt(password.encode(), salt=bytes.fromhex(salt_hex),
                            n=2**14, r=8, p=1)
        if not _hmac.compare_digest(dk.hex(), want):
            return None
        user.pop("password_hash", None)
        return user

    def user(self, user_id: int) -> dict | None:
        rows = self._rows("SELECT id, name, role, created_at FROM users "
                          "WHERE id=?", (user_id,))
        return dict(rows[0]) if rows else None

    @staticmethod
    def _token_hash(token: str) -> str:
        import hashlib
        return hashlib.sha256(token.encode()).hexdigest()

    def create_pat(self, user_id: int, *, label: str = "",
                   ttl_s: float = 0.0) -> str:
        """Mint a personal access token; only its HASH is stored (a DB leak
        must not leak bearer credentials)."""
        import secrets
        token = "dfp_" + secrets.token_urlsafe(32)
        expires = _now() + ttl_s if ttl_s > 0 else 0.0
        self._exec(
            "INSERT INTO personal_access_tokens"
            "(token_hash, label, user_id, expires_at, created_at) "
            "VALUES(?,?,?,?,?)",
            (self._token_hash(token), label, user_id, expires, _now()))
        return token

    def pat_user(self, token: str) -> dict | None:
        """The user behind a live PAT, or None (unknown/revoked/expired)."""
        rows = self._rows(
            "SELECT u.id, u.name, u.role, p.expires_at, p.revoked "
            "FROM personal_access_tokens p JOIN users u ON u.id=p.user_id "
            "WHERE p.token_hash=?", (self._token_hash(token),))
        if not rows:
            return None
        row = dict(rows[0])
        if row.pop("revoked"):
            return None
        expires = row.pop("expires_at")
        if expires and _now() > expires:
            return None
        return row

    # -- oauth sign-in states (single-use, DB-backed so they survive a
    # manager restart and work across replicas sharing the DB) -----------

    OAUTH_STATE_CAP = 10_000

    def save_oauth_nonce(self, nonce: str, expires_at: float) -> bool:
        """False when the active-state cap is hit: /signin is public, so
        an unauthenticated mint flood must saturate a bounded table, not
        the manager's memory/disk."""
        self._exec("DELETE FROM oauth_states WHERE expires_at < ?",
                   (_now(),))
        n = self._rows("SELECT COUNT(*) AS n FROM oauth_states")[0]["n"]
        if n >= self.OAUTH_STATE_CAP:
            return False
        self._exec("INSERT OR REPLACE INTO oauth_states(nonce, expires_at)"
                   " VALUES (?,?)", (nonce, expires_at))
        return True

    def consume_oauth_nonce(self, nonce: str) -> bool:
        """Atomically consume: True exactly once per saved nonce."""
        cur = self._exec(
            "DELETE FROM oauth_states WHERE nonce=? AND expires_at >= ?",
            (nonce, _now()))
        return cur.rowcount > 0

    # -- oauth providers (reference ``manager/models/oauth.go``) ---------

    def create_oauth(self, name: str, *, client_id: str, client_secret: str,
                     auth_url: str, token_url: str, userinfo_url: str,
                     scopes: str = "") -> int:
        cur = self._exec(
            "INSERT INTO oauth_providers(name, client_id, client_secret, "
            "auth_url, token_url, userinfo_url, scopes, created_at) "
            "VALUES(?,?,?,?,?,?,?,?)",
            (name, client_id, client_secret, auth_url, token_url,
             userinfo_url, scopes, _now()))
        return cur.lastrowid

    def oauth(self, name: str) -> dict | None:
        rows = self._rows("SELECT * FROM oauth_providers WHERE name=?",
                          (name,))
        return dict(rows[0]) if rows else None

    def oauths(self) -> list[dict]:
        """Provider list WITHOUT client secrets (REST-exposed)."""
        return [dict(r) for r in self._rows(
            "SELECT id, name, client_id, auth_url, token_url, userinfo_url, "
            "scopes, created_at FROM oauth_providers ORDER BY id")]

    def delete_oauth(self, oauth_id: int) -> bool:
        cur = self._exec("DELETE FROM oauth_providers WHERE id=?",
                         (oauth_id,))
        return cur.rowcount > 0

    def get_or_create_oauth_user(self, provider: str, login: str) -> dict:
        """The local user backing an external identity — created on first
        sign-in with an unusable password and the guest role (an operator
        promotes from there), namespaced so an attacker can't pre-register
        a colliding local username."""
        import secrets
        import sqlite3
        name = f"{provider}:{login}"
        rows = self._rows(
            "SELECT id, name, role, created_at FROM users WHERE name=?",
            (name,))
        if rows:
            return dict(rows[0])
        try:
            uid = self.create_user(name, secrets.token_urlsafe(32))
        except sqlite3.IntegrityError:
            # concurrent first sign-ins race the SELECT: the loser re-reads
            rows = self._rows(
                "SELECT id, name, role, created_at FROM users WHERE name=?",
                (name,))
            return dict(rows[0])
        return self.user(uid)

    def pats(self, user_id: int | None = None) -> list[dict]:
        sql = ("SELECT id, label, user_id, revoked, expires_at, created_at "
               "FROM personal_access_tokens")
        args: list = []
        if user_id is not None:
            sql += " WHERE user_id=?"
            args.append(user_id)
        return [dict(r) for r in self._rows(sql + " ORDER BY id", args)]

    def revoke_pat(self, pat_id: int) -> None:
        self._exec("UPDATE personal_access_tokens SET revoked=1 WHERE id=?",
                   (pat_id,))
