"""Manager job runner: preheat fan-out to scheduler instances.

Role parity: reference ``manager/job/preheat.go`` + ``internal/job``
(machinery group jobs over Redis queues). Here the queue is in-process and
delivery is a direct gRPC ``Preheat`` call to each target scheduler — same
verb, no broker.
"""

from __future__ import annotations

import asyncio
import logging

from ..idl.messages import PreheatRequest, SyncPeersRequest, UrlMeta
from ..rpc.client import ChannelPool, ServiceClient
from .store import Store

log = logging.getLogger("df.mgr.jobs")

SCHEDULER_SERVICE = "df.scheduler.Scheduler"


class JobRunner:
    def __init__(self, store: Store):
        self.store = store
        self._channels = ChannelPool(limit=64)
        self._running: set[asyncio.Task] = set()

    async def submit_preheat(self, *, url: str, url_meta: UrlMeta | None = None,
                             cluster_id: int | None = None) -> int:
        import dataclasses
        job_id = await asyncio.to_thread(
            self.store.create_job, "preheat",
            {"url": url, "cluster_id": cluster_id,
             # persisted so a crash-resume preheats the SAME task id
             # (UrlMeta participates in the task id)
             "url_meta": dataclasses.asdict(url_meta) if url_meta else None})
        t = asyncio.get_running_loop().create_task(
            self._run_preheat(job_id, url, url_meta, cluster_id))
        self._running.add(t)
        t.add_done_callback(self._running.discard)
        return job_id

    async def _fan_out(self, job_id: int, cluster_id: int | None,
                       kind: str, call) -> None:
        """Shared job scaffold: mark running, call every active scheduler
        with per-target isolation, aggregate, write the final state.
        ``call(client, addr)`` returns (result_dict, ok_bool)."""
        await asyncio.to_thread(self.store.update_job, job_id,
                                state="running")
        schedulers = await asyncio.to_thread(
            lambda: self.store.schedulers(cluster_id=cluster_id,
                                          only_active=True))
        if not schedulers:
            await asyncio.to_thread(self.store.update_job, job_id,
                                    state="failed",
                                    result={"error": "no active schedulers"})
            return
        results = {}
        ok = 0
        for sched in schedulers:
            addr = f"{sched.ip}:{sched.port}"
            try:
                client = ServiceClient(self._channels.get(addr),
                                       SCHEDULER_SERVICE)
                result, good = await call(client, addr)
                results[addr] = result
                if good:
                    ok += 1
            except Exception as exc:  # noqa: BLE001 - per-target isolation
                results[addr] = {"state": "failed", "error": str(exc)}
        state = "succeeded" if ok else "failed"
        await asyncio.to_thread(self.store.update_job, job_id, state=state,
                                result=results)
        log.info("%s job %d %s across %d scheduler(s)", kind, job_id, state,
                 len(schedulers))

    async def _run_preheat(self, job_id: int, url: str,
                           url_meta: UrlMeta | None,
                           cluster_id: int | None) -> None:
        async def call(client, addr):
            resp = await client.unary(
                "Preheat", PreheatRequest(url=url, url_meta=url_meta,
                                          wait=True), timeout=600.0)
            return ({"state": resp.state, "task_id": resp.task_id},
                    resp.state == "succeeded")

        await self._fan_out(job_id, cluster_id, "preheat", call)

    async def submit_sync_peers(self, *,
                                cluster_id: int | None = None) -> int:
        """Fan SyncPeers to active schedulers; the aggregated live-host
        view lands in the job result (reference manager/job/sync_peers.go
        aggregating scheduler/job syncPeers)."""
        job_id = await asyncio.to_thread(
            self.store.create_job, "sync_peers", {"cluster_id": cluster_id})
        t = asyncio.get_running_loop().create_task(
            self._run_sync_peers(job_id, cluster_id))
        self._running.add(t)
        t.add_done_callback(self._running.discard)
        return job_id

    async def _run_sync_peers(self, job_id: int,
                              cluster_id: int | None) -> None:
        async def call(client, addr):
            resp = await client.unary(
                "SyncPeers", SyncPeersRequest(cluster_id=cluster_id or 0),
                timeout=60.0)
            hosts = resp.hosts or []
            return ({"state": "succeeded",
                     "hosts": [{"id": h.id, "ip": h.ip,
                                "hostname": h.hostname, "type": int(h.type),
                                "download_port": h.download_port}
                               for h in hosts]}, True)

        await self._fan_out(job_id, cluster_id, "sync_peers", call)

    async def resume_interrupted(self) -> int:
        """Durable-queue semantics (reference internal/job over Redis keeps
        jobs across restarts): jobs the previous process left in
        pending/running are re-dispatched at boot. Both job types are
        idempotent — preheat re-triggers a seed that may already hold the
        content, sync_peers just re-reads state."""
        import json as _json

        # ONE snapshot before any dispatch: spawning from a first query and
        # then querying again would pick up the same job twice (a spawned
        # task flips pending->running between the queries)
        snapshot = [job
                    for state in ("pending", "running")
                    for job in await asyncio.to_thread(
                        lambda s=state: self.store.jobs(state=s))]
        seen: set[int] = set()
        resumed = 0
        for job in snapshot:
            if job["id"] in seen:
                continue
            seen.add(job["id"])
            args = _json.loads(job["args"] or "{}")
            if job["type"] == "preheat" and args.get("url"):
                meta = (UrlMeta(**args["url_meta"])
                        if args.get("url_meta") else None)
                t = asyncio.get_running_loop().create_task(
                    self._run_preheat(job["id"], args["url"], meta,
                                      args.get("cluster_id")))
            elif job["type"] == "sync_peers":
                t = asyncio.get_running_loop().create_task(
                    self._run_sync_peers(job["id"],
                                         args.get("cluster_id")))
            else:
                # unresumable (unknown type / malformed args): park it in a
                # terminal state — perpetual 'running' is the stuck state
                # this scan exists to eliminate
                await asyncio.to_thread(
                    self.store.update_job, job["id"], state="failed",
                    result={"error": f"unresumable job "
                                     f"type={job['type']!r}"})
                continue
            self._running.add(t)
            t.add_done_callback(self._running.discard)
            resumed += 1
        if resumed:
            log.info("resumed %d interrupted job(s)", resumed)
        return resumed

    async def close(self) -> None:
        for t in list(self._running):
            t.cancel()
        await asyncio.gather(*self._running, return_exceptions=True)
        await self._channels.close()
