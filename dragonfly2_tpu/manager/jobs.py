"""Manager job runner: preheat fan-out to scheduler instances.

Role parity: reference ``manager/job/preheat.go`` + ``internal/job``
(machinery group jobs over Redis queues). Here the queue is in-process and
delivery is a direct gRPC ``Preheat`` call to each target scheduler — same
verb, no broker.
"""

from __future__ import annotations

import asyncio
import logging

from ..idl.messages import PreheatRequest, SyncPeersRequest, UrlMeta
from ..rpc.client import ChannelPool, ServiceClient
from .store import Store

log = logging.getLogger("df.mgr.jobs")

SCHEDULER_SERVICE = "df.scheduler.Scheduler"


class JobRunner:
    def __init__(self, store: Store):
        self.store = store
        self._channels = ChannelPool(limit=64)
        self._running: set[asyncio.Task] = set()

    async def submit_preheat(self, *, url: str, url_meta: UrlMeta | None = None,
                             cluster_id: int | None = None,
                             type_: str = "file",
                             platform: str = "") -> int:
        """``type_`` "file" preheats one URL; "image" treats ``url`` as an
        OCI manifest reference (``.../v2/<name>/manifests/<ref>``),
        resolves it (manifest lists filtered by ``platform`` "os/arch"),
        and preheats every config+layer blob (reference
        ``manager/job/preheat.go`` getImageLayers)."""
        import dataclasses
        job_id = await asyncio.to_thread(
            self.store.create_job, "preheat",
            {"url": url, "cluster_id": cluster_id,
             "type": type_, "platform": platform,
             # persisted so a crash-resume preheats the SAME task id
             # (UrlMeta participates in the task id)
             "url_meta": dataclasses.asdict(url_meta) if url_meta else None})
        t = asyncio.get_running_loop().create_task(
            self._run_preheat(job_id, url, url_meta, cluster_id,
                              type_=type_, platform=platform))
        self._running.add(t)
        t.add_done_callback(self._running.discard)
        return job_id

    async def _fan_out(self, job_id: int, cluster_id: int | None,
                       kind: str, call) -> None:
        """Shared job scaffold: mark running, call every active scheduler
        with per-target isolation, aggregate, write the final state.
        ``call(client, addr)`` returns (result_dict, ok_bool)."""
        await asyncio.to_thread(self.store.update_job, job_id,
                                state="running")
        schedulers = await asyncio.to_thread(
            lambda: self.store.schedulers(cluster_id=cluster_id,
                                          only_active=True))
        if not schedulers:
            await asyncio.to_thread(self.store.update_job, job_id,
                                    state="failed",
                                    result={"error": "no active schedulers"})
            return
        results = {}
        ok = 0
        for sched in schedulers:
            addr = f"{sched.ip}:{sched.port}"
            try:
                client = ServiceClient(self._channels.get(addr),
                                       SCHEDULER_SERVICE)
                result, good = await call(client, addr)
                results[addr] = result
                if good:
                    ok += 1
            except Exception as exc:  # noqa: BLE001 - per-target isolation
                results[addr] = {"state": "failed", "error": str(exc)}
        state = "succeeded" if ok else "failed"
        await asyncio.to_thread(self.store.update_job, job_id, state=state,
                                result=results)
        log.info("%s job %d %s across %d scheduler(s)", kind, job_id, state,
                 len(schedulers))

    async def _run_preheat(self, job_id: int, url: str,
                           url_meta: UrlMeta | None,
                           cluster_id: int | None, *,
                           type_: str = "file",
                           platform: str = "") -> None:
        urls = [url]
        blob_meta = url_meta
        if type_ == "image":
            try:
                urls, auth = await self._resolve_image_layers(url, url_meta,
                                                              platform)
            except Exception as exc:  # noqa: BLE001 - job outcome, not crash
                await asyncio.to_thread(
                    self.store.update_job, job_id, state="failed",
                    result={"error": f"image resolution failed: {exc}"})
                return
            if not urls:
                await asyncio.to_thread(
                    self.store.update_job, job_id, state="failed",
                    result={"error": "image has no matching platform "
                                     "manifests/layers"})
                return
            if auth:
                # the SEEDS fetch the blobs: hand them the registry token
                # the resolution negotiated (reference parseLayers sets the
                # Authorization header on each layer's PreheatRequest).
                # Headers do not participate in the task id.
                import dataclasses
                base_meta = blob_meta or UrlMeta()
                blob_meta = dataclasses.replace(
                    base_meta, header={**(base_meta.header or {}), **auth})

        async def call(client, addr):
            # blobs are independent: overlap them (bounded) so the job
            # resolves at the slowest blob, not the sum of all of them
            sem = asyncio.Semaphore(8)

            async def one(u: str) -> dict:
                async with sem:
                    resp = await client.unary(
                        "Preheat", PreheatRequest(url=u, url_meta=blob_meta,
                                                  wait=True), timeout=600.0)
                return {"url": u, "state": resp.state,
                        "task_id": resp.task_id}

            states = list(await asyncio.gather(*[one(u) for u in urls]))
            good = all(s["state"] == "succeeded" for s in states)
            if type_ == "image":
                return ({"state": "succeeded" if good else "failed",
                         "blobs": states}, good)
            return (states[0], good)

        await self._fan_out(job_id, cluster_id, "preheat", call)

    # -- OCI image resolution (reference manager/job/preheat.go) ---------

    @staticmethod
    def _parse_bearer_challenge(header: str) -> dict:
        import re
        return dict(re.findall(r'(\w+)="([^"]*)"', header))

    async def _registry_get(self, session, url: str, headers: dict,
                            auth: dict) -> tuple[int, dict, bytes]:
        """GET with the standard registry token dance: on 401 with a
        Bearer challenge, fetch a token from the advertised realm and
        retry once (reference newImageAuthClient). A won token lands in
        ``auth`` (mutated) so later requests — and the seeds' blob
        fetches — reuse it."""
        async with session.get(url, headers={**headers, **auth}) as resp:
            if resp.status != 401:
                return resp.status, dict(resp.headers), await resp.read()
            challenge = resp.headers.get("WWW-Authenticate", "")
        ch = self._parse_bearer_challenge(challenge)
        realm = ch.get("realm")
        if not challenge.lower().startswith("bearer") or not realm:
            return 401, {}, b""
        params = {k: v for k, v in ch.items()
                  if k in ("service", "scope") and v}
        async with session.get(realm, params=params) as tresp:
            if tresp.status != 200:
                return 401, {}, b""
            tok = (await tresp.json()).get("token") or ""
        auth["Authorization"] = f"Bearer {tok}"
        async with session.get(url, headers={**headers, **auth}) as resp:
            return resp.status, dict(resp.headers), await resp.read()

    async def _resolve_image_layers(self, url: str,
                                    url_meta: UrlMeta | None,
                                    platform: str
                                    ) -> tuple[list[str], dict]:
        """Manifest reference -> (every config+layer blob URL, the auth
        header the token dance won, for the seeds' blob fetches),
        following one level of manifest list/index (filtered by
        ``platform`` "os/arch" when given, like reference
        filterManifests)."""
        import json as _json

        import aiohttp

        base, _, _ref = url.rpartition("/manifests/")
        if not base:
            raise ValueError(f"not a manifest reference: {url}")
        LIST_TYPES = (
            "application/vnd.docker.distribution.manifest.list.v2+json",
            "application/vnd.oci.image.index.v1+json")
        MANIFEST_TYPES = (
            "application/vnd.docker.distribution.manifest.v2+json",
            "application/vnd.oci.image.manifest.v1+json")
        headers = dict((url_meta.header or {}) if url_meta else {})
        headers["Accept"] = ", ".join((*LIST_TYPES, *MANIFEST_TYPES))
        want_os = want_arch = ""
        if platform:
            want_os, _, want_arch = platform.partition("/")
        blobs: list[str] = []
        auth: dict = {}
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60.0)) as session:
            status, hdrs, body = await self._registry_get(session, url,
                                                          headers, auth)
            if status != 200:
                raise ValueError(f"manifest fetch {status} for {url}")
            doc = _json.loads(body)
            ctype = hdrs.get("Content-Type", doc.get("mediaType", ""))
            manifests = [doc]
            if ctype in LIST_TYPES or "manifests" in doc:
                manifests = []
                for entry in doc.get("manifests", []):
                    p = entry.get("platform") or {}
                    if platform and (p.get("os") != want_os
                                     or p.get("architecture") != want_arch):
                        continue
                    status, _h, mbody = await self._registry_get(
                        session, f"{base}/manifests/{entry['digest']}",
                        headers, auth)
                    if status != 200:
                        raise ValueError(
                            f"sub-manifest fetch {status} for "
                            f"{entry['digest']}")
                    manifests.append(_json.loads(mbody))
            for m in manifests:
                cfg = (m.get("config") or {}).get("digest")
                if cfg:
                    blobs.append(f"{base}/blobs/{cfg}")
                for layer in m.get("layers", []):
                    if layer.get("digest"):
                        blobs.append(f"{base}/blobs/{layer['digest']}")
        # dedup preserving order (shared layers across platforms)
        seen: set[str] = set()
        return ([b for b in blobs if not (b in seen or seen.add(b))], auth)

    async def submit_sync_peers(self, *,
                                cluster_id: int | None = None) -> int:
        """Fan SyncPeers to active schedulers; the aggregated live-host
        view lands in the job result (reference manager/job/sync_peers.go
        aggregating scheduler/job syncPeers)."""
        job_id = await asyncio.to_thread(
            self.store.create_job, "sync_peers", {"cluster_id": cluster_id})
        t = asyncio.get_running_loop().create_task(
            self._run_sync_peers(job_id, cluster_id))
        self._running.add(t)
        t.add_done_callback(self._running.discard)
        return job_id

    async def _run_sync_peers(self, job_id: int,
                              cluster_id: int | None) -> None:
        async def call(client, addr):
            resp = await client.unary(
                "SyncPeers", SyncPeersRequest(cluster_id=cluster_id or 0),
                timeout=60.0)
            hosts = resp.hosts or []
            return ({"state": "succeeded",
                     "hosts": [{"id": h.id, "ip": h.ip,
                                "hostname": h.hostname, "type": int(h.type),
                                "download_port": h.download_port}
                               for h in hosts]}, True)

        await self._fan_out(job_id, cluster_id, "sync_peers", call)

    async def resume_interrupted(self) -> int:
        """Durable-queue semantics (reference internal/job over Redis keeps
        jobs across restarts): jobs the previous process left in
        pending/running are re-dispatched at boot. Both job types are
        idempotent — preheat re-triggers a seed that may already hold the
        content, sync_peers just re-reads state."""
        import json as _json

        # ONE snapshot before any dispatch: spawning from a first query and
        # then querying again would pick up the same job twice (a spawned
        # task flips pending->running between the queries)
        snapshot = [job
                    for state in ("pending", "running")
                    for job in await asyncio.to_thread(
                        lambda s=state: self.store.jobs(state=s))]
        seen: set[int] = set()
        resumed = 0
        for job in snapshot:
            if job["id"] in seen:
                continue
            seen.add(job["id"])
            args = _json.loads(job["args"] or "{}")
            if job["type"] == "preheat" and args.get("url"):
                meta = (UrlMeta(**args["url_meta"])
                        if args.get("url_meta") else None)
                t = asyncio.get_running_loop().create_task(
                    self._run_preheat(job["id"], args["url"], meta,
                                      args.get("cluster_id"),
                                      type_=args.get("type", "file"),
                                      platform=args.get("platform", "")))
            elif job["type"] == "sync_peers":
                t = asyncio.get_running_loop().create_task(
                    self._run_sync_peers(job["id"],
                                         args.get("cluster_id")))
            else:
                # unresumable (unknown type / malformed args): park it in a
                # terminal state — perpetual 'running' is the stuck state
                # this scan exists to eliminate
                await asyncio.to_thread(
                    self.store.update_job, job["id"], state="failed",
                    result={"error": f"unresumable job "
                                     f"type={job['type']!r}"})
                continue
            self._running.add(t)
            t.add_done_callback(self._running.discard)
            resumed += 1
        if resumed:
            log.info("resumed %d interrupted job(s)", resumed)
        return resumed

    async def close(self) -> None:
        for t in list(self._running):
            t.cancel()
        await asyncio.gather(*self._running, return_exceptions=True)
        await self._channels.close()
