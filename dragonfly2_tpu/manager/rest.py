"""Manager REST API.

Role parity: reference ``manager/handlers`` + ``manager/router`` (gin REST
CRUD + swagger). The surface is the operational subset: cluster and
instance listing/creation, applications, preheat job POST + status, and
health — JSON over aiohttp.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging

from aiohttp import web

from ..common.aiohttp_util import resolve_port
from ..common.errors import DFError
from ..common.metrics import REGISTRY
from ..idl.messages import ClusterConfig, UrlMeta
from .jobs import JobRunner
from .store import Store

log = logging.getLogger("df.mgr.rest")


class RestAPI:
    def __init__(self, store: Store, jobs: JobRunner, *, host: str = "0.0.0.0",
                 port: int = 0, auth=None):
        """``auth``: an ``auth.Authenticator`` — None leaves the API open
        (dev mode, reference parity with auth middleware disabled)."""
        self.store = store
        self.jobs = jobs
        self.host = host
        self.port = port
        self.auth = auth
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        middlewares = [self.auth.middleware()] if self.auth else []
        app = web.Application(middlewares=middlewares)
        r = app.router
        r.add_get("/healthy", self._healthy)
        r.add_get("/metrics", self._metrics)
        r.add_get("/api/v1/scheduler-clusters", self._list_sched_clusters)
        r.add_post("/api/v1/scheduler-clusters", self._create_sched_cluster)
        r.add_get("/api/v1/schedulers", self._list_schedulers)
        r.add_get("/api/v1/seed-peers", self._list_seed_peers)
        r.add_get("/api/v1/applications", self._list_applications)
        r.add_post("/api/v1/applications", self._create_application)
        r.add_get("/api/v1/tenants", self._list_tenants)
        r.add_post("/api/v1/tenants", self._create_tenant)
        r.add_post("/api/v1/jobs", self._create_job)
        r.add_get("/api/v1/jobs", self._list_jobs)
        r.add_get("/api/v1/jobs/{id}", self._get_job)
        r.add_get("/api/v1/models", self._list_models)
        r.add_post("/api/v1/users/signin", self._signin)
        r.add_post("/api/v1/users", self._create_user)
        r.add_post("/api/v1/personal-access-tokens", self._create_pat)
        r.add_get("/api/v1/personal-access-tokens", self._list_pats)
        r.add_delete("/api/v1/personal-access-tokens/{id}", self._revoke_pat)
        r.add_get("/api/v1/oauth", self._list_oauth)
        r.add_post("/api/v1/oauth", self._create_oauth)
        r.add_delete("/api/v1/oauth/{id}", self._delete_oauth)
        r.add_get("/api/v1/seed-peer-clusters", self._list_sp_clusters)
        r.add_post("/api/v1/seed-peer-clusters", self._create_sp_cluster)
        r.add_patch("/api/v1/scheduler-clusters/{id}",
                    self._update_sched_cluster)
        r.add_get("/api/v1/users", self._list_users)
        if self.auth is not None:
            from .auth import OAuthFlow
            self._oauth_flow = OAuthFlow(self.store, self.auth)
            r.add_get("/oauth/signin/{name}", self._oauth_signin)
            r.add_get("/oauth/callback/{name}", self._oauth_callback)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = resolve_port(self._runner)
        log.info("manager REST on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------------

    async def _healthy(self, _r: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def _metrics(self, _r: web.Request) -> web.Response:
        return web.Response(text=REGISTRY.expose())

    async def _list_sched_clusters(self, _r: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.store.scheduler_clusters))

    async def _create_sched_cluster(self, request: web.Request) -> web.Response:
        body = await request.json()
        cfg = ClusterConfig(**body.get("config", {}))
        cid = await asyncio.to_thread(
            lambda: self.store.create_scheduler_cluster(
                body["name"], config=cfg, scopes=body.get("scopes"),
                is_default=bool(body.get("is_default"))))
        return web.json_response({"id": cid}, status=201)

    async def _list_schedulers(self, _r: web.Request) -> web.Response:
        return web.json_response([
            dataclasses.asdict(s) for s in
            await asyncio.to_thread(self.store.schedulers)])

    async def _list_seed_peers(self, _r: web.Request) -> web.Response:
        return web.json_response([
            dataclasses.asdict(s) for s in
            await asyncio.to_thread(self.store.seed_peers)])

    async def _list_applications(self, _r: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.store.applications))

    async def _create_application(self, request: web.Request) -> web.Response:
        body = await request.json()
        app_id = await asyncio.to_thread(
            lambda: self.store.upsert_application(
                body["name"], url=body.get("url", ""),
                priority=body.get("priority")))
        return web.json_response({"id": app_id}, status=201)

    async def _list_tenants(self, _r: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.store.tenants))

    async def _create_tenant(self, request: web.Request) -> web.Response:
        """Tenant quota row (multi-tenant QoS): validated against the
        pinned class vocabulary at the WRITE — a typo'd class must fail
        the operator's POST, not silently lose its default at the
        scheduler's enforcement point."""
        from ..idl.messages import PRIORITY_CLASSES
        body = await request.json()
        if not body.get("name"):
            return web.json_response({"error": "name required"},
                                     status=400)
        cls = body.get("qos_class", "")
        if cls and cls not in PRIORITY_CLASSES:
            return web.json_response(
                {"error": f"unknown qos_class {cls!r} "
                          f"(known: {list(PRIORITY_CLASSES)})"},
                status=400)
        tenant_id = await asyncio.to_thread(
            lambda: self.store.upsert_tenant(
                body["name"], qos_class=cls,
                max_running=int(body.get("max_running", 0) or 0),
                shed_retry_after_ms=int(
                    body.get("shed_retry_after_ms", 0) or 0)))
        return web.json_response({"id": tenant_id}, status=201)

    async def _create_job(self, request: web.Request) -> web.Response:
        body = await request.json()
        args = body.get("args", {})
        if body.get("type") == "preheat":
            if not args.get("url"):
                return web.json_response(
                    {"error": "preheat requires args.url"}, status=400)
            if args.get("type", "file") not in ("file", "image"):
                return web.json_response(
                    {"error": "preheat args.type must be file|image"},
                    status=400)
            meta = UrlMeta(**args.get("url_meta", {})) \
                if args.get("url_meta") else None
            job_id = await self.jobs.submit_preheat(
                url=args["url"], url_meta=meta,
                cluster_id=args.get("cluster_id"),
                type_=args.get("type", "file"),
                platform=args.get("platform", ""))
        elif body.get("type") == "sync_peers":
            job_id = await self.jobs.submit_sync_peers(
                cluster_id=args.get("cluster_id"))
        else:
            return web.json_response({"error": "unknown job type"},
                                     status=400)
        return web.json_response({"id": job_id}, status=201)

    async def _list_jobs(self, _r: web.Request) -> web.Response:
        return web.json_response(await asyncio.to_thread(self.store.jobs))

    async def _list_models(self, request: web.Request) -> web.Response:
        name = request.query.get("name")
        return web.json_response(
            await asyncio.to_thread(lambda: self.store.models(name=name)))

    async def _get_job(self, request: web.Request) -> web.Response:
        job = await asyncio.to_thread(
            self.store.job, int(request.match_info["id"]))
        if job is None:
            return web.json_response({"error": "not found"}, status=404)
        job["args"] = json.loads(job["args"])
        job["result"] = json.loads(job["result"])
        return web.json_response(job)

    # -- users + tokens (reference manager/handlers/user.go, pat.go) ----

    async def _signin(self, request: web.Request) -> web.Response:
        if self.auth is None:
            return web.json_response({"error": "auth disabled"}, status=404)
        body = await request.json()
        user = await asyncio.to_thread(
            self.store.verify_user, body.get("name", ""),
            body.get("password", ""))
        if user is None:
            return web.json_response({"error": "bad credentials"}, status=401)
        return web.json_response({"token": self.auth.mint_session(user),
                                  "role": user["role"]})

    async def _create_user(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            uid = await asyncio.to_thread(
                lambda: self.store.create_user(
                    body["name"], body["password"],
                    role=body.get("role", "guest")))
        except Exception as exc:  # noqa: BLE001 - dup name / bad role
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"id": uid}, status=201)

    async def _create_pat(self, request: web.Request) -> web.Response:
        body = await request.json()
        user = request.get("user") or {"id": body.get("user_id", 0)}
        token = await asyncio.to_thread(
            lambda: self.store.create_pat(
                user["id"], label=body.get("label", ""),
                ttl_s=float(body.get("ttl_s", 0))))
        return web.json_response({"token": token}, status=201)

    async def _list_pats(self, request: web.Request) -> web.Response:
        user = request.get("user")
        uid = user["id"] if user and user["role"] != "root" else None
        return web.json_response(
            await asyncio.to_thread(lambda: self.store.pats(uid)))

    async def _revoke_pat(self, request: web.Request) -> web.Response:
        await asyncio.to_thread(self.store.revoke_pat,
                                int(request.match_info["id"]))
        return web.json_response({"ok": True})

    async def _list_sp_clusters(self, _r: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.store.seed_peer_clusters))

    async def _create_sp_cluster(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            cid = await asyncio.to_thread(self.store.create_seed_peer_cluster,
                                          body["name"])
        except KeyError:
            return web.json_response({"error": "missing field 'name'"},
                                     status=400)
        except Exception as exc:  # noqa: BLE001 - e.g. duplicate name
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"id": cid}, status=201)

    async def _update_sched_cluster(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except Exception as exc:  # noqa: BLE001 - malformed input is a 400
            return web.json_response({"error": str(exc)}, status=400)
        cid = int(request.match_info["id"])
        cfg = None
        if body.get("config") is not None:
            if not isinstance(body["config"], dict):
                return web.json_response({"error": "config must be an object"},
                                         status=400)
            # PARTIAL update: merge over the stored config — rebuilding from
            # dataclass defaults would silently reset every omitted tunable
            current = await asyncio.to_thread(self.store.cluster_config, cid)
            # validate each VALUE against the stored field's type (replace
            # only checks key names): a wrong-typed value would persist
            # fine here and blow up later inside every scheduler's
            # dynconfig refresh
            coerced = {}
            for k, v in body["config"].items():
                if not hasattr(current, k):
                    return web.json_response(
                        {"error": f"unknown config key {k!r}"}, status=400)
                target = type(getattr(current, k))
                bad = web.json_response(
                    {"error": f"{k} must be {target.__name__}"}, status=400)
                if target is bool:
                    if not isinstance(v, bool):
                        return bad
                    coerced[k] = v
                elif target is int:
                    # bool is an int subclass and float coercion would
                    # silently truncate — both are type errors here; a
                    # NUMERIC string coerces (what clients actually send)
                    if isinstance(v, (bool, float)):
                        return bad
                    try:
                        coerced[k] = int(v)
                    except (TypeError, ValueError):
                        return bad
                elif target is float:
                    if isinstance(v, bool):
                        return bad
                    try:
                        coerced[k] = float(v)
                    except (TypeError, ValueError):
                        return bad
                elif isinstance(v, target):
                    coerced[k] = v
                else:
                    return bad
            try:
                cfg = dataclasses.replace(current, **coerced)
            except TypeError as exc:
                return web.json_response({"error": str(exc)}, status=400)
        if cfg is None and body.get("scopes") is None:
            return web.json_response({"error": "nothing to update"},
                                     status=400)
        ok = await asyncio.to_thread(
            lambda: self.store.update_scheduler_cluster(
                cid, config=cfg, scopes=body.get("scopes")))
        return web.json_response({"ok": ok}, status=200 if ok else 404)

    async def _list_users(self, request: web.Request) -> web.Response:
        user = request.get("user")
        if user is not None and user.get("role") != "root":
            # usernames include oauth identities; only operators list them
            return web.json_response({"error": "forbidden"}, status=403)
        return web.json_response(await asyncio.to_thread(self.store.users))

    # -- oauth (reference manager/handlers/oauth.go) --------------------

    async def _list_oauth(self, _r: web.Request) -> web.Response:
        return web.json_response(await asyncio.to_thread(self.store.oauths))

    async def _create_oauth(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            oid = await asyncio.to_thread(
                lambda: self.store.create_oauth(
                    body["name"], client_id=body["client_id"],
                    client_secret=body["client_secret"],
                    auth_url=body["auth_url"], token_url=body["token_url"],
                    userinfo_url=body["userinfo_url"],
                    scopes=body.get("scopes", "")))
        except KeyError as exc:
            return web.json_response({"error": f"missing field {exc}"},
                                     status=400)
        except Exception as exc:  # noqa: BLE001 - e.g. duplicate name
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"id": oid}, status=201)

    async def _delete_oauth(self, request: web.Request) -> web.Response:
        ok = await asyncio.to_thread(self.store.delete_oauth,
                                     int(request.match_info["id"]))
        return web.json_response({"ok": ok},
                                 status=200 if ok else 404)

    async def _oauth_signin(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        redirect_uri = request.query.get(
            "redirect_uri",
            f"http://{request.host}/oauth/callback/{name}")
        try:
            url = await self._oauth_flow.signin_url(name, redirect_uri)
        except DFError as exc:
            # state-table cap under a mint flood: answer 429, don't 500
            return web.json_response({"error": exc.message}, status=429)
        if url is None:
            return web.json_response({"error": "unknown provider"},
                                     status=404)
        raise web.HTTPFound(url)

    async def _oauth_callback(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        code = request.query.get("code", "")
        state = request.query.get("state", "")
        redirect_uri = request.query.get(
            "redirect_uri",
            f"http://{request.host}/oauth/callback/{name}")
        result = await self._oauth_flow.callback(name, code, state,
                                                 redirect_uri)
        if result is None:
            return web.json_response({"error": "oauth signin rejected"},
                                     status=401)
        return web.json_response(result)
