"""Manager gRPC service.

Role parity: reference ``manager/rpcserver/`` — GetSchedulers (with
searcher-driven cluster pick + cluster config), GetSeedPeers, the KeepAlive
client-stream liveness protocol (``manager_server_v2.go:737``), and the
self-registration RPCs schedulers/seed peers call on boot.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import AsyncIterator

from ..common.errors import Code, DFError
from ..idl.messages import (CertificateRequest, CertificateResponse,
                            CreateModelRequest, Empty, GetModelRequest,
                            GetModelResponse, GetSchedulersRequest,
                            GetSchedulersResponse, GetSchedulerStateRequest,
                            GetSchedulerStateResponse, GetSeedPeersRequest,
                            GetSeedPeersResponse, KeepAliveRequest,
                            ModelEntity, RegisterSchedulerRequest,
                            RegisterSeedPeerRequest,
                            SetSchedulerStateRequest)
from ..rpc.server import ServiceDef
from .searcher import find_scheduler_cluster
from .store import Store

log = logging.getLogger("df.mgr.service")

MANAGER_SERVICE = "df.manager.Manager"


MAX_CERT_VALIDITY_S = 30 * 24 * 3600     # caller may ask for less, not more


class ManagerService:
    def __init__(self, store: Store, *, issuer=None,
                 issue_token: str = ""):
        """``issuer``: a ``common.certs.CertIssuer`` enabling fleet cert
        issuance (IssueCertificate); None disables the RPC.
        ``issue_token``: shared secret gating issuance — without a gate,
        anyone reaching the gRPC port could get fleet-CA-signed certs and
        the mTLS layer would authenticate nothing."""
        self.store = store
        self.issuer = issuer
        self.issue_token = issue_token

    async def get_schedulers(self, req: GetSchedulersRequest,
                             context) -> GetSchedulersResponse:
        clusters = await asyncio.to_thread(self.store.scheduler_clusters)
        cluster_id = find_scheduler_cluster(clusters, req)
        if cluster_id is None:
            raise DFError(Code.NOT_FOUND, "no scheduler clusters")
        schedulers = await asyncio.to_thread(
            lambda: self.store.schedulers(cluster_id=cluster_id,
                                          only_active=True))
        return GetSchedulersResponse(
            schedulers=schedulers,
            cluster_config=await asyncio.to_thread(
                self.store.cluster_config, cluster_id))

    async def get_seed_peers(self, req: GetSeedPeersRequest,
                             context) -> GetSeedPeersResponse:
        peers = await asyncio.to_thread(
            lambda: self.store.seed_peers(
                cluster_id=req.cluster_id or None, only_active=True))
        return GetSeedPeersResponse(seed_peers=peers)

    async def list_applications(self, req, context):
        """Applications + priorities for scheduler dynconfig (reference
        manager/rpcserver ListApplications consumed by
        ``Peer.CalculatePriority``). Priority persists as a JSON map
        (``{"value": N}``, reference JSONMap shape)."""
        from ..idl.messages import (ApplicationEntry,
                                    ListApplicationsResponse, Priority)
        rows = await asyncio.to_thread(self.store.applications)
        out = []
        for r in rows:
            # one malformed row must not fail the whole table: parse and
            # clamp per entry, default LEVEL0
            try:
                prio = int(json.loads(r.get("priority") or "{}")
                           .get("value", 0))
            except (ValueError, TypeError, AttributeError):
                prio = 0
            prio = min(max(prio, int(Priority.LEVEL0)), int(Priority.LEVEL6))
            out.append(ApplicationEntry(
                name=r["name"], url=r.get("url", "") or "",
                priority=Priority(prio)))
        return ListApplicationsResponse(applications=out)

    async def list_tenants(self, req, context):
        """Tenant quota table for scheduler dynconfig (the QoS analog of
        ListApplications): per-tenant default class + max_running quota,
        refreshed on the same cadence so quota edits reach every
        scheduler without a restart. Classes are clamped onto the pinned
        vocabulary here — a typo'd row must degrade to 'no default
        class', never to an unknown label at the enforcement point."""
        from ..idl.messages import (ListTenantsResponse, PRIORITY_CLASSES,
                                    TenantEntry)
        rows = await asyncio.to_thread(self.store.tenants)
        out = []
        for r in rows:
            cls = r.get("qos_class") or ""
            if cls not in PRIORITY_CLASSES:
                cls = ""
            out.append(TenantEntry(
                name=r["name"], qos_class=cls,
                max_running=int(r.get("max_running") or 0),
                shed_retry_after_ms=int(r.get("shed_retry_after_ms")
                                        or 0)))
        return ListTenantsResponse(tenants=out)

    async def register_scheduler(self, req: RegisterSchedulerRequest,
                                 context) -> Empty:
        cluster_id = req.scheduler_cluster_id or \
            await asyncio.to_thread(self.store.default_scheduler_cluster)
        await asyncio.to_thread(
            lambda: self.store.upsert_scheduler(
                hostname=req.hostname, ip=req.ip, port=req.port,
                cluster_id=cluster_id, topology=req.topology))
        return Empty()

    async def register_seed_peer(self, req: RegisterSeedPeerRequest,
                                 context) -> Empty:
        cluster_id = req.seed_peer_cluster_id or 1
        await asyncio.to_thread(
            lambda: self.store.upsert_seed_peer(
                hostname=req.hostname, ip=req.ip, port=req.port,
                download_port=req.download_port,
                object_storage_port=req.object_storage_port,
                type_=req.type or "super", cluster_id=cluster_id,
                topology=req.topology))
        return Empty()

    # -- scheduler handoff relay (control-plane failover) --------------

    async def set_scheduler_state(self, req: SetSchedulerStateRequest,
                                  context) -> Empty:
        """Park a demoting scheduler's state summary. The manager is a
        dumb relay: the blob is opaque (sealed by the exporter) and the
        HMAC signature is verified by the IMPORTER against the shared
        issuance token — a compromised relay can drop a handoff (safe:
        successor falls back to its own snapshot + live rebuild) but
        cannot forge one that verifies."""
        if not req.scheduler_id or not req.blob:
            raise DFError(Code.INVALID_ARGUMENT,
                          "scheduler_id and blob required")
        await asyncio.to_thread(
            lambda: self.store.park_scheduler_state(
                cluster_id=req.cluster_id, scheduler_id=req.scheduler_id,
                blob=req.blob, signature=req.signature))
        return Empty()

    async def get_scheduler_state(self, req: GetSchedulerStateRequest,
                                  context) -> GetSchedulerStateResponse:
        row = await asyncio.to_thread(
            lambda: self.store.latest_scheduler_state(
                cluster_id=req.cluster_id, exclude=req.exclude))
        if row is None:
            return GetSchedulerStateResponse()
        return GetSchedulerStateResponse(
            scheduler_id=row["scheduler_id"], blob=row["blob"],
            signature=row["signature"])

    # -- model registry (reference manager/models/model.go:36) ---------

    async def create_model(self, req: CreateModelRequest, context) -> Empty:
        if not req.name or not req.version or not req.data:
            raise DFError(Code.INVALID_ARGUMENT,
                          "name, version, data required")
        await asyncio.to_thread(
            lambda: self.store.create_model(
                name=req.name, version=req.version, data=req.data,
                metrics=req.metrics,
                scheduler_cluster_id=req.scheduler_cluster_id))
        log.info("model registered: %s@%s (%d bytes)", req.name, req.version,
                 len(req.data))
        return Empty()

    async def get_model(self, req: GetModelRequest,
                        context) -> GetModelResponse:
        row = await asyncio.to_thread(
            lambda: self.store.get_model(
                req.name, version=req.version,
                scheduler_cluster_id=req.scheduler_cluster_id))
        if row is None:
            return GetModelResponse(model=None)
        unchanged = bool(req.if_none_match
                         and row["version"] == req.if_none_match)
        return GetModelResponse(model=ModelEntity(
            id=row["id"], name=row["name"], version=row["version"],
            state=row["state"],
            scheduler_cluster_id=row["scheduler_cluster_id"],
            metrics=row["metrics"],
            data=b"" if unchanged else row["data"],
            created_at=row["created_at"]))

    # -- fleet cert issuance (reference security_server_v1.go) ----------

    async def issue_certificate(self, req: CertificateRequest,
                                context) -> CertificateResponse:
        if self.issuer is None:
            raise DFError(Code.SCHED_FORBIDDEN,
                          "certificate issuance not enabled")
        import hmac as _hmac

        if not self.issue_token or not _hmac.compare_digest(
                req.token or "", self.issue_token):
            raise DFError(Code.SCHED_FORBIDDEN, "bad issuance token")
        if not req.public_key_pem or not req.hosts:
            raise DFError(Code.INVALID_ARGUMENT,
                          "public_key_pem and hosts required")
        import datetime

        from ..common import cryptoshim
        # no-op when the real wheel is importable; first call may probe
        # for an openssl binary, so keep it off the loop thread
        await asyncio.to_thread(cryptoshim.install)
        from cryptography.hazmat.primitives import serialization

        def sign() -> bytes:
            pub = serialization.load_pem_public_key(req.public_key_pem)
            want = req.validity_s if req.validity_s > 0 else 24 * 3600
            ttl = datetime.timedelta(
                seconds=min(want, MAX_CERT_VALIDITY_S))
            return self.issuer.sign_public_key(pub, list(req.hosts), ttl=ttl)

        cert_pem = await asyncio.to_thread(sign)
        return CertificateResponse(cert_pem=cert_pem,
                                   ca_cert_pem=self.issuer._ca_pem())

    async def keep_alive(self, request_iter, context) -> Empty:
        """Client-stream: one message per interval; instance goes inactive
        when the stream dies and the TTL sweep catches it."""
        ident = None
        async for req in request_iter:
            ident = (req.source_type, req.hostname, req.ip)
            ok = await asyncio.to_thread(
                self.store.keepalive, req.source_type, req.hostname, req.ip,
                req.port)
            if not ok:
                log.warning("keepalive from unregistered %s %s@%s",
                            req.source_type, req.hostname, req.ip)
        if ident:
            log.info("keepalive stream ended: %s %s@%s", *ident)
        return Empty()


def build_service(svc: ManagerService) -> ServiceDef:
    d = ServiceDef(MANAGER_SERVICE)
    d.unary_unary("GetSchedulers", svc.get_schedulers)
    d.unary_unary("GetSeedPeers", svc.get_seed_peers)
    d.unary_unary("ListApplications", svc.list_applications)
    d.unary_unary("ListTenants", svc.list_tenants)
    d.unary_unary("RegisterScheduler", svc.register_scheduler)
    d.unary_unary("RegisterSeedPeer", svc.register_seed_peer)
    d.stream_unary("KeepAlive", svc.keep_alive)
    d.unary_unary("SetSchedulerState", svc.set_scheduler_state)
    d.unary_unary("GetSchedulerState", svc.get_scheduler_state)
    d.unary_unary("CreateModel", svc.create_model)
    d.unary_unary("GetModel", svc.get_model)
    d.unary_unary("IssueCertificate", svc.issue_certificate)
    return d
