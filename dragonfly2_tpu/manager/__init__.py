"""Manager: the global control plane of record.

Role parity: reference ``manager/`` (SURVEY §2.5) — clusters, scheduler and
seed-peer instances, applications, keepalive liveness, cluster-config
(dynconfig) serving, the searcher that assigns peers to scheduler clusters,
and preheat jobs. GORM/MySQL/Redis/machinery collapse to sqlite + in-proc
queues + direct gRPC fan-out: one store, no side infrastructure.
"""

from .server import Manager, ManagerConfig  # noqa: F401
