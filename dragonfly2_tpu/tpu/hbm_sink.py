"""The HBM sink: verified pieces land in device memory, overlapped with the
download.

This is the TPU-native replacement for the GPUDirect/pinned-CUDA-memory role
in GPU-side distribution stacks (see BASELINE.json north star). Design:

- Pieces are written into a preallocated host ``numpy`` buffer (the pinned
  staging area) at their content offsets, zero extra copies in Python
  (memoryview slicing).
- The content is split into ``shard_count`` contiguous byte shards. The
  moment every byte of a shard is present, that shard's index is enqueued to
  a dedicated transfer thread that owns every ``jax.device_put`` call.
  ``write()`` never waits on a device transfer — on real TPU hardware
  ``device_put`` of an unpinned host buffer is synchronous (it blocks the
  caller for the whole staging copy + DMA), so dispatching it from the
  asyncio event loop or awaiting it from the piece-landing path stalls the
  daemon's own sockets. The worker thread absorbs that blocking; the landing
  path only memcpys.
- ``result()`` drains the transfer queue, blocks until the DMAs finish, and
  assembles per-device shards into ONE logically-global jax.Array via
  ``jax.make_array_from_single_device_arrays`` when a mesh sharding is
  given, so downstream JAX code sees a normal sharded array on the mesh.

Single-host by design: each daemon feeds its own host's devices; cross-host
distribution is the P2P fabric's job, not XLA's.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable

import numpy as np

from ..common import faultgate
from ..common.metrics import REGISTRY

log = logging.getLogger("df.storage.hbm")

# sink telemetry in the process registry (scraped at /metrics) instead of
# instance-private fields only a result() caller could read: the DMA
# overlap picture must survive the task and be visible to an operator
# mid-download
_hbm_transfer_s = REGISTRY.histogram(
    "df_hbm_transfer_seconds", "device shard DMA duration",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 10.0))
_hbm_transfers = REGISTRY.counter(
    "df_hbm_transfers_total", "device shard transfers", ("result",))
_hbm_bytes = REGISTRY.counter(
    "df_hbm_staged_bytes_total", "bytes staged into the host buffer")
_hbm_queue = REGISTRY.gauge(
    "df_hbm_transfer_queue_depth", "shard transfers enqueued, not yet done")
_hbm_done = REGISTRY.gauge(
    "df_hbm_done_fraction", "coverage fraction of the most recent sink")


class CoverageMap:
    """Tracks which byte ranges are present; answers 'is [a,b) complete?'.

    Piece arrivals are arbitrary-order; ranges are merged as they land.
    """

    def __init__(self) -> None:
        self._ranges: list[tuple[int, int]] = []  # merged, sorted [start,end)
        self._lock = threading.Lock()

    def add(self, start: int, end: int) -> None:
        with self._lock:
            ranges = self._ranges
            lo, hi = start, end
            out = []
            inserted = False
            for s, e in ranges:
                if e < lo or s > hi:   # disjoint
                    if s > hi and not inserted:
                        out.append((lo, hi))
                        inserted = True
                    out.append((s, e))
                else:                   # overlap/adjacent: merge
                    lo, hi = min(lo, s), max(hi, e)
            if not inserted:
                out.append((lo, hi))
            out.sort()
            self._ranges = out

    def covers(self, start: int, end: int) -> bool:
        if start >= end:
            return True
        with self._lock:
            for s, e in self._ranges:
                if s <= start and end <= e:
                    return True
        return False

    def covered_bytes(self) -> int:
        with self._lock:
            return sum(e - s for s, e in self._ranges)


class DeviceIngest:
    """Streams a task's bytes into per-device shards as pieces arrive.

    All device transfers run on one dedicated worker thread so neither the
    asyncio event loop nor the piece-landing path ever blocks on DMA
    (the round-3 TPU failure mode: ``device_put`` on-loop starved the
    daemon's sockets mid-download).
    """

    def __init__(self, content_length: int, *, devices: Any = None,
                 sharding: Any = None, dtype: str = "uint8",
                 shards_per_device: int = 1,
                 shard_specs: list | None = None,
                 on_shard_ready: Callable[[str, float], None] | None = None,
                 device_put_fn: Callable[[Any, Any], Any] | None = None):
        """``devices``: explicit device list (contiguous shards per device),
        or ``sharding``: a 1-D jax NamedSharding to assemble a global array
        on. ``shards_per_device`` > 1 pipelines the host->HBM DMA: each
        device's range is cut into that many transfer units so streaming can
        overlap even on a single chip (a 1-device host would otherwise hold
        its one transfer until the last byte arrived) and so no single
        ``device_put`` blocks the worker for the whole file. Only 1 is
        supported with ``sharding`` (global-array assembly needs one array
        per device). ``device_put_fn`` is injectable for tests (defaults to
        ``jax.device_put``).

        ``shard_specs`` switches the sink to MANIFEST mode (sharded tasks,
        common/sharding.py): instead of equal-split anonymous shards, each
        entry is ``(name, start, size[, dtype, shape])`` — a named byte
        range that transfers the moment its bytes are covered (ranges may
        be uneven, need not cover the content, and gaps never transfer).
        ``result()`` then returns ``{name: array}``, each array viewed as
        the spec's dtype (the sink default when "") and reshaped to the
        spec's shape when one is given. Devices are assigned round-robin
        per spec. Incompatible with ``sharding`` (global-array assembly
        needs the equal-split geometry). ``on_shard_ready`` is called ON
        THE TRANSFER THREAD as ``(name, monotonic_done_time)`` after each
        named shard's device transfer completes — callbacks must be cheap
        and thread-safe (hand off to the loop, don't compute)."""
        import jax

        if content_length <= 0:
            raise ValueError("content_length must be known for device ingest")
        self.content_length = content_length
        self.dtype = np.dtype(dtype)
        self._sharding = sharding
        if sharding is not None:
            if shards_per_device != 1:
                raise ValueError("shards_per_device must be 1 with sharding")
            if shard_specs is not None:
                raise ValueError("shard_specs incompatible with sharding")
            devices = list(sharding.mesh.devices.flat)
        elif devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.shards_per_device = max(1, shards_per_device)
        self.on_shard_ready = on_shard_ready
        self._specs: list[tuple] | None = None
        if shard_specs is not None:
            if not shard_specs:
                raise ValueError("shard_specs must be non-empty")
            specs = []
            for sp in shard_specs:
                name, start, size = sp[0], int(sp[1]), int(sp[2])
                sdtype = np.dtype(sp[3]) if len(sp) > 3 and sp[3] \
                    else self.dtype
                shape = tuple(sp[4]) if len(sp) > 4 and sp[4] else None
                if size <= 0 or start < 0 or start + size > content_length:
                    raise ValueError(f"shard {name}: bad range "
                                     f"[{start}, {start + size})")
                if size % sdtype.itemsize:
                    raise ValueError(f"shard {name}: size {size} not a "
                                     f"multiple of {sdtype} itemsize")
                specs.append((name, start, size, sdtype, shape))
            self._specs = specs
            n = len(specs)
            self.n_shards = n
            self.padded_length = content_length
            self.shard_bytes = 0            # uneven; see _shard_range
            # overlap scan order: (start, end, index) sorted by start
            self._spec_order = sorted(
                (sp[1], sp[1] + sp[2], i) for i, sp in enumerate(specs))
            self.host = np.zeros(content_length, dtype=np.uint8)
        else:
            n = len(self.devices) * self.shards_per_device
            self.n_shards = n
            # equal shards padded to dtype & shard-count alignment
            itemsize = self.dtype.itemsize
            padded = -(-content_length // (n * itemsize)) * (n * itemsize)
            self.padded_length = padded
            self.shard_bytes = padded // n
            self.host = np.zeros(padded, dtype=np.uint8)
        self._coverage = CoverageMap()
        self._shard_arrays: list[Any | None] = [None] * n
        self._shard_sent = [False] * n       # transfer COMPLETED
        self._shard_queued = [False] * n     # enqueued to the worker
        # (monotonic start, end) of each completed device transfer — lets
        # callers measure how much DMA ran concurrently with the download
        # without run-to-run wall-clock subtraction (bench + tracing)
        self.transfer_spans: list[tuple[float, float]] = []
        self._lock = threading.Lock()
        self._device_put = device_put_fn or jax.device_put
        self._queue: queue.SimpleQueue = queue.SimpleQueue()
        self._pending = 0                    # queued-but-unfinished transfers
        self._idle = threading.Event()
        self._idle.set()
        self._error: BaseException | None = None
        self._closed = False
        self._worker = threading.Thread(target=self._transfer_loop,
                                        name="hbm-sink", daemon=True)
        self._worker.start()
        if content_length < self.padded_length:  # pad tail trivially "present"
            self._coverage.add(content_length, self.padded_length)

    # ------------------------------------------------------------------
    # producer side (piece-landing path) — never blocks on DMA
    # ------------------------------------------------------------------

    def write(self, offset: int, data: bytes | memoryview) -> None:
        """Land one verified piece; enqueues device transfers for any shard
        the piece completes. Returns as soon as the memcpy is done.

        Buffer lifetime rule (the piece-buffer pool depends on it): this
        method NEVER retains a reference to ``data`` past its return. The
        numpy assignment below copies into the sink's own host buffer and
        the transient ``frombuffer`` view dies with the statement — so the
        landing path may recycle the piece buffer (bufpool.POOL.release)
        the moment its landing call stack unwinds. Device transfers read
        ONLY ``self.host``, never the caller's buffer."""
        if faultgate.ARMED:
            # a raising script here exercises the conductor's sink-failure
            # path: ingest disabled, download continues to disk
            faultgate.fire_sync("hbm.ingest")
        end = offset + len(data)
        if end > self.content_length:
            raise ValueError(f"write beyond content: {end} > {self.content_length}")
        self.host[offset:end] = np.frombuffer(data, dtype=np.uint8)
        self._coverage.add(offset, end)
        _hbm_bytes.inc(len(data))
        _hbm_done.set(self.done_fraction())
        if self._specs is not None:
            # manifest mode: enqueue every named range this span touches
            # (a piece straddling a shard boundary can complete two)
            for s, e, idx in self._spec_order:
                if e <= offset:
                    continue
                if s >= end:
                    break
                self._maybe_enqueue(idx)
            return
        first = offset // self.shard_bytes
        last = (end - 1) // self.shard_bytes
        for shard in range(first, min(last + 1, self.n_shards)):
            self._maybe_enqueue(shard)

    def _shard_range(self, shard: int) -> tuple[int, int]:
        if self._specs is not None:
            _name, s, size, _dt, _shape = self._specs[shard]
            return s, s + size
        return shard * self.shard_bytes, (shard + 1) * self.shard_bytes

    def _maybe_enqueue(self, shard: int) -> None:
        s, e = self._shard_range(shard)
        with self._lock:
            if self._shard_queued[shard] or self._closed:
                return
            if not self._coverage.covers(s, min(e, self.content_length)):
                return
            self._shard_queued[shard] = True
            self._pending += 1
            # delta, not set(): several sinks share the process gauge and
            # one instance's private _pending must not clobber the others'
            _hbm_queue.inc()
            self._idle.clear()
            # put stays under the lock (SimpleQueue.put never blocks): outside
            # it, a concurrent close() could slip its sentinel in first and
            # the worker would exit with this shard queued behind it, leaving
            # _pending stuck > 0 and drain() hung
            self._queue.put(shard)

    def flush(self) -> None:
        """Enqueue any fully-covered shard whose transfer hasn't fired — in
        practice the padding-only tail shards that no write ever touches.
        Non-blocking; shards with missing content bytes are left unsent
        (result() will name them)."""
        for shard in range(self.n_shards):
            self._maybe_enqueue(shard)

    # ------------------------------------------------------------------
    # worker thread — owns every device_put
    # ------------------------------------------------------------------

    def _transfer_loop(self) -> None:
        while True:
            shard = self._queue.get()
            if shard is None:            # shutdown sentinel
                return
            try:
                s, e = self._shard_range(shard)
                if self._specs is not None:
                    name, _s, _size, sdtype, shape = self._specs[shard]
                    view = self.host[s:e].view(sdtype)
                    if shape is not None:
                        view = view.reshape(shape)
                    device = self.devices[shard % len(self.devices)]
                else:
                    name = None
                    view = self.host[s:e].view(self.dtype)
                    device = self.devices[shard // self.shards_per_device]
                t0 = time.monotonic()
                arr = self._device_put(view, device)
                # span must end at transfer COMPLETION, not dispatch — on
                # backends where device_put returns before the DMA lands,
                # a dispatch-end span would report overlap that never ran
                wait = getattr(arr, "block_until_ready", None)
                if wait is not None:
                    wait()
                t1 = time.monotonic()
                with self._lock:
                    self._shard_arrays[shard] = arr
                    self._shard_sent[shard] = True
                    self.transfer_spans.append((t0, t1))
                _hbm_transfer_s.observe(t1 - t0)
                _hbm_transfers.labels("ok").inc()
                if name is not None and self.on_shard_ready is not None:
                    try:
                        self.on_shard_ready(name, t1)
                    except Exception:  # noqa: BLE001 - observer only
                        log.exception("on_shard_ready(%s) raised", name)
                log.debug("shard %d/%d -> %s", shard, self.n_shards, device)
            except BaseException as exc:  # noqa: BLE001 - surfaced by result()
                with self._lock:
                    if self._error is None:
                        self._error = exc
                _hbm_transfers.labels("fail").inc()
                log.exception("device transfer of shard %d failed", shard)
            finally:
                with self._lock:
                    self._pending -= 1
                    _hbm_queue.dec()
                    if self._pending == 0:
                        self._idle.set()
                    # self-terminate once every shard has shipped: a consumer
                    # that never calls result()/close() (task finished, nobody
                    # collected) must not leak this thread + the file-sized
                    # host buffer it pins for the daemon's lifetime
                    if all(self._shard_sent):
                        self._closed = True
                        return

    # ------------------------------------------------------------------
    # consumer side
    # ------------------------------------------------------------------

    def done_fraction(self) -> float:
        return self._coverage.covered_bytes() / self.padded_length

    def drain(self, timeout: float | None = None) -> None:
        """Block (the CALLING thread — run via to_thread from async code)
        until every enqueued transfer has completed. Raises the first
        transfer error, if any."""
        if not self._idle.wait(timeout):
            raise TimeoutError("device transfers still in flight")
        with self._lock:
            if self._error is not None:
                raise RuntimeError("device transfer failed") from self._error

    def close(self) -> None:
        """Stop the worker thread. Idempotent; safe mid-stream (pending
        transfers finish first — the sentinel queues behind them)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)

    def result(self, timeout: float | None = None):
        """Flush + drain, then return the device-resident data.

        Blocking — call via ``asyncio.to_thread`` from the event loop. With
        a sharding: one global jax.Array of shape (padded_length //
        itemsize,) sharded over the mesh axis. With ``shard_specs``: a
        ``{name: array}`` dict in manifest order. Without either: list of
        per-device arrays.
        """
        import jax

        try:
            self.flush()
            self.drain(timeout)
            with self._lock:
                sent = list(self._shard_sent)
                arrays = list(self._shard_arrays)
            if not all(sent):
                missing = [self._specs[i][0] if self._specs is not None
                           else i for i, s in enumerate(sent) if not s]
                raise RuntimeError(f"shards incomplete: {missing}")
        finally:
            # stop the worker on EVERY exit — a raising result() must not
            # leave the thread parked on queue.get holding the host buffer
            self.close()
        for a in arrays:
            a.block_until_ready()
        if self._specs is not None:
            return {sp[0]: arrays[i] for i, sp in enumerate(self._specs)}
        if self._sharding is None:
            return arrays
        global_shape = (self.padded_length // self.dtype.itemsize,)
        return jax.make_array_from_single_device_arrays(
            global_shape, self._sharding, arrays)
