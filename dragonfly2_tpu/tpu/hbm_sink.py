"""The HBM sink: verified pieces land in device memory, overlapped with the
download.

This is the TPU-native replacement for the GPUDirect/pinned-CUDA-memory role
in GPU-side distribution stacks (see BASELINE.json north star). Design:

- Pieces are written into a preallocated host ``numpy`` buffer (the pinned
  staging area) at their content offsets, zero extra copies in Python
  (memoryview slicing).
- The content is split into ``shard_count`` contiguous byte shards. The
  moment every byte of a shard is present, that shard is handed to
  ``jax.device_put`` — transfers overlap the rest of the download instead of
  waiting for completion (piece-verify ∥ device-DMA, the overlap SURVEY §7
  flags as the hard part).
- ``result()`` assembles per-device shards into ONE logically-global jax.Array
  via ``jax.make_array_from_single_device_arrays`` when a mesh sharding is
  given, so downstream JAX code sees a normal sharded array on the mesh.

Single-host by design: each daemon feeds its own host's devices; cross-host
distribution is the P2P fabric's job, not XLA's.
"""

from __future__ import annotations

import logging
import threading
from typing import Any

import numpy as np

log = logging.getLogger("df.storage.hbm")


class CoverageMap:
    """Tracks which byte ranges are present; answers 'is [a,b) complete?'.

    Piece arrivals are arbitrary-order; ranges are merged as they land.
    """

    def __init__(self) -> None:
        self._ranges: list[tuple[int, int]] = []  # merged, sorted [start,end)
        self._lock = threading.Lock()

    def add(self, start: int, end: int) -> None:
        with self._lock:
            ranges = self._ranges
            lo, hi = start, end
            out = []
            inserted = False
            for s, e in ranges:
                if e < lo or s > hi:   # disjoint
                    if s > hi and not inserted:
                        out.append((lo, hi))
                        inserted = True
                    out.append((s, e))
                else:                   # overlap/adjacent: merge
                    lo, hi = min(lo, s), max(hi, e)
            if not inserted:
                out.append((lo, hi))
            out.sort()
            self._ranges = out

    def covers(self, start: int, end: int) -> bool:
        if start >= end:
            return True
        with self._lock:
            for s, e in self._ranges:
                if s <= start and end <= e:
                    return True
        return False

    def covered_bytes(self) -> int:
        with self._lock:
            return sum(e - s for s, e in self._ranges)


class DeviceIngest:
    """Streams a task's bytes into per-device shards as pieces arrive."""

    def __init__(self, content_length: int, *, devices: Any = None,
                 sharding: Any = None, dtype: str = "uint8",
                 shards_per_device: int = 1):
        """``devices``: explicit device list (contiguous shards per device),
        or ``sharding``: a 1-D jax NamedSharding to assemble a global array
        on. ``shards_per_device`` > 1 pipelines the host->HBM DMA: each
        device's range is cut into that many transfer units so streaming can
        overlap even on a single chip (a 1-device host would otherwise hold
        its one transfer until the last byte arrived). Only 1 is supported
        with ``sharding`` (global-array assembly needs one array per
        device)."""
        import jax

        if content_length <= 0:
            raise ValueError("content_length must be known for device ingest")
        self.content_length = content_length
        self.dtype = np.dtype(dtype)
        self._sharding = sharding
        if sharding is not None:
            if shards_per_device != 1:
                raise ValueError("shards_per_device must be 1 with sharding")
            devices = list(sharding.mesh.devices.flat)
        elif devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.shards_per_device = max(1, shards_per_device)
        n = len(self.devices) * self.shards_per_device
        self.n_shards = n
        # equal shards padded to dtype & shard-count alignment
        itemsize = self.dtype.itemsize
        padded = -(-content_length // (n * itemsize)) * (n * itemsize)
        self.padded_length = padded
        self.shard_bytes = padded // n
        self.host = np.zeros(padded, dtype=np.uint8)
        self._coverage = CoverageMap()
        self._shard_arrays: list[Any | None] = [None] * n
        self._shard_sent = [False] * n
        self._lock = threading.Lock()
        if content_length < padded:  # pad tail is trivially "present"
            self._coverage.add(content_length, padded)

    def write(self, offset: int, data: bytes | memoryview) -> None:
        """Land one verified piece; fires device transfers for any shard the
        piece completes."""
        end = offset + len(data)
        if end > self.content_length:
            raise ValueError(f"write beyond content: {end} > {self.content_length}")
        self.host[offset:end] = np.frombuffer(data, dtype=np.uint8)
        self._coverage.add(offset, end)
        first = offset // self.shard_bytes
        last = (end - 1) // self.shard_bytes
        for shard in range(first, min(last + 1, self.n_shards)):
            self._maybe_send(shard)

    def _maybe_send(self, shard: int) -> None:
        import jax

        s, e = shard * self.shard_bytes, (shard + 1) * self.shard_bytes
        with self._lock:
            if self._shard_sent[shard]:
                return
            if not self._coverage.covers(s, min(e, self.content_length)):
                return
            view = self.host[s:e].view(self.dtype)
            device = self.devices[shard // self.shards_per_device]
            # async dispatch: returns immediately, DMA overlaps further pieces.
            # array assignment stays under the lock so result()'s all-sent
            # check can never observe a sent-but-None shard.
            self._shard_arrays[shard] = jax.device_put(view, device)
            self._shard_sent[shard] = True
        log.debug("shard %d/%d -> %s", shard, self.n_shards, device)

    def done_fraction(self) -> float:
        return self._coverage.covered_bytes() / self.padded_length

    def flush(self) -> None:
        """Send any fully-covered shard whose transfer hasn't fired — in
        practice the padding-only tail shards that no write ever touches.
        Shards with missing content bytes are left unsent (result() will
        name them)."""
        for shard in range(self.n_shards):
            self._maybe_send(shard)

    def result(self):
        """Block until transfers finish; return the device-resident data.

        With a sharding: one global jax.Array of shape (padded_length //
        itemsize,) sharded over the mesh axis. Without: list of per-device
        arrays.
        """
        import jax

        with self._lock:
            sent = list(self._shard_sent)
            arrays = list(self._shard_arrays)
        if not all(sent):
            missing = [i for i, s in enumerate(sent) if not s]
            raise RuntimeError(f"shards incomplete: {missing}")
        for a in arrays:
            a.block_until_ready()
        if self._sharding is None:
            return arrays
        global_shape = (self.padded_length // self.dtype.itemsize,)
        return jax.make_array_from_single_device_arrays(
            global_shape, self._sharding, arrays)
