"""TPU pod topology: where this host sits, and link classification.

This is the TPU-native replacement for the reference's IDC/location string
affinity (``scheduler/scheduling/evaluator/evaluator_base.go`` scores IDC and
location by string match). Here hosts carry real fabric coordinates: slice
name + ICI chip coords + zone, and the scheduler computes a ``LinkType``
(LOCAL > ICI > DCN > WAN) plus an ICI hop distance for parent scoring.
"""

from __future__ import annotations

import functools
import logging
import os
import socket
import time

from ..idl.messages import LinkType, TopologyInfo

log = logging.getLogger("df.tpu.topology")


def _wedge_cache_path() -> str:
    """Host-global marker keyed by the env that steers jax's platform
    choice (processes pinned differently can see different runtimes) and
    by uid (shared /dev/shm)."""
    import hashlib
    import tempfile

    key = hashlib.sha256(
        f"{os.environ.get('JAX_PLATFORMS', '')}\x00"
        f"{os.environ.get('XLA_FLAGS', '')}".encode()).hexdigest()[:16]
    base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    return os.path.join(base, f"df-accel-wedged-{os.getuid()}-{key}")


WEDGE_CACHE_TTL_S = 60.0


def probe_jax_devices(timeout_s: float | None = None
                      ) -> tuple[str, object]:
    """TIME-BOUNDED jax device probe from a daemon thread.

    jax backend init talks to the accelerator runtime (a tunnel, on some
    deployments) and can hang indefinitely when it is wedged — and a
    DISTRIBUTION daemon must come up and serve the CPU-side mesh even
    while the accelerator runtime is sick (a wedged tunnel froze every
    daemon of an r04 bench at construction for >120s). A daemon thread is
    essential: an executor's non-daemon worker would block interpreter
    exit via its atexit join.

    A TIMED-OUT probe is cached host-globally for ``WEDGE_CACHE_TTL_S``
    (``DF_TOPOLOGY_WEDGE_CACHE=0`` disables): a wedged runtime is a host
    condition, and without the cache every process of a 16-daemon fleet
    boot (or a restart storm on a sick host) serially re-pays the full
    probe timeout — 15s x N of pure wall. A successful probe deletes the
    marker, so a recovered tunnel is re-noticed within one TTL.

    Returns (status, payload):
      ("ok", (tpu_chip_count, first_tpu_device | None, device_count))
      ("error", exception)   — jax absent or backend init raised
      ("timeout", None)      — runtime never answered
    """
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("DF_TOPOLOGY_PROBE_TIMEOUT_S", "15"))
    cache_on = os.environ.get("DF_TOPOLOGY_WEDGE_CACHE", "1") != "0"
    cache = _wedge_cache_path()
    if cache_on:
        try:
            if time.time() - os.stat(cache).st_mtime < WEDGE_CACHE_TTL_S:
                log.info("accelerator runtime marked wedged by a recent "
                         "probe on this host; skipping (%s)", cache)
                return ("timeout", None)
        except OSError:
            pass
    box: list = []

    def _probe() -> None:
        try:
            import jax
            devs = [d for d in jax.local_devices() if d.platform == "tpu"]
            box.append(("ok", (len(devs), devs[0] if devs else None,
                               jax.device_count())))
        except BaseException as exc:  # noqa: BLE001 - reported to caller
            box.append(("error", exc))

    t = threading.Thread(target=_probe, name="df-topo-probe", daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    result = box[0] if box else ("timeout", None)
    global _local_probe_hung, _runtime_ok
    if result[0] == "timeout":
        # an ACTUAL thread of this process is now parked in jax init —
        # permanent poison, unlike a cache-hit (see runtime_wedged)
        _local_probe_hung = True
        if cache_on:
            try:
                with open(cache, "w"):
                    pass
            except OSError:
                pass   # cache is best-effort
    elif result[0] == "ok":
        _runtime_ok = True
        # deleting a stale wedge marker is ALWAYS right — even for a
        # process that reads with the cache disabled (the bench's
        # recovery detector must broadcast the recovery it just proved)
        try:
            os.unlink(cache)
        except OSError:
            pass
    return result


_local_probe_hung = False      # THIS process parked a thread in jax init
_runtime_ok = False            # a probe in THIS process saw jax answer
_reprobe_inflight = False      # background re-verification running


def runtime_wedged() -> bool:
    """THE CONTRACT for a wedged accelerator runtime, two strengths:

    - ``_local_probe_hung``: THIS process's probe thread is parked INSIDE
      jax backend init holding jax's init locks — any later jax call from
      any thread of this process can block forever behind it. Permanent
      for the process lifetime.
    - a FRESH host wedge marker (another process's probe timed out within
      the TTL): this process has no parked thread, but the runtime was
      recently observed dead — touching jax now would hang anew. SOFT:
      clears when the marker expires or a successful probe deletes it.
      Not consulted when ``DF_TOPOLOGY_WEDGE_CACHE=0`` (a process that
      deliberately re-probes must trust its own result, not a stale
      marker).

    Every optional jax entry point (the daemon's device-sink factory,
    bench phases) checks this instead of finding out by hanging the event
    loop."""
    if _local_probe_hung:
        return True
    if _runtime_ok:
        return False
    if os.environ.get("DF_TOPOLOGY_WEDGE_CACHE", "1") == "0":
        return False
    try:
        return (time.time() - os.stat(_wedge_cache_path()).st_mtime
                < WEDGE_CACHE_TTL_S)
    except OSError:
        return False


def ensure_runtime_alive() -> bool:
    """NON-BLOCKING safe-to-touch-jax check for event-loop entry points
    (device sink). O(1): returns True only when a probe in THIS process
    has seen the backend answer. When the verdict is unknown (this
    process booted off a cache-hit and never probed) and the host marker
    has lapsed, a full-timeout background probe is kicked off and False
    is returned — the CURRENT request degrades (disk-only), the NEXT one
    after a successful probe gets the sink. Never joins a probe thread on
    the caller's thread: a 'bounded' 2s join here would still freeze the
    daemon's entire event loop when the runtime is sick, and would
    poison healthy-but-slow (>2s init) backends."""
    global _reprobe_inflight
    if _local_probe_hung:
        return False
    if _runtime_ok:
        return True
    if runtime_wedged():
        return False
    if not _reprobe_inflight:
        import threading

        _reprobe_inflight = True

        def _reprobe() -> None:
            global _reprobe_inflight
            try:
                probe_jax_devices()
            finally:
                _reprobe_inflight = False

        threading.Thread(target=_reprobe, name="df-topo-reprobe",
                         daemon=True).start()
    return False


@functools.lru_cache(maxsize=1)
def detect() -> TopologyInfo:
    """Best-effort detection of this host's pod position.

    On TPU VMs, JAX exposes per-device mesh coordinates; worker identity comes
    from the TPU runtime env. On CPU hosts everything degrades to empty — the
    scheduler then treats the host as a plain DCN peer.
    """
    slice_name = os.environ.get("TPU_SLICE_NAME", "")
    pod = os.environ.get("DF_POD_ID", "")
    zone = os.environ.get("DF_ZONE", os.environ.get("CLOUD_ZONE", ""))
    try:
        worker = int(os.environ.get("TPU_WORKER_ID", "-1"))
    except ValueError:
        worker = -1
    coords = None
    # explicit coord injection: multi-process fake-pod harnesses (and
    # deployments where the runtime doesn't expose coords) set e.g.
    # DF_ICI_COORDS=0,1,2 — malformed values degrade to None (a typo must
    # not kill daemon startup), and the injected value takes precedence
    # over jax detection below
    coords_env = os.environ.get("DF_ICI_COORDS", "")
    if coords_env:
        try:
            coords = tuple(int(x) for x in coords_env.split(","))
        except ValueError:
            coords = None
    num_chips = 0
    status, payload = probe_jax_devices()
    if status == "timeout":
        log.warning("accelerator runtime did not answer the topology probe;"
                    " running topology-less (device sink unavailable)")
    elif status == "ok":
        num_chips, first, total = payload
        if first is not None:
            if coords is None:   # explicit injection wins over detection
                coords = tuple(getattr(first, "coords", ()) or ()) or None
            if not slice_name:
                slice_name = f"{getattr(first, 'device_kind', 'tpu')}-{total}"
            if worker < 0:
                worker = getattr(first, "process_index", 0)
    # status == "error": jax absent/misconfigured — silent, like always
    if not zone:
        zone = os.environ.get("DF_DEFAULT_ZONE", "local")
    return TopologyInfo(slice_name=slice_name, worker_index=worker,
                        ici_coords=coords, num_chips=num_chips, zone=zone,
                        pod=pod)


def hostname_ip() -> tuple[str, str]:
    hostname = socket.gethostname()
    try:
        ip = socket.gethostbyname(hostname)
    except OSError:
        ip = "127.0.0.1"
    return hostname, ip


def pod_id(t: TopologyInfo | None) -> str:
    """The host's pod identity: the ICI bandwidth domain it belongs to.

    An explicit ``pod`` (``DF_POD_ID``, deployments that group hosts
    across slice boundaries) wins; otherwise the pod is derived from
    slice identity — one slice == one ICI domain == one pod. "" means no
    pod identity at all (the plain-DCN-peer fallback ``detect()``
    degrades to on non-TPU hosts): such a host belongs to no pod and the
    federation plane never restricts it. Stable across re-announce by
    construction — a pure function of the announced coordinates, never
    of announce order or time."""
    if t is None:
        return ""
    return t.pod or t.slice_name


def same_pod(a: TopologyInfo | None, b: TopologyInfo | None) -> bool:
    pa, pb = pod_id(a), pod_id(b)
    return bool(pa) and pa == pb


def link_type(a: TopologyInfo | None, b: TopologyInfo | None,
              *, same_host: bool = False) -> LinkType:
    """Classify the best link between two hosts' positions."""
    if same_host:
        return LinkType.LOCAL
    if a is None or b is None:
        return LinkType.WAN
    if a.slice_name and a.slice_name == b.slice_name:
        return LinkType.ICI
    if a.zone and a.zone == b.zone:
        return LinkType.DCN
    return LinkType.WAN


class LinkClass:
    """One classified (child, parent) pair: the link tier plus the pod/
    DCN coordinates the federation plane routes by. ``dcn_hops`` is the
    DCN distance between the two PODS: 0 = same pod (bytes stay on the
    wired ICI mesh), 1 = pod-crossing inside one zone (the DCN tier
    cross-pod federation exists to ration), 2 = cross-zone / unknown
    (WAN). ``ici`` is the chip-mesh Manhattan distance, meaningful only
    when ``link`` is ICI."""

    __slots__ = ("link", "same_pod", "dcn_hops", "ici")

    def __init__(self, link: LinkType, same_pod_: bool, dcn_hops: int,
                 ici: int):
        self.link = link
        self.same_pod = same_pod_
        self.dcn_hops = dcn_hops
        self.ici = ici


def classify(a: TopologyInfo | None, b: TopologyInfo | None,
             *, same_host: bool = False) -> LinkClass:
    """``link_type`` plus the pod tier: where the bytes would flow AND
    whether they would leave the pod. A host with no topology at all
    classifies as a plain WAN peer with no pod (the ``detect()``
    fallback) — cross-pod routing never restricts it, it just scores
    like the distant peer it is."""
    lt = link_type(a, b, same_host=same_host)
    sp = same_host or same_pod(a, b)
    if sp:
        dcn = 0
    elif lt in (LinkType.LOCAL, LinkType.ICI, LinkType.DCN):
        dcn = 1
    else:
        dcn = 2
    hops = ici_hops(a, b) if a is not None and b is not None else 1 << 16
    return LinkClass(lt, sp, dcn, hops)


def ici_hops(a: TopologyInfo, b: TopologyInfo) -> int:
    """Manhattan distance in the chip mesh; large when unknown.

    On a v5p torus each hop adds latency but per-hop bandwidth stays high;
    the evaluator uses this only to break ties between same-slice parents.
    """
    if not a.ici_coords or not b.ici_coords or len(a.ici_coords) != len(b.ici_coords):
        return 1 << 16
    return int(sum(abs(int(x) - int(y)) for x, y in zip(a.ici_coords, b.ici_coords)))


# relative bandwidth expectations per link class, used by evaluator scoring:
# ICI on v5p is ~4.8 TB/s/chip-neighborhood vs ~100-400 Gbps DCN NICs.
LINK_BANDWIDTH_SCORE = {
    LinkType.LOCAL: 1.0,
    LinkType.ICI: 0.9,
    LinkType.DCN: 0.4,
    LinkType.WAN: 0.1,
}

# The pinned link-tier vocabulary: the name each LinkType rides the
# decision ledger under (candidate ``link_tier`` — docs/OBSERVABILITY.md
# decision-row schema). Pinned like EXCLUSION_REASONS: replaying
# federation fairness offline needs the tier strings stable across
# versions, and the ordering here (best to worst) must agree with
# LINK_BANDWIDTH_SCORE (descending) and the dispatcher's LINK_TIER
# (ascending) — unit-pinned in tests/test_federation.py.
LINK_TIER_NAMES = {
    LinkType.LOCAL: "local",
    LinkType.ICI: "ici",
    LinkType.DCN: "dcn",
    LinkType.WAN: "wan",
}
