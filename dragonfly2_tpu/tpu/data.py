"""Training-loop data prefetch: shard URLs -> device arrays, overlapped.

BASELINE config #4's user-facing surface: "dfstore streaming of
WebDataset/TFRecord shards from GCS -> peer HBM prefetch during JAX
training". The reference's GPU stacks hand this to a dataloader talking
to the local dfdaemon; here the training process EMBEDS the daemon (the
device arrays must land in the training process's runtime, so the last
hop cannot cross a process boundary). The daemon's asyncio loop runs in
a background thread; the (synchronous) training thread iterates::

    # background thread: asyncio.run(daemon_main()) started the Daemon
    # and published (daemon, loop)
    pf = ShardPrefetcher(daemon, shard_urls, depth=2, loop=daemon_loop)
    for arrays in pf:                       # training thread
        params = train_step(params, decode(arrays))

    # from async code co-located with the daemon, use the async form:
    async for arrays in ShardPrefetcher(daemon, urls).astream(): ...

While step i consumes shard i, shards i+1..i+depth ride the P2P mesh and
DMA into device memory on the HBM sink's transfer thread — the same
overlap the bench measures as ``train_step_slowdown_pct``. Each yielded
item is the shard's raw bytes as per-device uint8 arrays (the HBM sink's
result); decoding stays with the caller (WebDataset/TFRecord framing is
format-specific and cheap next to the transfer). For a single global
sharded ``jax.Array``, use ``tpu.hbm_sink.DeviceIngest`` with a
``sharding`` directly.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Iterable, Iterator

from ..idl.messages import DeviceSink, DownloadRequest, UrlMeta

log = logging.getLogger("df.tpu.data")


class ShardPrefetcher:
    """Iterate device-resident shards with ``depth`` fetches in flight.

    Sync-iterable by design: JAX training loops are synchronous Python.
    The daemon's asyncio loop must run in another thread (the normal
    embedded-daemon arrangement: ``asyncio.run(daemon_main())`` in a
    background thread, training in the main thread); pass that loop as
    ``loop``. Failed shards raise at the consuming step unless
    ``skip_failed`` (then they are logged and skipped — dataset loaders
    routinely tolerate a missing shard).
    """

    def __init__(self, daemon, urls: Iterable[str], *, depth: int = 2,
                 loop: asyncio.AbstractEventLoop | None = None,
                 url_meta: UrlMeta | None = None,
                 dtype: str = "uint8",
                 skip_failed: bool = False,
                 delete_after: bool = True):
        self.daemon = daemon
        self.urls = list(urls)
        self.depth = max(1, depth)
        self.loop = loop
        self.url_meta = url_meta
        self.dtype = dtype
        self.skip_failed = skip_failed
        # training data is streamed-through, not cached: drop each shard's
        # pieces once its device array is handed over, or a long epoch
        # accumulates the whole dataset on local disk
        self.delete_after = delete_after

    # -- async core ----------------------------------------------------

    SHARD_TIMEOUT_S = 600.0

    async def _ingest_from_storage(self, task_id: str):
        """Device leg for content already on disk: the task fast path
        (completed-task reuse, e.g. epoch >= 2 with ``delete_after=False``)
        returns no conductor/sink, so feed the stored pieces through a
        fresh DeviceIngest."""
        store = self.daemon.ptm.storage_mgr.find_completed_task(task_id)
        if store is None:
            return None
        factory = self.daemon.device_sink_builder(
            DeviceSink(enabled=True, dtype=self.dtype))
        ingest = factory(store.md.content_length)

        def feed():
            for p in store.piece_infos():
                ingest.write(p.start, store.read_piece(p.num))
            return ingest.result(timeout=self.SHARD_TIMEOUT_S)

        return await asyncio.to_thread(feed)

    async def _fetch(self, url: str):
        """One shard through the real daemon path; returns the device
        array(s) (the HBM sink's result)."""
        sink = DeviceSink(enabled=True, dtype=self.dtype)
        task_id = None
        try:
            async for resp in self.daemon.ptm.start_file_task(
                    DownloadRequest(url=url, url_meta=self.url_meta,
                                    device_sink=sink,
                                    timeout_s=self.SHARD_TIMEOUT_S)):
                task_id = resp.task_id or task_id
            conductor = self.daemon.ptm.conductor(task_id) if task_id \
                else None
            ingest = conductor.device_ingest if conductor is not None \
                else None
            if ingest is not None:
                arrays = await asyncio.to_thread(
                    ingest.result, self.SHARD_TIMEOUT_S)
                # the sink is consumed (arrays may be donated into the
                # train step): a later epoch's reuse must rebuild from
                # storage, never re-read this one
                conductor.device_ingest = None
            else:
                arrays = await self._ingest_from_storage(task_id) \
                    if task_id else None
                if arrays is None:
                    raise RuntimeError(
                        f"shard {url}: no device ingest (wedged runtime, "
                        "or content length unknown)")
            return arrays
        finally:
            # streamed-through on EVERY path: a failed shard's partial
            # pieces must not accumulate either
            if self.delete_after and task_id is not None:
                await self.daemon.ptm.delete_task(task_id)

    async def astream(self):
        """Async iterator over device arrays, ``depth`` shards in flight,
        strictly in input order. Duplicate URLs (sampling with
        replacement) are serialized: concurrent fetches of one URL would
        share a conductor and harvest the same consumed (donated) sink."""
        pending: list[asyncio.Task] = []
        last_for_url: dict[str, asyncio.Task] = {}
        idx = 0

        def spawn(url: str) -> asyncio.Task:
            prev = last_for_url.get(url)

            async def run():
                if prev is not None and not prev.done():
                    await asyncio.wait({prev})
                return await self._fetch(url)

            t = asyncio.create_task(run())
            last_for_url[url] = t
            return t

        try:
            while pending or idx < len(self.urls):
                while idx < len(self.urls) and len(pending) < self.depth:
                    pending.append(spawn(self.urls[idx]))
                    idx += 1
                head = pending.pop(0)
                try:
                    yield await head
                except Exception:
                    if not self.skip_failed:
                        raise
                    log.warning("skipping failed shard", exc_info=True)
        finally:
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)

    # -- sync facade for training loops --------------------------------

    def __iter__(self) -> Iterator:
        loop = self.loop
        if loop is None:
            raise RuntimeError(
                "sync iteration needs the daemon's event loop (pass "
                "loop=...); from async code use astream()")
        done = object()
        q: asyncio.Queue | None = None

        async def _pump() -> None:
            try:
                async for arrays in self.astream():
                    await q.put(arrays)
                await q.put(done)
            except asyncio.CancelledError:
                raise          # early consumer exit: unwind astream's finally
            except BaseException as exc:  # noqa: BLE001 - relayed to consumer
                # never BLOCK delivering the error (the full-queue await
                # deadlocked a cancelled pump): displacing the undelivered
                # item is fine — the error ends the iteration anyway
                while True:
                    try:
                        q.put_nowait(exc)
                        return
                    except asyncio.QueueFull:
                        try:
                            q.get_nowait()
                        except asyncio.QueueEmpty:
                            pass

        async def _start() -> "asyncio.Task":
            nonlocal q
            # queue created BEFORE the pump task exists: the consumer's
            # first q.get() must never race a not-yet-created queue
            q = asyncio.Queue(maxsize=1)
            return asyncio.get_running_loop().create_task(_pump())

        import concurrent.futures
        fut = asyncio.run_coroutine_threadsafe(_start(), loop)
        pump_task = fut.result(timeout=30)
        try:
            while True:
                get_fut = asyncio.run_coroutine_threadsafe(q.get(), loop)
                while True:
                    try:
                        # bounded waits on ONE outstanding future (a
                        # cancel-on-timeout could race an already-popped
                        # item into the void): if the daemon loop dies
                        # mid-iteration the training thread must error,
                        # not hang forever
                        item = get_fut.result(timeout=5.0)
                        break
                    except concurrent.futures.TimeoutError:
                        if loop.is_closed() or not loop.is_running():
                            raise RuntimeError(
                                "daemon event loop stopped during shard "
                                "iteration") from None
                if item is done:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            if not loop.is_closed():
                loop.call_soon_threadsafe(pump_task.cancel)
