"""TPU-native layer: pod topology detection, mesh helpers, and the HBM sink
that lands verified pieces in device memory."""
