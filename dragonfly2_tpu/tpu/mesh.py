"""Device-mesh helpers: named-axis meshes over local or pod devices.

The fabric uses meshes in two places: the HBM sink shards downloaded content
across a mesh axis, and the trainer pjit-shards its training step. Axis
conventions: ``data`` (batch / file-shard parallel), ``model`` (tensor
parallel within the predictor).
"""

from __future__ import annotations

import numpy as np


def make_mesh(axis_sizes: dict[str, int] | None = None, *, devices=None):
    """A ``jax.sharding.Mesh`` with named axes.

    Without ``axis_sizes``, all devices go on one ``data`` axis. Sizes must
    multiply to the device count (use -1 for one inferred axis).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"data": n}
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if known <= 0 or n % known:
            raise ValueError(f"cannot infer axis size: {n} devices over {sizes}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"axis sizes {dict(zip(names, sizes))} != {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def named_sharding(mesh, *axes: str | None):
    """``NamedSharding`` over ``mesh`` with a PartitionSpec of ``axes``."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*axes))
