"""PeerTaskManager: deduplicates conductors per task, serves file/stream
façades, and the reuse fast path.

Role parity: reference ``client/daemon/peer/peertask_manager.go`` +
``peertask_file.go`` / ``peertask_stream.go`` / ``peertask_reuse.go``.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import replace
from typing import Any, AsyncIterator

from ..common import ids
from ..common.errors import Code, DFError
from ..common.piece import Range, parse_http_range
from ..idl.messages import (DownloadRequest, DownloadResponse, TaskStat,
                            TaskType, UrlMeta)
from ..storage.manager import StorageManager
from .conductor import PeerTaskConductor
from .piece_manager import PieceManager

log = logging.getLogger("df.core.peertask")


class PeerTaskManager:
    def __init__(self, *, storage_mgr: StorageManager, piece_mgr: PieceManager,
                 hostname: str, host_ip: str, scheduler: Any = None,
                 p2p_engine_factory: Any = None,
                 device_sink_builder: Any = None, is_seed: bool = False,
                 shaper: Any = None, prefetch_whole_file: bool = False,
                 flight_recorder: Any = None, pex: Any = None,
                 relay: Any = None, qos: Any = None):
        self.storage_mgr = storage_mgr
        self.piece_mgr = piece_mgr
        self.hostname = hostname
        self.host_ip = host_ip
        self.scheduler = scheduler
        self.p2p_engine_factory = p2p_engine_factory
        self.device_sink_builder = device_sink_builder
        self.is_seed = is_seed
        self.shaper = shaper
        self.prefetch_whole_file = prefetch_whole_file
        self.flight_recorder = flight_recorder
        self.pex = pex
        self.relay = relay            # RelayHub (None = cut-through off)
        self.qos = qos                # QosGovernor (None = admission off)
        self._conductors: dict[str, PeerTaskConductor] = {}
        self._prefetching: set[str] = set()
        # strong refs: the loop only weak-refs tasks, and a GC'd prefetch
        # would strand its id in _prefetching forever
        self._prefetch_tasks: set[asyncio.Task] = set()
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------------

    def _task_id(self, url: str, meta: UrlMeta) -> str:
        return ids.task_id(
            url, tag=meta.tag, application=meta.application, digest=meta.digest,
            piece_range=meta.range,
            filtered_query_params=list(meta.filtered_query_params or []))

    async def get_or_create_conductor(
            self, url: str, meta: UrlMeta, *,
            task_type: TaskType = TaskType.STANDARD,
            disable_back_source: bool = False,
            device_sink_factory: Any = None,
            ordered: bool = False,
            shard_manifest: Any = None) -> PeerTaskConductor:
        task_id = self._task_id(url, meta)
        content_range: Range | None = None
        requested_shards = None
        if meta.shards:
            from ..common.sharding import parse_shard_names
            requested_shards = parse_shard_names(meta.shards) or None
        existing = await self._join_existing(
            task_id, ordered, requested_shards=requested_shards)
        if existing is not None:
            return existing
        # QoS admission happens OUTSIDE the manager lock: a bulk request
        # riding the brownout queue must never hold the lock critical
        # traffic needs to create ITS conductor (priority inversion by
        # lock). May raise RESOURCE_EXHAUSTED (+retry_after_ms) — the
        # 429-shaped shed the proxy/gateway/rpc surfaces forward.
        from ..idl.messages import resolve_class
        qos_cls = qos_ruling = None
        if self.qos is not None:
            qos_cls, qos_ruling = await self.qos.admit(
                resolve_class(meta.qos_class), meta.tenant)
        # the class stored on the flight is CLAMPED ("" stays classless):
        # it becomes a df_qos_slo_breach_total label via observe_summary,
        # and a raw wire string there would be unbounded client-
        # controlled metric cardinality
        flight_cls = resolve_class(meta.qos_class) if meta.qos_class \
            else ""
        async with self._lock:
            conductor = self._conductors.get(task_id)
            if (conductor is not None
                    and conductor.state != PeerTaskConductor.FAILED):
                # lost the creation race while queued at admission: the
                # winner's admission is the accounted one. A FINISHED or
                # finishing subset conductor that doesn't cover this
                # request falls through to a fresh conductor instead
                # (same task storage; only the gap transfers).
                gap = self._subset_gap(conductor, requested_shards)
                if not gap or (not conductor.done_event.is_set()
                               and conductor.widen_to_whole_file()):
                    if qos_cls is not None:
                        self.qos.release(qos_cls)
                    return conductor
            peer_id = ids.peer_id(self.hostname, self.host_ip,
                                  seed=self.is_seed)
            flight = (self.flight_recorder.begin(
                task_id, peer_id, url=url,
                qos_class=flight_cls, tenant=meta.tenant)
                if self.flight_recorder is not None else None)
            conductor = PeerTaskConductor(
                task_id=task_id, peer_id=peer_id,
                url=url, url_meta=meta, storage_mgr=self.storage_mgr,
                piece_mgr=self.piece_mgr, scheduler=self.scheduler,
                content_range=content_range,
                disable_back_source=disable_back_source, task_type=task_type,
                device_sink_factory=device_sink_factory, ordered=ordered,
                flight=flight, pex=self.pex, relay=self.relay,
                shard_manifest=shard_manifest,
                requested_shards=requested_shards)
            if qos_cls is not None:
                conductor.qos_release = (
                    lambda c=qos_cls: self.qos.release(c))
                if flight is not None:
                    # journal the admission ruling: a bulk task that rode
                    # the brownout queue carries the wait in its journal
                    from . import flight_recorder as fr
                    flight.event(fr.QOS, parent=(
                        "brownout" if qos_ruling == "queued"
                        else self.qos.state))
            if self.p2p_engine_factory is not None:
                conductor.set_p2p_engine(self.p2p_engine_factory())
            if self.shaper is not None:
                conductor.attach_shaper(self.shaper)
            self._conductors[task_id] = conductor
            conductor.start()
            return conductor

    async def _join_existing(self, task_id: str, ordered: bool,
                             requested_shards: list[str] | None = None,
                             ) -> PeerTaskConductor | None:
        """Join a live conductor for this task if one exists (subscribers
        share one download — joining costs no QoS admission; the original
        admission already accounts the work)."""
        async with self._lock:
            conductor = self._conductors.get(task_id)
            if conductor is None \
                    or conductor.state == PeerTaskConductor.FAILED:
                return None
            if ordered and not conductor.ordered:
                # a stream consumer joined a running file task: switch to
                # in-order fetching so read_ordered() doesn't stall
                conductor.ordered = True
                engine = conductor._p2p_engine
                if engine is not None:
                    engine.dispatcher.ordered = True
            if self._subset_gap(conductor, requested_shards):
                # the joiner needs shards (or the whole file) the live
                # subset download would never fetch: widen to the full
                # piece set so its done_event covers both. A FINISHED
                # (or finishing — widen refuses) subset download can't
                # grow: a fresh conductor over the same task storage
                # adopts its pieces (place_from_store) and fetches only
                # the gap.
                if (conductor.done_event.is_set()
                        or not conductor.widen_to_whole_file()):
                    return None
            return conductor

    @staticmethod
    def _subset_gap(conductor: PeerTaskConductor,
                    requested_shards: list[str] | None) -> bool:
        """True when ``conductor`` is a requested-subset download that
        does NOT cover this request's needs (other shards, or the whole
        file)."""
        if conductor.requested_shards is None:
            return False
        if requested_shards is None:
            return True
        return bool(set(requested_shards)
                    - set(conductor.requested_shards))

    def conductor(self, task_id: str) -> PeerTaskConductor | None:
        return self._conductors.get(task_id)

    def _start_prefetch(self, url: str, meta: UrlMeta) -> None:
        """Fire-and-forget whole-file download backing a ranged request."""
        whole = replace(meta, range="")
        task_id = self._task_id(url, whole)
        if (task_id in self._prefetching
                or self.storage_mgr.find_completed_task(task_id) is not None):
            return
        self._prefetching.add(task_id)

        async def run() -> None:
            try:
                conductor = await self.get_or_create_conductor(url, whole)
                await conductor.wait_done()
            except Exception:  # noqa: BLE001 - prefetch is best-effort
                log.exception("whole-file prefetch of %s failed", url)
            finally:
                self._prefetching.discard(task_id)

        t = asyncio.get_running_loop().create_task(run())
        self._prefetch_tasks.add(t)
        t.add_done_callback(self._prefetch_tasks.discard)

    # ------------------------------------------------------------------
    # file task: download -> progress events -> land at output path
    # ------------------------------------------------------------------

    async def start_file_task(
            self, req: DownloadRequest) -> AsyncIterator[DownloadResponse]:
        meta = req.url_meta or UrlMeta()
        task_id = self._task_id(req.url, meta)

        # reuse fast path: completed task (or a whole-file parent covering a
        # ranged request) already on disk
        reuse = self.storage_mgr.find_completed_task(task_id)
        rng: Range | None = None
        if meta.range and reuse is None:
            # ranged request: serve from the whole-file parent when present
            parent_id = ids.parent_task_id(
                req.url, tag=meta.tag, application=meta.application,
                digest=meta.digest,
                filtered_query_params=list(meta.filtered_query_params or []))
            parent = self.storage_mgr.get(parent_id)
            parent_done = (parent is not None
                           and getattr(parent.md, "done", False)
                           and parent.md.content_length >= 0)
            if self.prefetch_whole_file and not parent_done:
                # warm the whole file in the background so later ranged
                # requests are local subtask reads (reference
                # ``client/daemon/peer/peertask_manager.go:262-287``)
                self._start_prefetch(req.url, meta)
            if parent_done:
                total = parent.md.content_length
                try:
                    rng = parse_http_range(meta.range, total)
                except ValueError as exc:
                    raise DFError(Code.INVALID_ARGUMENT, str(exc)) from None
                reuse = self.storage_mgr.find_partial_completed_task(
                    parent_id, rng.start, rng.length)
                if reuse is None:
                    rng = None
        if reuse is not None:
            if req.output:
                await asyncio.to_thread(
                    reuse.store_to, req.output,
                    **({"range_start": rng.start, "range_length": rng.length}
                       if rng else {}))
            length = rng.length if rng else reuse.md.content_length
            yield DownloadResponse(task_id=task_id, peer_id="reused",
                                   completed_length=length,
                                   content_length=length, done=True,
                                   output=req.output)
            return

        device_factory = None
        if req.device_sink is not None and req.device_sink.enabled \
                and self.device_sink_builder is not None:
            device_factory = self.device_sink_builder(req.device_sink)

        conductor = await self.get_or_create_conductor(
            req.url, meta, task_type=req.task_type,
            disable_back_source=req.disable_back_source,
            device_sink_factory=device_factory,
            shard_manifest=req.shard_manifest)
        q = conductor.subscribe()
        try:
            while True:
                timeout = req.timeout_s if req.timeout_s > 0 else None
                try:
                    event = await asyncio.wait_for(q.get(), timeout)
                except asyncio.TimeoutError:
                    raise DFError(Code.DEADLINE_EXCEEDED,
                                  f"download timed out after {req.timeout_s}s") from None
                if event["type"] == "piece":
                    yield DownloadResponse(
                        task_id=conductor.task_id, peer_id=conductor.peer_id,
                        completed_length=event["completed"],
                        content_length=event["total"])
                elif event["type"] == "shard":
                    # sharded tasks: one progress frame per shard that
                    # became ready (all bytes verified) — dfget prints
                    # the per-shard ready timestamps off these
                    yield DownloadResponse(
                        task_id=conductor.task_id, peer_id=conductor.peer_id,
                        completed_length=conductor.completed_length,
                        content_length=conductor.content_length,
                        shard=event["name"], shard_src=event["src"],
                        shards_ready=event["ready"],
                        shards_total=event["total"])
                elif event["type"] == "done":
                    if not event.get("success"):
                        raise DFError(Code(event.get("code") or Code.UNKNOWN),
                                      event.get("message", "download failed"))
                    if req.output:
                        assert conductor.storage is not None
                        await asyncio.to_thread(conductor.storage.store_to,
                                                req.output)
                    yield DownloadResponse(
                        task_id=conductor.task_id, peer_id=conductor.peer_id,
                        completed_length=conductor.completed_length,
                        content_length=conductor.content_length,
                        done=True, output=req.output)
                    return
        finally:
            conductor.unsubscribe(q)

    # ------------------------------------------------------------------
    # stream task: ordered bytes (proxy / gateway / dfget stdout)
    # ------------------------------------------------------------------

    async def stream_task(self, url: str, meta: UrlMeta | None = None,
                          ) -> tuple[str, AsyncIterator[bytes]]:
        meta = meta or UrlMeta()
        task_id = self._task_id(url, meta)
        reuse = self.storage_mgr.find_completed_task(task_id)
        if reuse is not None:
            async def replay() -> AsyncIterator[bytes]:
                for p in reuse.piece_infos():
                    yield await asyncio.to_thread(reuse.read_piece, p.num)
            return task_id, replay()
        conductor = await self.get_or_create_conductor(url, meta, ordered=True)
        return task_id, conductor.read_ordered()

    # ------------------------------------------------------------------
    # cache ops (dfcache surface)
    # ------------------------------------------------------------------

    async def stat_task(self, task_id: str, *, local_only: bool = True) -> TaskStat:
        ts = self.storage_mgr.get(task_id)
        if ts is None:
            conductor = self._conductors.get(task_id)
            if conductor is None:
                raise DFError(Code.NOT_FOUND, f"task {task_id[:12]} not found")
            return TaskStat(id=task_id, state=conductor.state,
                            content_length=conductor.content_length,
                            total_piece_count=conductor.total_pieces)
        md = ts.md
        return TaskStat(id=task_id, type=md.task_type,
                        content_length=md.content_length,
                        total_piece_count=md.total_piece_count,
                        state="success" if md.success else
                              ("done" if md.done else "running"),
                        has_available_peer=md.done and md.success)

    async def import_file(self, path: str, url: str, meta: UrlMeta | None = None,
                          task_type: TaskType = TaskType.PERSISTENT) -> str:
        meta = meta or UrlMeta()
        task_id = self._task_id(url, meta)
        if self.storage_mgr.find_completed_task(task_id) is not None:
            return task_id
        conductor = PeerTaskConductor(
            task_id=task_id,
            peer_id=ids.peer_id(self.hostname, self.host_ip, seed=self.is_seed),
            url=url, url_meta=meta, storage_mgr=self.storage_mgr,
            piece_mgr=self.piece_mgr, scheduler=None, task_type=task_type)
        self._conductors[task_id] = conductor

        async def run_import():
            try:
                await self.piece_mgr.import_file(conductor, path)
                await conductor._finish_success()
            except DFError as exc:
                await conductor._finish_fail(exc.code, exc.message)
            except Exception as exc:  # noqa: BLE001
                await conductor._finish_fail(Code.UNKNOWN, str(exc))

        # retain + drain (DF002): a fire-and-forget import task is only
        # weakly referenced by the loop — GC could kill it mid-import and
        # wait_done() below would park forever on a conductor nobody is
        # feeding
        import_task = asyncio.get_running_loop().create_task(run_import())
        try:
            ok = await conductor.wait_done()
        except BaseException:
            # caller gone/cancelled: reap the import without letting its
            # CancelledError mask what we're already raising (run_import
            # catches everything else internally)
            import_task.cancel()
            try:
                await import_task
            except asyncio.CancelledError:
                pass
            raise
        try:
            # normal path: wait_done() returns at done_event.set(), but
            # _finish_* may still owe a _piece_cond notify_all — let it
            # run to completion rather than cancelling it mid-finish and
            # stranding piece waiters until their timeouts
            await import_task
        except asyncio.CancelledError:
            import_task.cancel()
            try:
                await import_task
            except asyncio.CancelledError:
                pass
            raise
        if not ok:
            raise DFError(conductor.fail_code, conductor.fail_message)
        return task_id

    async def export_file(self, url: str, output: str,
                          meta: UrlMeta | None = None, *,
                          local_only: bool = False, timeout_s: float = 0.0) -> str:
        meta = meta or UrlMeta()
        task_id = self._task_id(url, meta)
        ts = self.storage_mgr.find_completed_task(task_id)
        if ts is not None:
            await asyncio.to_thread(ts.store_to, output)
            return task_id
        if local_only:
            raise DFError(Code.NOT_FOUND, "task not cached locally")
        req = DownloadRequest(url=url, output=output, url_meta=meta,
                              timeout_s=timeout_s)
        async for _ in self.start_file_task(req):
            pass
        return task_id

    async def delete_task(self, task_id: str) -> bool:
        conductor = self._conductors.pop(task_id, None)
        if conductor is not None and not conductor.done_event.is_set():
            conductor.cancel()
        return self.storage_mgr.delete_task(task_id)

    async def shutdown(self) -> None:
        for conductor in list(self._conductors.values()):
            if not conductor.done_event.is_set():
                conductor.cancel()
